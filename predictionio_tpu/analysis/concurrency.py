"""The concurrency rule family behind ``ptpu check``.

PRs 2–4 made the serving path heavily threaded (micro-batcher, cache
tiers + invalidation bus, candidate-binding promote swap, rollout
verdict loop, hot-tier refresh), and the riskiest bug class in such a
system is a cross-thread state race or an acquisition-order deadlock —
invisible to both ``ruff`` and the JAX rules. Four rules make lock
discipline statically checkable:

- ``unguarded-shared-state`` — per class, infer the lock-guarded
  attribute set (any ``self._x`` written under ``with self._lock`` in
  some method) and flag reads/writes of those attributes outside the
  lock. The ``# ptpu: guarded-by[lock]`` annotation is the escape
  hatch AND the contract language: on an ``__init__`` assignment it
  declares the attribute guarded; on a ``def`` line it asserts every
  caller holds the lock (the whole body is then treated as locked); on
  an access line it blesses that one access (caller holds the lock, or
  a justified benign racy read of an atomically-swapped reference).
- ``lock-order-inversion`` — project-scoped: build the static
  acquisition graph from nested ``with``-lock scopes across every
  scanned file and report cycles. Lock identity is ``Class.attr`` for
  ``self``/``cls`` locks (conservative: instances of one class merge)
  and ``module.name`` for globals.
- ``blocking-under-lock`` — device dispatch (``jax.*``),
  ``block_until_ready``, HTTP/socket I/O, storage access, ``sleep``,
  zero-arg ``.join()``, ``.wait()``/``.result()`` inside a held-lock
  region in ``server/``, ``cache/``, or ``rollout/``. A lock held
  across a blocking call serializes every other thread on that I/O —
  and held across a device dispatch it caps throughput at one
  round-trip per lock.
- ``callback-under-lock`` — invoking a dynamic callable (subscriber,
  plugin hook, loop-variable function) or a publish/notify-style
  method while holding a lock: the callee can re-enter the publisher
  and deadlock, and the bus pattern (snapshot under lock, call
  outside) exists precisely to prevent it.

All four honor ``# ptpu: allow[rule] — justification`` pragmas. The
runtime complement lives in :mod:`predictionio_tpu.concurrency`
(DebugLock order graph, watchdog, ``pio_lock_*`` metrics).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .core import (
    CheckContext,
    Finding,
    ModuleInfo,
    chain_related,
    chain_text,
    short_name,
    strongly_connected,
)

#: what makes a name "a lock" for the with-scope rules
LOCK_NAME_RE = re.compile(r"lock|mutex", re.IGNORECASE)

#: constructors whose result is a mutex, regardless of attribute name
LOCK_FACTORY_SUFFIXES = {"Lock", "RLock", "Condition",
                         "new_lock", "new_rlock"}

#: directories whose lock regions must not block (the serving stack)
SERVING_DIR_PARTS = {"server", "cache", "rollout"}

#: attribute-method names that suggest delivering to subscribers or
#: plugins — calling one with a lock held invites re-entrant deadlock
CALLBACK_ATTRS = {"publish", "process_output", "on_event", "notify",
                  "emit", "fire_event"}

#: blocking calls by resolved dotted name
BLOCKING_EXACT = {
    "time.sleep": "time.sleep blocks every thread queued on this lock",
    "jax.device_get": "jax.device_get is a synchronous device→host "
                      "transfer",
    "jax.block_until_ready": "blocks on device completion",
}
BLOCKING_PREFIXES = (
    ("jax.", "device work dispatched (and possibly compiled) with the "
             "lock held"),
    ("urllib.", "HTTP I/O under a lock serializes all waiters on the "
                "network"),
    ("requests.", "HTTP I/O under a lock serializes all waiters on "
                  "the network"),
    ("socket.", "socket I/O under a lock serializes all waiters on "
                "the network"),
    ("http.client", "HTTP I/O under a lock serializes all waiters on "
                    "the network"),
)
#: blocking method calls by attribute name
BLOCKING_METHOD_ATTRS = {
    "block_until_ready": "blocks on device completion",
    "urlopen": "HTTP I/O under a lock serializes all waiters on the "
               "network",
    "wait": "waiting on an event/condition while holding a lock is a "
            "classic lost-wakeup deadlock",
    "result": "blocking on a Future while holding a lock deadlocks if "
              "the producer needs the same lock",
}


def _in_serving_stack(path: str) -> bool:
    return bool(set(path.split("/")[:-1]) & SERVING_DIR_PARTS)


def _mod_stem(path: str) -> str:
    return os.path.basename(path)[:-3] if path.endswith(".py") \
        else os.path.basename(path)


def _is_lock_factory(mod: ModuleInfo, value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    name = mod.resolve(value.func)
    if not name:
        return False
    return name.split(".")[-1] in LOCK_FACTORY_SUFFIXES


def _self_attr(node: ast.AST) -> Optional[str]:
    """``X`` for ``self.X`` / ``cls.X``, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id in ("self", "cls"):
        return node.attr
    return None


# ---------------------------------------------------------------------------
# rule: unguarded-shared-state
# ---------------------------------------------------------------------------

class _Access:
    __slots__ = ("attr", "line", "col", "store", "held", "method")

    def __init__(self, attr: str, line: int, col: int, store: bool,
                 held: FrozenSet[str], method: str):
        self.attr = attr
        self.line = line
        self.col = col
        self.store = store
        self.held = held
        self.method = method


def _class_lock_attrs(mod: ModuleInfo, cls: ast.ClassDef) -> Set[str]:
    """Attributes of ``cls`` that hold mutexes: assigned from a lock
    factory (anywhere in the class) or lock-named."""
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is None and isinstance(t, ast.Name):
                    attr = t.id  # class-level `_lock = Lock()`
                if attr and (_is_lock_factory(mod, node.value)
                             or LOCK_NAME_RE.search(attr)):
                    locks.add(attr)
    return locks


def _walk_method_accesses(mod: ModuleInfo, method: ast.AST,
                          lock_attrs: Set[str]) -> List[_Access]:
    """Every ``self.X``/``cls.X`` access in ``method`` with the set of
    class locks syntactically held at that point. Entering a nested
    function resets the held set (deferred execution) except for locks
    the nested def's own ``guarded-by`` line asserts."""
    accesses: List[_Access] = []
    mname = getattr(method, "name", "<lambda>")

    def held_from_with(item: ast.withitem,
                       held: FrozenSet[str]) -> FrozenSet[str]:
        attr = _self_attr(item.context_expr)
        if attr and attr in lock_attrs:
            return held | {attr}
        return held

    def visit(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, ast.With):
            h = held
            for item in node.items:
                visit(item.context_expr, h)
                h = held_from_with(item, h)
                if item.optional_vars is not None:
                    visit(item.optional_vars, h)
            for stmt in node.body:
                visit(stmt, h)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not method:
            inner = frozenset(mod.guards_at(node.lineno) & lock_attrs)
            for child in ast.iter_child_nodes(node):
                visit(child, inner)
            return
        if isinstance(node, ast.Lambda):
            for child in ast.iter_child_nodes(node):
                visit(child, frozenset())
            return
        attr = _self_attr(node)
        if attr is not None:
            accesses.append(_Access(
                attr, node.lineno, node.col_offset,
                isinstance(node.ctx, (ast.Store, ast.Del)), held,
                mname))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    start = frozenset(mod.guards_at(method.lineno) & lock_attrs) \
        if isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)) \
        else frozenset()
    for child in ast.iter_child_nodes(method):
        visit(child, start)
    return accesses


def rule_unguarded_shared_state(mod: ModuleInfo,
                                ctx: CheckContext) -> List[Finding]:
    findings: List[Finding] = []
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        lock_attrs = {a for a in _class_lock_attrs(mod, cls)}
        if not lock_attrs:
            continue
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        per_method = {m.name: _walk_method_accesses(mod, m, lock_attrs)
                      for m in methods}
        # infer the guarded set: attr → locks it was written under
        guarded: Dict[str, Set[str]] = {}
        for m in methods:
            exempt = m.name in ("__init__", "__del__")
            for acc in per_method[m.name]:
                if acc.attr in lock_attrs:
                    continue
                if acc.store and acc.held and not exempt:
                    guarded.setdefault(acc.attr, set()).update(acc.held)
                if acc.store and exempt:
                    # declaration form: `self._x = 0  # ptpu:
                    # guarded-by[_lock]` in __init__
                    declared = mod.guards_at(acc.line) & lock_attrs
                    if declared:
                        guarded.setdefault(acc.attr,
                                           set()).update(declared)
        if not guarded:
            continue
        for m in methods:
            if m.name in ("__init__", "__del__"):
                continue
            for acc in per_method[m.name]:
                locks = guarded.get(acc.attr)
                if not locks or acc.held & locks:
                    continue
                asserted = mod.guards_at(acc.line)
                if asserted & locks or "*" in asserted:
                    continue
                verb = "written" if acc.store else "read"
                lock_list = "/".join(sorted(locks))
                findings.append(Finding(
                    "unguarded-shared-state", mod.path, acc.line,
                    acc.col,
                    f"`self.{acc.attr}` is {verb} in "
                    f"`{cls.name}.{m.name}` without holding "
                    f"`{lock_list}`, but other methods write it under "
                    f"that lock; take the lock, or annotate with "
                    f"'# ptpu: guarded-by[{sorted(locks)[0]}] — why' "
                    f"if the caller holds it"))
    return findings


# ---------------------------------------------------------------------------
# shared with-scope walker (lock-order / blocking / callback rules)
# ---------------------------------------------------------------------------

def lock_expr_name(mod: ModuleInfo, expr: ast.AST,
                   class_name: Optional[str]) -> Optional[str]:
    """Canonical cross-file name for a lock expression in a ``with``
    item, or None when the expression is not lock-like. Shared with
    the interprocedural acquires-locks summaries
    (:class:`~.core.ProjectIndex`), so call-through acquisition edges
    land on the same graph nodes as syntactic nesting."""
    attr = _self_attr(expr)
    if attr is not None:
        if LOCK_NAME_RE.search(attr):
            return f"{class_name or _mod_stem(mod.path)}.{attr}"
        return None
    if isinstance(expr, ast.Name) and LOCK_NAME_RE.search(expr.id):
        return f"{_mod_stem(mod.path)}.{expr.id}"
    if isinstance(expr, ast.Attribute) \
            and LOCK_NAME_RE.search(expr.attr):
        base = expr.value
        recv = base.id if isinstance(base, ast.Name) else "?"
        return f"{_mod_stem(mod.path)}:{recv}.{expr.attr}"
    return None


class _WithScopeWalker:
    """Walks one module, calling ``on_edge`` for every nested-lock
    acquisition edge and ``on_node`` for every AST node with the
    currently-held lock list. Held state resets at function
    boundaries (each call stack acquires from scratch; nested defs are
    deferred execution)."""

    def __init__(self, mod: ModuleInfo, on_edge=None, on_node=None):
        self.mod = mod
        self.on_edge = on_edge
        self.on_node = on_node

    def run(self) -> None:
        self._visit_block(self.mod.tree.body, [], None)

    def _visit_block(self, stmts, held: List[str],
                     class_name: Optional[str]) -> None:
        for stmt in stmts:
            self._visit(stmt, held, class_name)

    def _visit(self, node: ast.AST, held: List[str],
               class_name: Optional[str]) -> None:
        if isinstance(node, ast.ClassDef):
            self._visit_block(node.body, [], node.name)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            body = node.body if isinstance(node.body, list) \
                else [node.body]
            self._visit_block(body, [], class_name)
            return
        if isinstance(node, ast.With):
            h = list(held)
            for item in node.items:
                self._visit(item.context_expr, h, class_name)
                name = lock_expr_name(self.mod, item.context_expr,
                                      class_name)
                if name is not None:
                    if self.on_edge is not None:
                        for prior in h:
                            if prior != name:
                                self.on_edge(prior, name,
                                             item.context_expr)
                    h.append(name)
            self._visit_block(node.body, h, class_name)
            return
        if self.on_node is not None and held:
            self.on_node(node, held, class_name)
        for child in ast.iter_child_nodes(node):
            self._visit(child, held, class_name)


# ---------------------------------------------------------------------------
# rule: lock-order-inversion (project-scoped)
# ---------------------------------------------------------------------------

def rule_lock_order_inversion(mods: Sequence[ModuleInfo],
                              ctx: CheckContext) -> List[Finding]:
    """Cycles in the acquisition graph. Edges come from two sources:
    syntactic nesting (``with a:`` containing ``with b:``) and — via
    the interprocedural acquires-locks summaries — calls made while a
    lock is held into functions that (transitively) acquire another
    lock: ``with a: self._refill()`` where ``_refill`` takes ``b`` is
    an a→b edge even though no ``with b:`` is lexically in sight."""
    edges: Dict[str, Set[str]] = {}
    sites: Dict[Tuple[str, str], Tuple[str, int, int]] = {}
    proj = ctx.project

    def add_edge(src: str, dst: str, path: str, line: int,
                 col: int) -> None:
        if src == dst:
            return
        edges.setdefault(src, set()).add(dst)
        sites.setdefault((src, dst), (path, line, col))

    for mod in mods:
        def on_edge(src: str, dst: str, expr: ast.AST,
                    _mod: ModuleInfo = mod) -> None:
            add_edge(src, dst, _mod.path, expr.lineno, expr.col_offset)

        def on_node(node: ast.AST, held: List[str],
                    class_name: Optional[str],
                    _mod: ModuleInfo = mod) -> None:
            if proj is None or not isinstance(node, ast.Call):
                return
            qname, _ = proj.resolve_call(_mod, class_name, node.func)
            callee = proj.functions.get(qname or "")
            if callee is None:
                return
            for acq in callee.acquires:
                for prior in held:
                    add_edge(prior, acq, _mod.path, node.lineno,
                             node.col_offset)

        _WithScopeWalker(mod, on_edge=on_edge, on_node=on_node).run()

    nodes = set(edges) | {d for ds in edges.values() for d in ds}
    findings: List[Finding] = []
    for scc in strongly_connected(nodes, edges):
        if len(scc) < 2:
            continue
        internal = sorted(
            ((src, dst) for src in scc
             for dst in edges.get(src, ()) if dst in scc))
        edge_desc = "; ".join(
            f"{src} → {dst} at "
            f"{sites[(src, dst)][0]}:{sites[(src, dst)][1]}"
            for src, dst in internal)
        anchor = min(sites[e] for e in internal)
        findings.append(Finding(
            "lock-order-inversion", anchor[0], anchor[1], anchor[2],
            f"cyclic lock acquisition order between "
            f"{', '.join(sorted(scc))}: {edge_desc} — two threads "
            f"interleaving these paths deadlock; pick one global "
            f"order (or merge the critical sections)"))
    return findings


# ---------------------------------------------------------------------------
# rule: blocking-under-lock
# ---------------------------------------------------------------------------

def _storage_chain(resolved: Optional[str]) -> bool:
    if not resolved:
        return False
    # a Capitalized tail is a class constructor (data.storage.Model),
    # not an I/O call — building the record doesn't touch the backend
    if resolved.split(".")[-1][:1].isupper():
        return False
    return any(seg in ("storage", "_storage")
               for seg in resolved.split("."))


def blocking_reason(mod: ModuleInfo, node: ast.Call) -> Optional[str]:
    """Why this call blocks, or None — the shared predicate behind the
    direct rule and the interprocedural blocks summaries
    (:class:`~.core.ProjectIndex`)."""
    resolved = mod.resolve(node.func)
    if resolved in BLOCKING_EXACT:
        return BLOCKING_EXACT[resolved]
    if resolved:
        for prefix, reason in BLOCKING_PREFIXES:
            if resolved.startswith(prefix):
                return reason
        if _storage_chain(resolved):
            return ("storage/event-store I/O under a lock serializes "
                    "every waiter on the backend")
    if isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        if attr in ("block_until_ready", "urlopen") \
                or (attr == "join" and not node.args
                    and not node.keywords) \
                or attr in ("wait", "result"):
            return BLOCKING_METHOD_ATTRS.get(
                attr, "blocking call while a lock is held")
    return None


def rule_blocking_under_lock(mod: ModuleInfo,
                             ctx: CheckContext) -> List[Finding]:
    if not _in_serving_stack(mod.path):
        return []
    findings: List[Finding] = []
    seen: Set[int] = set()
    proj = ctx.project

    def on_node(node: ast.AST, held: List[str],
                class_name: Optional[str]) -> None:
        if not isinstance(node, ast.Call) or id(node) in seen:
            return
        seen.add(id(node))
        why = blocking_reason(mod, node)
        if why is not None:
            findings.append(Finding(
                "blocking-under-lock", mod.path, node.lineno,
                node.col_offset,
                f"blocking call while holding {'/'.join(held)}: {why}; "
                f"snapshot state under the lock and do the slow work "
                f"outside it"))
            return
        # interprocedural: the blocking call hides inside a helper —
        # report the held-lock call site with the chain to the direct
        # blocking site
        if proj is None:
            return
        qname, _ = proj.resolve_call(mod, class_name, node.func)
        callee = proj.functions.get(qname or "")
        if callee is None or callee.effects["blocking"] is None:
            return
        hops = proj.chain(callee, "blocking")
        if not hops:
            return
        findings.append(Finding(
            "blocking-under-lock", mod.path, node.lineno,
            node.col_offset,
            f"calling `{short_name(callee.qname)}` while holding "
            f"{'/'.join(held)} transitively blocks: "
            f"{chain_text(hops)}; snapshot state under the lock and "
            f"do the slow work outside it (or pragma the helper's "
            f"blocking site if it is the blessed shape)",
            related=chain_related(hops)))

    _WithScopeWalker(mod, on_node=on_node).run()
    return findings


# ---------------------------------------------------------------------------
# rule: callback-under-lock
# ---------------------------------------------------------------------------

def _function_scopes(tree: ast.Module):
    """Top-level-ish functions (module funcs + class methods), each
    with its dynamically-bound local names: parameters, loop targets,
    and plain assignments — excluding nested ``def``/lambda bindings
    (those bodies are statically known, not foreign callbacks)."""
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        static_names: Set[str] = set()
        dynamic: Set[str] = set()
        a = node.args
        for p in (*a.posonlyargs, *a.args, *a.kwonlyargs):
            dynamic.add(p.arg)
        for sub in ast.walk(node):
            if sub is node:
                continue
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                static_names.add(sub.name)
            elif isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        if isinstance(sub.value, ast.Lambda):
                            static_names.add(t.id)
                        else:
                            dynamic.add(t.id)
            elif isinstance(sub, (ast.For, ast.AsyncFor)):
                for n in ast.walk(sub.target):
                    if isinstance(n, ast.Name):
                        dynamic.add(n.id)
            elif isinstance(sub, ast.withitem) \
                    and sub.optional_vars is not None:
                for n in ast.walk(sub.optional_vars):
                    if isinstance(n, ast.Name):
                        dynamic.add(n.id)
        yield node, dynamic - static_names


def rule_callback_under_lock(mod: ModuleInfo,
                             ctx: CheckContext) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[int] = set()

    proj = ctx.project
    owners: Dict[int, str] = {}
    for cls in ast.walk(mod.tree):
        if isinstance(cls, ast.ClassDef):
            for sub in cls.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    owners[id(sub)] = cls.name

    # walk per function scope so each scope's dynamically-bound names
    # are in force; _WithScopeWalker supplies the held-lock context
    for fn, dynamic in _function_scopes(mod.tree):

        def on_node(node: ast.AST, held: List[str],
                    class_name: Optional[str],
                    _dynamic: Set[str] = dynamic) -> None:
            if not isinstance(node, ast.Call) or id(node) in seen:
                return
            if isinstance(node.func, ast.Name) \
                    and node.func.id in _dynamic:
                seen.add(id(node))
                findings.append(Finding(
                    "callback-under-lock", mod.path, node.lineno,
                    node.col_offset,
                    f"`{node.func.id}(…)` invokes a dynamically-bound "
                    f"callable while holding {'/'.join(held)}; the "
                    f"callee can re-enter and deadlock — snapshot "
                    f"under the lock, call outside it (the "
                    f"invalidation-bus publish pattern)"))
                return
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in CALLBACK_ATTRS:
                seen.add(id(node))
                findings.append(Finding(
                    "callback-under-lock", mod.path, node.lineno,
                    node.col_offset,
                    f"`.{node.func.attr}(…)` delivers to subscribers/"
                    f"plugins while holding {'/'.join(held)}; a "
                    f"subscriber that takes the same lock (or "
                    f"publishes back) deadlocks — move the delivery "
                    f"outside the critical section"))
                return
            if proj is None:
                return
            # interprocedural: (a) the delivery hides inside a helper;
            # (b) a dynamically-bound callable is PASSED into a helper
            # that invokes its argument — either way the foreign code
            # runs with this lock held
            qname, bound = proj.resolve_call(mod, class_name,
                                             node.func)
            callee = proj.functions.get(qname or "")
            if callee is None:
                return
            if callee.effects["callback"] is not None:
                hops = proj.chain(callee, "callback")
                if hops:
                    seen.add(id(node))
                    findings.append(Finding(
                        "callback-under-lock", mod.path, node.lineno,
                        node.col_offset,
                        f"calling `{short_name(callee.qname)}` while "
                        f"holding {'/'.join(held)} transitively "
                        f"delivers to subscribers/plugins: "
                        f"{chain_text(hops)}; snapshot under the "
                        f"lock, deliver outside it",
                        related=chain_related(hops)))
                    return
            if not callee.call_sinks:
                return
            off = 1 if bound else 0
            for i, a in enumerate(node.args):
                pos = i + off
                passed_dynamic = (
                    (isinstance(a, ast.Name) and a.id in _dynamic)
                    or isinstance(a, ast.Lambda))
                if passed_dynamic and pos in callee.call_sinks:
                    seen.add(id(node))
                    hops = proj.sink_chain(callee, "call", pos)
                    findings.append(Finding(
                        "callback-under-lock", mod.path, node.lineno,
                        node.col_offset,
                        f"passing a dynamically-bound callable into "
                        f"`{short_name(callee.qname)}` while holding "
                        f"{'/'.join(held)} — the helper invokes it "
                        f"with the lock held: {chain_text(hops)}; "
                        f"snapshot under the lock, call outside it",
                        related=chain_related(hops)))
                    return

        walker = _WithScopeWalker(mod, on_node=on_node)
        # held state starts fresh inside fn (function boundaries reset
        # acquisition context); the owning class rides along so
        # self-method calls resolve in the project index
        walker._visit_block([fn], [], owners.get(id(fn)))
    return findings
