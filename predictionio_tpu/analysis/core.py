"""Core machinery of ``ptpu check`` — the JAX-aware static-analysis pass.

Pure-AST: this package never imports jax/numpy, so ``ptpu check`` runs in
milliseconds on a storage-only host and in CI without an accelerator.

Pieces:

- :class:`Finding` — one lint hit (rule, path, line, col, message),
  optionally carrying ``related`` call-chain locations (SARIF
  ``relatedLocations``).
- :class:`ModuleInfo` — a parsed file plus its import-alias table, so
  rules match *resolved* dotted names (``np.asarray`` and
  ``numpy.asarray`` are the same callee; ``from jax import jit`` is
  ``jax.jit``; relative imports resolve against the module's own
  package path).
- :class:`ProjectIndex` — the interprocedural layer: a project-wide
  symbol table and call graph over the parsed module set, with
  per-function effect summaries (performs-host-sync, blocks,
  delivers-callbacks, acquires-locks, uses-param-as-gather-index,
  invokes-param) propagated through calls with cycle handling, so a
  violation hidden one helper call away is reported at the hot-path
  call site with the call chain in the message.
- pragma suppression — ``# ptpu: allow[rule]`` on the finding line or
  the line directly above silences that rule there (``allow[*]``
  silences every rule). Justify the pragma in prose after the bracket.
  A pragma at an effect's *direct site* also stops the effect from
  propagating: blessing the one named D2H helper blesses its callers.
- :func:`run_check` — walk paths, parse once per file, run every rule,
  drop pragma'd findings, return the rest sorted. A file that fails to
  parse or decode becomes a per-file ``parse-error`` finding; a rule
  that crashes becomes a ``checker-error`` finding — one bad file or
  rule never kills the run.

The rule catalogue lives in :mod:`predictionio_tpu.analysis.rules`
(JAX + Pallas-kernel families) and
:mod:`predictionio_tpu.analysis.concurrency`;
``docs/static-analysis.md`` is the operator-facing reference.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: ``# ptpu: allow[rule-a,rule-b] — justification``; the marker may sit
#: anywhere inside a comment (pragmas usually end a justification
#: sentence), and the justification is free-form prose
PRAGMA_RE = re.compile(r"#.*?ptpu:\s*allow\[([^\]]*)\]")

#: ``# ptpu: guarded-by[lock] — justification``: the concurrency
#: contract annotation (see rule ``unguarded-shared-state``). On an
#: ``__init__`` attribute assignment it DECLARES the attribute
#: lock-guarded; on a ``def`` line it asserts every caller holds the
#: lock; on an access line it asserts that access is safe (caller
#: holds the lock, or a justified benign racy read).
GUARDED_RE = re.compile(r"#.*?ptpu:\s*guarded-by\[([^\]]*)\]")


@dataclass(frozen=True)
class Finding:
    """One checker hit, formatted ``path:line:col: rule: message``.

    ``related`` carries (path, line, note) hops of an interprocedural
    call chain — rendered as SARIF ``relatedLocations`` so a
    code-scanning UI can walk from the hot call site down to the
    offending helper."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    related: Tuple[Tuple[str, int, str], ...] = ()

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"


@dataclass
class CheckContext:
    """Cross-file facts rules need: the mesh axis names declared by
    ``parallel/mesh.py`` (for sharding-mismatch), the declared axis
    GROUPS — which axes coexist on one mesh, e.g. ``(data, model)``
    and ``(batch, model)`` — for the sharding-flow rules, and the
    interprocedural :class:`ProjectIndex` over the scanned module set
    (built once per run by the orchestrator)."""

    declared_axes: Set[str] = field(default_factory=set)
    declared_groups: Set[Tuple[str, ...]] = field(default_factory=set)
    project: Optional["ProjectIndex"] = None


def _module_parts(path: str) -> List[str]:
    """Dotted-name parts a file would import as: path components minus
    the ``.py`` suffix, with a package's ``__init__`` collapsing into
    the package name. Used for relative-import resolution and for the
    suffix-keyed function index (an absolute path's leading directories
    simply become extra — harmless — suffix prefixes)."""
    parts = [p for p in path.replace(os.sep, "/").split("/")
             if p not in ("", ".", "..")]
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return parts


class ModuleInfo:
    """A parsed module plus resolution helpers shared by every rule."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self.module_parts = _module_parts(self.path)
        self.aliases = _collect_aliases(
            tree, self.module_parts,
            is_init=self.path.endswith("__init__.py"))
        self.pragmas = _collect_pragmas(self.lines)
        self.guards = _collect_guards(self.lines)

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain with import aliases
        expanded (``np.asarray`` → ``numpy.asarray``), else None."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = self.aliases.get(node.id, node.id)
        parts.append(head)
        return ".".join(reversed(parts))

    def _covering_lines(self, line: int) -> List[int]:
        """``line`` itself plus the contiguous comment block directly
        above it — the lines whose markers cover a statement at
        ``line`` (a multi-line justification can carry the marker on
        any of its lines)."""
        candidates = [line]
        ln = line - 1
        while 1 <= ln <= len(self.lines) \
                and self.lines[ln - 1].strip().startswith("#"):
            candidates.append(ln)
            ln -= 1
        return candidates

    def suppressed(self, finding: Finding) -> bool:
        """A pragma suppresses a finding on its own line, or anywhere in
        the contiguous comment block directly above the finding line."""
        for ln in self._covering_lines(finding.line):
            allowed = self.pragmas.get(ln)
            if allowed and ("*" in allowed or finding.rule in allowed):
                return True
        return False

    def guards_at(self, line: int) -> Set[str]:
        """Lock names asserted by ``# ptpu: guarded-by[...]`` markers
        covering ``line`` (same placement rules as pragmas)."""
        out: Set[str] = set()
        for ln in self._covering_lines(line):
            out |= self.guards.get(ln, set())
        return out


def _collect_aliases(tree: ast.Module,
                     module_parts: Optional[Sequence[str]] = None,
                     is_init: bool = False) -> Dict[str, str]:
    """Local name → dotted origin, from every import in the module
    (function-local imports included — the hot packages import jnp
    inside functions to keep storage-only commands jax-free). Relative
    imports resolve against ``module_parts`` (the importing module's
    own dotted path), so ``from ..parallel.collectives import x`` in
    ``predictionio_tpu/models/als.py`` binds
    ``predictionio_tpu.parallel.collectives.x`` — the call-graph layer
    needs cross-module names to land on indexed functions."""
    aliases: Dict[str, str] = {}
    pkg = list(module_parts or [])
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0 and not node.module:
                continue
            if node.level == 0:
                base = node.module
            else:
                # `from .` in a/b/c.py is package a.b (module_parts
                # minus the module itself); in a/b/__init__.py the
                # collapsed parts a.b already ARE the `.` base. Each
                # extra dot climbs one level.
                head = pkg if is_init else pkg[:-1]
                up = node.level - 1
                head = head[:len(head) - up] if up else head
                if not head:
                    continue
                base = ".".join(head + ([node.module]
                                        if node.module else []))
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{base}.{a.name}"
    return aliases


def _collect_pragmas(lines: Sequence[str]) -> Dict[int, Set[str]]:
    pragmas: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, start=1):
        m = PRAGMA_RE.search(line)
        if m:
            pragmas[i] = {r.strip() for r in m.group(1).split(",")
                          if r.strip()}
    return pragmas


def _collect_guards(lines: Sequence[str]) -> Dict[int, Set[str]]:
    guards: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, start=1):
        m = GUARDED_RE.search(line)
        if m:
            guards[i] = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
    return guards


# ---------------------------------------------------------------------------
# mesh axis extraction (sharding-mismatch's ground truth)
# ---------------------------------------------------------------------------

def extract_mesh_axes(source: str) -> Set[str]:
    """Axis names a ``parallel/mesh.py`` declares: module constants
    ending in ``_AXIS`` bound to string literals, plus any literal axis
    names in ``Mesh(devices, (<axes>))`` calls (Names resolve through
    the constants)."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return set()
    consts: Dict[str, str] = {}
    axes: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            consts[node.targets[0].id] = node.value.value
            if node.targets[0].id.endswith("_AXIS"):
                axes.add(node.value.value)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = node.func
        name = callee.attr if isinstance(callee, ast.Attribute) else \
            callee.id if isinstance(callee, ast.Name) else ""
        if name != "Mesh":
            continue
        args = list(node.args[1:2]) + \
            [kw.value for kw in node.keywords if kw.arg == "axis_names"]
        for arg in args:
            if isinstance(arg, (ast.Tuple, ast.List)):
                for elt in arg.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str):
                        axes.add(elt.value)
                    elif isinstance(elt, ast.Name) and elt.id in consts:
                        axes.add(consts[elt.id])
    return axes


def extract_mesh_groups(source: str) -> Set[Tuple[str, ...]]:
    """Axis GROUPS a ``parallel/mesh.py`` declares: every tuple/list
    literal whose elements are all ``*_AXIS`` constant names —
    ``(DATA_AXIS, MODEL_AXIS)`` declares that ``data`` and ``model``
    coexist on one mesh. The sharding-flow rules use this to catch
    boundaries mixing axes of *different* meshes (``data`` with
    ``batch``), which no single mesh this framework builds can
    carry."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return set()
    consts: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.endswith("_AXIS") \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            consts[node.targets[0].id] = node.value.value
    groups: Set[Tuple[str, ...]] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Tuple, ast.List)) \
                or len(node.elts) < 2:
            continue
        names: List[str] = []
        for e in node.elts:
            if isinstance(e, ast.Name) and e.id in consts:
                names.append(consts[e.id])
            else:
                names = []
                break
        if names:
            groups.add(tuple(names))
    return groups


def _find_mesh_source(files: Sequence[str]) -> Optional[str]:
    """The scanned tree's ``parallel/mesh.py`` if present, else this
    package's own (so ``ptpu check some/engine/dir`` still validates
    axis names against the framework mesh)."""
    for f in files:
        norm = f.replace(os.sep, "/")
        if norm.endswith("parallel/mesh.py"):
            try:
                with open(f, "r", encoding="utf-8") as fh:
                    return fh.read()
            except OSError:
                continue
    fallback = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "parallel", "mesh.py")
    try:
        with open(fallback, "r", encoding="utf-8") as fh:
            return fh.read()
    except OSError:
        return None


def default_context() -> CheckContext:
    """Context anchored to this package's own mesh declarations (used
    when checking loose files/snippets with no mesh.py in scope)."""
    mesh_src = _find_mesh_source([])
    if not mesh_src:
        return CheckContext()
    return CheckContext(declared_axes=extract_mesh_axes(mesh_src),
                        declared_groups=extract_mesh_groups(mesh_src))


# ---------------------------------------------------------------------------
# graph utilities (shared by the call graph and the lock-order graph)
# ---------------------------------------------------------------------------

def strongly_connected(nodes: Set[str],
                       edges: Dict[str, Set[str]]) -> List[Set[str]]:
    """Tarjan SCCs (iterative), deterministic. Emission order is
    reverse-topological over the condensation — every SCC is emitted
    before any of its callers — which is exactly the order effect
    propagation wants (callees first)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[Set[str]] = []
    counter = [0]

    for root in sorted(nodes):
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, pi = work[-1]
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            advanced = False
            succs = sorted(edges.get(node, ()))
            for i in range(pi, len(succs)):
                s = succs[i]
                if s not in index:
                    work[-1] = (node, i + 1)
                    work.append((s, 0))
                    advanced = True
                    break
                if s in on_stack:
                    low[node] = min(low[node], index[s])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc: Set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)
    return sccs


# ---------------------------------------------------------------------------
# interprocedural layer: symbol table, call graph, effect summaries
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Witness:
    """Where an effect is anchored. ``via=None``: the direct site (the
    offending expression itself, described by ``what``). ``via=qname``:
    this function inherits the effect from a call to ``qname`` at
    (path, line) — follow the callee's own witness to keep walking the
    chain down to the direct site."""

    rule: str
    path: str
    line: int
    col: int
    what: str
    via: Optional[str] = None


@dataclass
class CallSite:
    """One call edge out of a function's immediate body (nested defs
    are deferred execution and keep their own edges out of summaries).
    ``arg_names[i]`` / ``kwarg_names[k]`` hold the bare variable name
    passed at that slot (None for non-Name expressions) so param-flow
    sinks (gather indices, invoked callables) can be matched through
    the call."""

    line: int
    col: int
    callee: Optional[str]           # resolved qname, or None
    bound: bool                     # invoked as self.m(...) / cls.m(...)
    arg_names: List[Optional[str]]
    kwarg_names: Dict[str, Optional[str]]
    lambda_args: Set[int] = field(default_factory=set)


#: effect summary slots propagated through the call graph; each maps
#: to the rule whose `# ptpu: allow[...]` pragma at the DIRECT site
#: stops propagation (blessing the helper blesses its callers)
EFFECTS = ("host_sync", "blocking", "callback", "net_wait")
EFFECT_RULE = {
    "host_sync": "host-sync-in-hot-path",
    "blocking": "blocking-under-lock",
    "callback": "callback-under-lock",
    "net_wait": "missing-timeout",
}


class FunctionInfo:
    """One indexed function (module-level def or class method) with its
    direct facts and, after :meth:`ProjectIndex._propagate`, the
    transitive summaries."""

    def __init__(self, qname: str, mod: ModuleInfo, node: ast.AST,
                 cls: Optional[str]):
        self.qname = qname
        self.mod = mod
        self.node = node
        self.cls = cls
        a = node.args
        self.params: List[str] = [p.arg for p in
                                  (*a.posonlyargs, *a.args)]
        self.calls: List[CallSite] = []
        #: effect name → Witness (direct first, transitive after
        #: propagation); acquired lock names use the canonical
        #: Class.attr / module.name identities of the lock-order graph
        self.effects: Dict[str, Optional[Witness]] = \
            {e: None for e in EFFECTS}
        self.acquires: Dict[str, Witness] = {}
        #: param position → Witness: the param ends up used as an
        #: advanced-indexing / jnp.take gather index (materialized-
        #: gather), or invoked as a callable (callback-under-lock)
        self.index_sinks: Dict[int, Witness] = {}
        self.call_sinks: Dict[int, Witness] = {}
        #: param position → Witness / canonical PartitionSpec string:
        #: the param flows into a shard_map boundary that pins that
        #: spec (implicit-reshard; collected by analysis/sharding.py)
        self.spec_sinks: Dict[int, Witness] = {}
        self.spec_constraints: Dict[int, str] = {}
        #: param position → Witness: the param is reduced (sum/dot/
        #: einsum/@) at operand precision — no f32 accumulator — so a
        #: caller passing bf16/f16 inherits the loss
        #: (low-precision-reduction; collected by analysis/numerics.py)
        self.lowprec_sinks: Dict[int, Witness] = {}

    def hot(self, dir_parts: Set[str]) -> bool:
        return bool(set(self.mod.path.split("/")[:-1]) & dir_parts)


def _immediate_body(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function's own body, NOT descending into nested
    defs/lambdas — those are deferred execution with their own call
    timing, so their effects must not leak into the enclosing
    function's summary (mirrors the held-lock reset in the walkers)."""
    stack = list(fn.body) if isinstance(fn.body, list) else [fn.body]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


_AMBIGUOUS = object()


class ProjectIndex:
    """Project-wide symbol table + call graph + effect summaries.

    Functions are keyed by their full dotted qname and registered under
    every dotted *suffix* (``als._lhs_fn``,
    ``models.als._lhs_fn``, …) so call sites resolve however the
    caller imported the module; a suffix naming two different functions
    is ambiguous and resolves to nothing (conservative silence beats a
    wrong chain). ``self.m()`` / ``cls.m()`` resolve within the
    enclosing class only.
    """

    def __init__(self, mods: Sequence[ModuleInfo]):
        self.functions: Dict[str, FunctionInfo] = {}
        self._suffixes: Dict[str, object] = {}
        for mod in mods:
            self._index_module(mod)
        for fn in self.functions.values():
            self._collect_direct(fn)
        self._propagate()

    # -- symbol table -------------------------------------------------

    def _register(self, key: str, fn: FunctionInfo) -> None:
        cur = self._suffixes.get(key)
        if cur is None:
            self._suffixes[key] = fn
        elif cur is not fn:
            self._suffixes[key] = _AMBIGUOUS

    def _index_module(self, mod: ModuleInfo) -> None:
        parts = mod.module_parts
        dotted = ".".join(parts)

        def add(local: str, node: ast.AST, cls: Optional[str]) -> None:
            qname = f"{dotted}.{local}" if dotted else local
            fn = FunctionInfo(qname, mod, node, cls)
            self.functions[qname] = fn
            for k in range(1, len(parts) + 1):
                self._register(
                    ".".join(parts[-k:] + [local]), fn)

        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add(node.name, node, None)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        add(f"{node.name}.{sub.name}", sub, node.name)

    def lookup(self, dotted: Optional[str]) -> Optional[FunctionInfo]:
        if not dotted:
            return None
        hit = self._suffixes.get(dotted)
        return hit if isinstance(hit, FunctionInfo) else None

    def resolve_call(self, mod: ModuleInfo, class_name: Optional[str],
                     func_expr: ast.AST) -> Tuple[Optional[str], bool]:
        """(callee qname, bound?) for a call's func expression:
        ``self.m``/``cls.m`` resolves within ``class_name``; names and
        attribute chains resolve through the import-alias table and
        the suffix index."""
        if isinstance(func_expr, ast.Attribute) \
                and isinstance(func_expr.value, ast.Name) \
                and func_expr.value.id in ("self", "cls"):
            if class_name is None:
                return None, True
            dotted = ".".join(mod.module_parts
                              + [class_name, func_expr.attr])
            fn = self.functions.get(dotted)
            return (fn.qname if fn else None), True
        resolved = mod.resolve(func_expr)
        if resolved is None:
            return None, False
        fn = self.lookup(resolved)
        if fn is None and isinstance(func_expr, ast.Name):
            # plain local call: the alias table has no entry, so try
            # the caller's own module
            dotted = ".".join(mod.module_parts + [func_expr.id])
            f2 = self.functions.get(dotted)
            return (f2.qname if f2 else None), False
        return (fn.qname if fn else None), False

    # -- direct facts -------------------------------------------------

    def _suppressed_at(self, mod: ModuleInfo, rule: str,
                       line: int) -> bool:
        return mod.suppressed(Finding(rule, mod.path, line, 0, ""))

    def _collect_direct(self, fn: FunctionInfo) -> None:
        # lazy imports: rules/concurrency import this module at top
        # level, so the detector tables are pulled in at call time
        from .concurrency import blocking_reason, lock_expr_name
        from .concurrency import CALLBACK_ATTRS
        from .lifecycle import net_wait_reason
        from .rules import GATHER_CALLS, host_sync_reason

        mod = fn.mod
        params = fn.params

        def witness(effect: str, node: ast.AST, what: str) -> None:
            rule = EFFECT_RULE[effect]
            if fn.effects[effect] is not None \
                    or self._suppressed_at(mod, rule, node.lineno):
                return
            fn.effects[effect] = Witness(rule, mod.path, node.lineno,
                                         node.col_offset, what)

        for node in _immediate_body(fn.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    name = lock_expr_name(mod, item.context_expr,
                                          fn.cls)
                    if name is not None and name not in fn.acquires:
                        fn.acquires[name] = Witness(
                            "lock-order-inversion", mod.path,
                            item.context_expr.lineno,
                            item.context_expr.col_offset,
                            f"acquires {name}")
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and isinstance(node.slice, ast.Name) \
                    and node.slice.id in params \
                    and isinstance(node.value, (ast.Name,
                                                ast.Attribute)) \
                    and not (isinstance(node.value, ast.Attribute)
                             and node.value.attr == "at"):
                pos = params.index(node.slice.id)
                if pos not in fn.index_sinks \
                        and not self._suppressed_at(
                            mod, "materialized-gather", node.lineno):
                    vname = mod.resolve(node.value) or "<expr>"
                    fn.index_sinks[pos] = Witness(
                        "materialized-gather", mod.path, node.lineno,
                        node.col_offset,
                        f"`{vname}[{node.slice.id}]` advanced-"
                        f"indexing gather")
            if not isinstance(node, ast.Call):
                continue
            resolved = mod.resolve(node.func)
            # host sync
            why = host_sync_reason(mod, node)
            if why is not None:
                witness("host_sync", node, why)
            # blocking
            why = blocking_reason(mod, node)
            if why is not None:
                witness("blocking", node, why)
            # timeout-less network wait (missing-timeout)
            why = net_wait_reason(mod, node)
            if why is not None:
                witness("net_wait", node, why)
            # delivery-style callback
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in CALLBACK_ATTRS:
                witness("callback", node,
                        f"`.{node.func.attr}(…)` delivers to "
                        f"subscribers/plugins")
            # param invoked as a callable
            if isinstance(node.func, ast.Name) \
                    and node.func.id in params:
                pos = params.index(node.func.id)
                if pos not in fn.call_sinks \
                        and not self._suppressed_at(
                            mod, "callback-under-lock", node.lineno):
                    fn.call_sinks[pos] = Witness(
                        "callback-under-lock", mod.path, node.lineno,
                        node.col_offset,
                        f"invokes its `{node.func.id}` argument")
            # gather-by-call (jnp.take / take_along_axis)
            gat = GATHER_CALLS.get(resolved or "")
            if gat is not None:
                idx_arg = None
                if len(node.args) > gat:
                    idx_arg = node.args[gat]
                for kw in node.keywords:
                    if kw.arg == "indices":
                        idx_arg = kw.value
                if isinstance(idx_arg, ast.Name) \
                        and idx_arg.id in params:
                    pos = params.index(idx_arg.id)
                    if pos not in fn.index_sinks \
                            and not self._suppressed_at(
                                mod, "materialized-gather",
                                node.lineno):
                        short = (resolved or "").rsplit(".", 1)[-1]
                        fn.index_sinks[pos] = Witness(
                            "materialized-gather", mod.path,
                            node.lineno, node.col_offset,
                            f"`jnp.{short}` gather")
            # call edge
            callee, bound = self.resolve_call(mod, fn.cls, node.func)
            arg_names = [a.id if isinstance(a, ast.Name) else None
                         for a in node.args]
            lambda_args = {i for i, a in enumerate(node.args)
                           if isinstance(a, (ast.Lambda,))}
            kwarg_names = {kw.arg: (kw.value.id
                                    if isinstance(kw.value, ast.Name)
                                    else None)
                           for kw in node.keywords if kw.arg}
            fn.calls.append(CallSite(node.lineno, node.col_offset,
                                     callee, bound, arg_names,
                                     kwarg_names, lambda_args))
        # sharding-flow direct sites: params this function feeds into
        # a shard_map boundary with a pinned in_spec (implicit-reshard)
        from .sharding import collect_spec_sinks
        for pos, (spec, w) in collect_spec_sinks(fn).items():
            fn.spec_sinks[pos] = w
            fn.spec_constraints[pos] = spec
        # numerics-flow direct sites: params this function reduces at
        # operand precision (low-precision-reduction)
        from .numerics import collect_lowprec_sinks
        for pos, w in collect_lowprec_sinks(fn).items():
            fn.lowprec_sinks[pos] = w

    # -- propagation --------------------------------------------------

    def _arg_to_param(self, call: CallSite,
                      callee: FunctionInfo) -> List[Tuple[int, int]]:
        """(caller arg slot, callee param position) pairs, accounting
        for the implicit self of bound calls. The caller arg slot is
        the positional index into ``call.arg_names``; keyword args get
        synthetic slots past the positionals."""
        pairs: List[Tuple[int, int]] = []
        off = 1 if call.bound else 0
        for i in range(len(call.arg_names)):
            pairs.append((i, i + off))
        base = len(call.arg_names)
        for j, k in enumerate(call.kwarg_names):
            if k in callee.params:
                pairs.append((base + j, callee.params.index(k)))
        return pairs

    def _call_slot_name(self, call: CallSite,
                        slot: int) -> Optional[str]:
        if slot < len(call.arg_names):
            return call.arg_names[slot]
        keys = list(call.kwarg_names)
        j = slot - len(call.arg_names)
        return call.kwarg_names[keys[j]] if j < len(keys) else None

    def _propagate(self) -> None:
        edges: Dict[str, Set[str]] = {}
        for q, fn in self.functions.items():
            edges[q] = {c.callee for c in fn.calls
                        if c.callee and c.callee in self.functions}
        sccs = strongly_connected(set(self.functions), edges)
        for scc in sccs:  # emitted callees-first
            members = [self.functions[q] for q in sorted(scc)]
            # fixpoint within the SCC (mutual recursion: an effect
            # anywhere in the cycle reaches every member)
            for _ in range(len(members) + 1):
                changed = False
                for fn in members:
                    changed |= self._absorb(fn)
                if not changed:
                    break

    def _absorb(self, fn: FunctionInfo) -> bool:
        changed = False
        for call in fn.calls:
            callee = self.functions.get(call.callee or "")
            if callee is None or callee is fn:
                continue
            for eff in EFFECTS:
                if fn.effects[eff] is None \
                        and callee.effects[eff] is not None:
                    fn.effects[eff] = Witness(
                        EFFECT_RULE[eff], fn.mod.path, call.line,
                        call.col, "", via=callee.qname)
                    changed = True
            for name, w in callee.acquires.items():
                if name not in fn.acquires:
                    fn.acquires[name] = Witness(
                        "lock-order-inversion", fn.mod.path, call.line,
                        call.col, f"acquires {name}",
                        via=callee.qname)
                    changed = True
            for slot, pos in self._arg_to_param(call, callee):
                name = self._call_slot_name(call, slot)
                if name is None or name not in fn.params:
                    continue
                my_pos = fn.params.index(name)
                if pos in callee.index_sinks \
                        and my_pos not in fn.index_sinks:
                    fn.index_sinks[my_pos] = Witness(
                        "materialized-gather", fn.mod.path, call.line,
                        call.col, "", via=f"{callee.qname}#{pos}")
                    changed = True
                if pos in callee.call_sinks \
                        and my_pos not in fn.call_sinks:
                    fn.call_sinks[my_pos] = Witness(
                        "callback-under-lock", fn.mod.path, call.line,
                        call.col, "", via=f"{callee.qname}#{pos}")
                    changed = True
                if pos in callee.lowprec_sinks \
                        and my_pos not in fn.lowprec_sinks:
                    fn.lowprec_sinks[my_pos] = Witness(
                        "low-precision-reduction", fn.mod.path,
                        call.line, call.col, "",
                        via=f"{callee.qname}#{pos}")
                    changed = True
                if pos in callee.spec_constraints \
                        and my_pos not in fn.spec_constraints:
                    fn.spec_sinks[my_pos] = Witness(
                        "implicit-reshard", fn.mod.path, call.line,
                        call.col, "", via=f"{callee.qname}#{pos}")
                    fn.spec_constraints[my_pos] = \
                        callee.spec_constraints[pos]
                    changed = True
        return changed

    # -- chain reconstruction ----------------------------------------

    def chain(self, start: FunctionInfo, effect: str
              ) -> List[Tuple[str, Witness]]:
        """(function qname, witness) hops from ``start`` down to the
        direct site; the last hop's witness has ``via=None`` and a
        populated ``what``. Cycle-guarded."""
        hops: List[Tuple[str, Witness]] = []
        fn: Optional[FunctionInfo] = start
        seen: Set[str] = set()
        while fn is not None and fn.qname not in seen:
            seen.add(fn.qname)
            w = fn.effects.get(effect)
            if w is None:
                break
            hops.append((fn.qname, w))
            fn = self.functions.get(w.via) if w.via else None
        return hops

    def sink_chain(self, start: FunctionInfo, kind: str, pos: int
                   ) -> List[Tuple[str, Witness]]:
        """Like :meth:`chain` for a param-position sink (``kind`` is
        ``index``, ``call``, or ``spec``)."""
        hops: List[Tuple[str, Witness]] = []
        fn: Optional[FunctionInfo] = start
        seen: Set[Tuple[str, int]] = set()
        while fn is not None and (fn.qname, pos) not in seen:
            seen.add((fn.qname, pos))
            sinks = {"index": fn.index_sinks, "call": fn.call_sinks,
                     "spec": fn.spec_sinks,
                     "lowprec": fn.lowprec_sinks}[kind]
            w = sinks.get(pos)
            if w is None:
                break
            hops.append((fn.qname, w))
            if not w.via:
                break
            qname, _, p = w.via.partition("#")
            fn = self.functions.get(qname)
            pos = int(p) if p else pos
        return hops


def short_name(qname: str) -> str:
    """`pkg.mod.Class.meth` → `Class.meth`; `pkg.mod.fn` → `fn` (for
    finding messages — the full path rides in ``related``)."""
    parts = qname.split(".")
    if len(parts) >= 2 and parts[-2][:1].isupper():
        return ".".join(parts[-2:])
    return parts[-1]


def chain_text(hops: List[Tuple[str, Witness]]) -> str:
    """Human call-chain starting at the callee of the flagged call
    site: ``outer (lib/middle.py:4) → inner (utils/x.py:2):
    np.asarray …`` — each hop's location is the site *inside* that
    function (its call to the next hop, or the direct effect)."""
    if not hops:
        return ""
    segs = [f"{short_name(q)} ({w.path}:{w.line})" for q, w in hops]
    last = hops[-1][1]
    return f"{' → '.join(segs)}: {last.what}"


def chain_related(hops: List[Tuple[str, Witness]]
                  ) -> Tuple[Tuple[str, int, str], ...]:
    out: List[Tuple[str, int, str]] = []
    for qname, w in hops:
        note = w.what if not w.via \
            else f"`{short_name(qname)}` calls " \
                 f"`{short_name(w.via.partition('#')[0])}` here"
        out.append((w.path, w.line, note))
    return tuple(out)


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if not d.startswith(".")
                             and d != "__pycache__")
            for n in sorted(names):
                if n.endswith(".py"):
                    out.append(os.path.join(root, n))
    return out


def _run_rules(mods: List[ModuleInfo],
               rule_names: Optional[Sequence[str]],
               ctx: CheckContext) -> List[Finding]:
    """Module-scoped rules per file, then project-scoped rules over the
    whole parsed set (the cross-file lock-order graph, the
    interprocedural summary consumers); pragma suppression is resolved
    against the module each finding points at. A rule that crashes on
    one module becomes a ``checker-error`` finding instead of killing
    the run — the checker must never be the flakiest thing in CI."""
    from .rules import RULES

    if ctx.project is None:
        ctx.project = ProjectIndex(mods)
    by_path = {m.path: m for m in mods}
    findings: List[Finding] = []

    def guarded(fn, target, anchor_path: str, name: str) -> None:
        try:
            findings.extend(fn(target, ctx))
        except Exception as e:  # noqa: BLE001 — robustness boundary
            findings.append(Finding(
                "checker-error", anchor_path, 1, 0,
                f"rule `{name}` crashed: {type(e).__name__}: {e} "
                f"(checker bug — findings for this rule are "
                f"incomplete here)"))

    for name, rule in RULES.items():
        if rule_names and name not in rule_names:
            continue
        if rule.project:
            guarded(rule.fn, mods,
                    mods[0].path if mods else "<project>", name)
        else:
            for mod in mods:
                guarded(rule.fn, mod, mod.path, name)
    surviving = [f for f in findings
                 if f.path not in by_path
                 or not by_path[f.path].suppressed(f)]
    return sorted(surviving,
                  key=lambda f: (f.path, f.line, f.col, f.rule))


def check_source(source: str, path: str = "<string>",
                 rule_names: Optional[Sequence[str]] = None,
                 ctx: Optional[CheckContext] = None) -> List[Finding]:
    """Run the (selected) rules over one source blob — the test and
    single-file entry point. Pragma suppression applies; project rules
    see a one-module project."""
    ctx = ctx or default_context()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding("parse-error", path, e.lineno or 1, 0,
                        f"cannot parse: {e.msg}")]
    return _run_rules([ModuleInfo(path, source, tree)], rule_names, ctx)


def check_project(files: Dict[str, str],
                  rule_names: Optional[Sequence[str]] = None,
                  ctx: Optional[CheckContext] = None) -> List[Finding]:
    """Run the (selected) rules over an in-memory multi-module project
    — the entry point the interprocedural tests use (cross-module
    summary propagation without touching disk). ``files`` maps
    relative paths to sources; unparsable entries become per-file
    ``parse-error`` findings like :func:`run_check`."""
    ctx = ctx or default_context()
    findings: List[Finding] = []
    mods: List[ModuleInfo] = []
    for path, source in sorted(files.items()):
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            findings.append(Finding("parse-error", path, e.lineno or 1,
                                    0, f"cannot parse: {e.msg}"))
            continue
        mods.append(ModuleInfo(path, source, tree))
    findings.extend(_run_rules(mods, rule_names, ctx))
    return sorted(findings,
                  key=lambda f: (f.path, f.line, f.col, f.rule))


def run_check(paths: Sequence[str],
              rule_names: Optional[Sequence[str]] = None) -> List[Finding]:
    """Walk ``paths``, check every ``.py`` file, return surviving
    findings sorted by location."""
    from .rules import RULES

    unknown = set(rule_names or ()) - set(RULES)
    if unknown:
        raise ValueError(f"unknown rule(s): {sorted(unknown)} "
                         f"(have: {sorted(RULES)})")
    files = iter_py_files(paths)
    mesh_src = _find_mesh_source(files)
    ctx = CheckContext(
        declared_axes=extract_mesh_axes(mesh_src) if mesh_src else set(),
        declared_groups=extract_mesh_groups(mesh_src)
        if mesh_src else set())
    findings: List[Finding] = []
    mods: List[ModuleInfo] = []
    for f in files:
        try:
            with open(f, "r", encoding="utf-8") as fh:
                src = fh.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding("parse-error", f, 1, 0, str(e)))
            continue
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            findings.append(Finding("parse-error", f, e.lineno or 1, 0,
                                    f"cannot parse: {e.msg}"))
            continue
        mods.append(ModuleInfo(f, src, tree))
    findings.extend(_run_rules(mods, rule_names, ctx))
    return findings
