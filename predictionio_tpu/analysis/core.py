"""Core machinery of ``ptpu check`` — the JAX-aware static-analysis pass.

Pure-AST: this package never imports jax/numpy, so ``ptpu check`` runs in
milliseconds on a storage-only host and in CI without an accelerator.

Pieces:

- :class:`Finding` — one lint hit (rule, path, line, col, message).
- :class:`ModuleInfo` — a parsed file plus its import-alias table, so
  rules match *resolved* dotted names (``np.asarray`` and
  ``numpy.asarray`` are the same callee; ``from jax import jit`` is
  ``jax.jit``).
- pragma suppression — ``# ptpu: allow[rule]`` on the finding line or
  the line directly above silences that rule there (``allow[*]``
  silences every rule). Justify the pragma in prose after the bracket.
- :func:`run_check` — walk paths, parse once per file, run every rule,
  drop pragma'd findings, return the rest sorted.

The rule catalogue lives in :mod:`predictionio_tpu.analysis.rules`;
``docs/static-analysis.md`` is the operator-facing reference.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set

#: ``# ptpu: allow[rule-a,rule-b] — justification``; the marker may sit
#: anywhere inside a comment (pragmas usually end a justification
#: sentence), and the justification is free-form prose
PRAGMA_RE = re.compile(r"#.*?ptpu:\s*allow\[([^\]]*)\]")

#: ``# ptpu: guarded-by[lock] — justification``: the concurrency
#: contract annotation (see rule ``unguarded-shared-state``). On an
#: ``__init__`` attribute assignment it DECLARES the attribute
#: lock-guarded; on a ``def`` line it asserts every caller holds the
#: lock; on an access line it asserts that access is safe (caller
#: holds the lock, or a justified benign racy read).
GUARDED_RE = re.compile(r"#.*?ptpu:\s*guarded-by\[([^\]]*)\]")


@dataclass(frozen=True)
class Finding:
    """One checker hit, formatted ``path:line:col: rule: message``."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"


@dataclass
class CheckContext:
    """Cross-file facts rules need: the mesh axis names declared by
    ``parallel/mesh.py`` (for sharding-mismatch)."""

    declared_axes: Set[str] = field(default_factory=set)


class ModuleInfo:
    """A parsed module plus resolution helpers shared by every rule."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()
        self.aliases = _collect_aliases(tree)
        self.pragmas = _collect_pragmas(self.lines)
        self.guards = _collect_guards(self.lines)

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain with import aliases
        expanded (``np.asarray`` → ``numpy.asarray``), else None."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        head = self.aliases.get(node.id, node.id)
        parts.append(head)
        return ".".join(reversed(parts))

    def _covering_lines(self, line: int) -> List[int]:
        """``line`` itself plus the contiguous comment block directly
        above it — the lines whose markers cover a statement at
        ``line`` (a multi-line justification can carry the marker on
        any of its lines)."""
        candidates = [line]
        ln = line - 1
        while 1 <= ln <= len(self.lines) \
                and self.lines[ln - 1].strip().startswith("#"):
            candidates.append(ln)
            ln -= 1
        return candidates

    def suppressed(self, finding: Finding) -> bool:
        """A pragma suppresses a finding on its own line, or anywhere in
        the contiguous comment block directly above the finding line."""
        for ln in self._covering_lines(finding.line):
            allowed = self.pragmas.get(ln)
            if allowed and ("*" in allowed or finding.rule in allowed):
                return True
        return False

    def guards_at(self, line: int) -> Set[str]:
        """Lock names asserted by ``# ptpu: guarded-by[...]`` markers
        covering ``line`` (same placement rules as pragmas)."""
        out: Set[str] = set()
        for ln in self._covering_lines(line):
            out |= self.guards.get(ln, set())
        return out


def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
    """Local name → dotted origin, from every import in the module
    (function-local imports included — the hot packages import jnp
    inside functions to keep storage-only commands jax-free)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _collect_pragmas(lines: Sequence[str]) -> Dict[int, Set[str]]:
    pragmas: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, start=1):
        m = PRAGMA_RE.search(line)
        if m:
            pragmas[i] = {r.strip() for r in m.group(1).split(",")
                          if r.strip()}
    return pragmas


def _collect_guards(lines: Sequence[str]) -> Dict[int, Set[str]]:
    guards: Dict[int, Set[str]] = {}
    for i, line in enumerate(lines, start=1):
        m = GUARDED_RE.search(line)
        if m:
            guards[i] = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
    return guards


# ---------------------------------------------------------------------------
# mesh axis extraction (sharding-mismatch's ground truth)
# ---------------------------------------------------------------------------

def extract_mesh_axes(source: str) -> Set[str]:
    """Axis names a ``parallel/mesh.py`` declares: module constants
    ending in ``_AXIS`` bound to string literals, plus any literal axis
    names in ``Mesh(devices, (<axes>))`` calls (Names resolve through
    the constants)."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return set()
    consts: Dict[str, str] = {}
    axes: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            consts[node.targets[0].id] = node.value.value
            if node.targets[0].id.endswith("_AXIS"):
                axes.add(node.value.value)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = node.func
        name = callee.attr if isinstance(callee, ast.Attribute) else \
            callee.id if isinstance(callee, ast.Name) else ""
        if name != "Mesh":
            continue
        args = list(node.args[1:2]) + \
            [kw.value for kw in node.keywords if kw.arg == "axis_names"]
        for arg in args:
            if isinstance(arg, (ast.Tuple, ast.List)):
                for elt in arg.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str):
                        axes.add(elt.value)
                    elif isinstance(elt, ast.Name) and elt.id in consts:
                        axes.add(consts[elt.id])
    return axes


def _find_mesh_source(files: Sequence[str]) -> Optional[str]:
    """The scanned tree's ``parallel/mesh.py`` if present, else this
    package's own (so ``ptpu check some/engine/dir`` still validates
    axis names against the framework mesh)."""
    for f in files:
        norm = f.replace(os.sep, "/")
        if norm.endswith("parallel/mesh.py"):
            try:
                with open(f, "r", encoding="utf-8") as fh:
                    return fh.read()
            except OSError:
                continue
    fallback = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "parallel", "mesh.py")
    try:
        with open(fallback, "r", encoding="utf-8") as fh:
            return fh.read()
    except OSError:
        return None


def default_context() -> CheckContext:
    """Context anchored to this package's own mesh declarations (used
    when checking loose files/snippets with no mesh.py in scope)."""
    mesh_src = _find_mesh_source([])
    return CheckContext(declared_axes=extract_mesh_axes(mesh_src)
                        if mesh_src else set())


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if not d.startswith(".")
                             and d != "__pycache__")
            for n in sorted(names):
                if n.endswith(".py"):
                    out.append(os.path.join(root, n))
    return out


def _run_rules(mods: List[ModuleInfo],
               rule_names: Optional[Sequence[str]],
               ctx: CheckContext) -> List[Finding]:
    """Module-scoped rules per file, then project-scoped rules over the
    whole parsed set (the cross-file lock-order graph); pragma
    suppression is resolved against the module each finding points at."""
    from .rules import RULES

    by_path = {m.path: m for m in mods}
    findings: List[Finding] = []
    for name, rule in RULES.items():
        if rule_names and name not in rule_names:
            continue
        if rule.project:
            findings.extend(rule.fn(mods, ctx))
        else:
            for mod in mods:
                findings.extend(rule.fn(mod, ctx))
    surviving = [f for f in findings
                 if f.path not in by_path
                 or not by_path[f.path].suppressed(f)]
    return sorted(surviving,
                  key=lambda f: (f.path, f.line, f.col, f.rule))


def check_source(source: str, path: str = "<string>",
                 rule_names: Optional[Sequence[str]] = None,
                 ctx: Optional[CheckContext] = None) -> List[Finding]:
    """Run the (selected) rules over one source blob — the test and
    single-file entry point. Pragma suppression applies; project rules
    see a one-module project."""
    ctx = ctx or default_context()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding("parse-error", path, e.lineno or 1, 0,
                        f"cannot parse: {e.msg}")]
    return _run_rules([ModuleInfo(path, source, tree)], rule_names, ctx)


def run_check(paths: Sequence[str],
              rule_names: Optional[Sequence[str]] = None) -> List[Finding]:
    """Walk ``paths``, check every ``.py`` file, return surviving
    findings sorted by location."""
    from .rules import RULES

    unknown = set(rule_names or ()) - set(RULES)
    if unknown:
        raise ValueError(f"unknown rule(s): {sorted(unknown)} "
                         f"(have: {sorted(RULES)})")
    files = iter_py_files(paths)
    mesh_src = _find_mesh_source(files)
    ctx = CheckContext(declared_axes=extract_mesh_axes(mesh_src)
                       if mesh_src else set())
    findings: List[Finding] = []
    mods: List[ModuleInfo] = []
    for f in files:
        try:
            with open(f, "r", encoding="utf-8") as fh:
                src = fh.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding("parse-error", f, 1, 0, str(e)))
            continue
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            findings.append(Finding("parse-error", f, e.lineno or 1, 0,
                                    f"cannot parse: {e.msg}"))
            continue
        mods.append(ModuleInfo(f, src, tree))
    findings.extend(_run_rules(mods, rule_names, ctx))
    return findings
