"""``ptpu audit-lifecycle`` — the runtime resource-leak audit.

The static lifecycle rules (:mod:`.lifecycle`) catch the leaks the AST
can see — a spawned thread with no join path, a queue with no bound.
This module catches the ones only a running process shows: it BOOTS
each subsystem the fleet/control-plane era added (event / storage /
engine servers, the stream trainer, the fleet aggregator, the router
autoscaler + replica lifecycle), drives full start→serve→stop cycles,
and snapshots the process before and after:

- ``threads`` — entries under ``/proc/self/task``;
- ``fds``     — entries under ``/proc/self/fd``;
- ``sockets`` — fds whose readlink target is a socket.

Each entry runs one un-measured warmup cycle first (lazy imports,
logging handlers, interpreter pools — one-time costs are not leaks),
then ``cycles`` measured cycles. Anything still held after a
gc+settle loop is the per-entry leak census. A subsystem that leaks
one thread per cycle shows ``threads >= cycles`` here — exactly the
daemon the static ``leaked-thread`` rule points at.

The census gates against a committed golden manifest
(``analysis/lifecycle_baseline.json``) with the same ratchet semantics
as ``audit-hlo`` / ``audit-numerics``:

- a leak count above the recorded one FAILS, naming the entry and the
  resource (the recorded value is the *allowed* leak — ideally 0);
- an entry the baseline never recorded FAILS (record deliberately
  with ``--baseline-grow``);
- counts below the record print as shrinkable, and
  ``--write-baseline`` only ever ratchets the file down.

Everything servers-flavored imports lazily inside the entry builders;
the CLI pins ``JAX_PLATFORMS=cpu`` before the first jax import so the
engine entries train/serve on host devices. Entries bind HTTP
listeners to ``127.0.0.1:0`` (ephemeral ports) — the audit never
needs a free well-known port.

See docs/static-analysis.md ("the audit-lifecycle gate failed — now
what").
"""

from __future__ import annotations

import gc
import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .hlo_audit import AuditError

MANIFEST_VERSION = 1

#: measured start→serve→stop cycles per entry (after one warmup)
DEFAULT_CYCLES = 3

#: how long the settle loop waits for lazily-released resources
#: (executor reaper threads, GC-driven socket closes) to drain before
#: the after-snapshot is final
SETTLE_SEC = 5.0

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "lifecycle_baseline.json")

RESOURCES = ("threads", "fds", "sockets")


# ---------------------------------------------------------------------------
# process snapshots
# ---------------------------------------------------------------------------

def snapshot() -> Dict[str, int]:
    """Count this process's threads / fds / socket-fds via ``/proc``.
    Off Linux (no ``/proc/self``) threads fall back to
    ``threading.active_count()`` and fd counts read as 0 — the thread
    gate still works everywhere the CI runs."""
    task_dir = "/proc/self/task"
    fd_dir = "/proc/self/fd"
    if not os.path.isdir(task_dir):
        import threading

        return {"threads": threading.active_count(),
                "fds": 0, "sockets": 0}
    threads = len(os.listdir(task_dir))
    fds = 0
    sockets = 0
    for fd in os.listdir(fd_dir):
        fds += 1
        try:
            if os.readlink(os.path.join(fd_dir, fd)).startswith(
                    "socket:"):
                sockets += 1
        except OSError:
            pass  # the fd closed between listdir and readlink
    return {"threads": threads, "fds": fds, "sockets": sockets}


def _leak(before: Dict[str, int], after: Dict[str, int]) -> Dict[str, int]:
    return {k: max(0, after.get(k, 0) - before.get(k, 0))
            for k in RESOURCES}


def _settle(before: Dict[str, int],
            settle_sec: float = SETTLE_SEC) -> Dict[str, int]:
    """Re-snapshot until the census returns to ``before`` (or the
    budget runs out): a thread mid-exit or a socket awaiting GC is
    lag, not a leak — but anything still held past ``settle_sec`` is
    charged."""
    deadline = time.monotonic() + max(settle_sec, 0.0)
    while True:
        gc.collect()
        now = snapshot()
        if not any(_leak(before, now).values()) \
                or time.monotonic() >= deadline:
            return now
        time.sleep(0.05)


# ---------------------------------------------------------------------------
# entry-point builders
#
# Each builder runs the one-time setup (training a model, seeding a
# storage) and returns the ``cycle()`` callable the harness measures.
# One cycle = start the subsystem, exercise it, stop it — everything
# the subsystem allocated for the cycle must be released by the stop.
# ---------------------------------------------------------------------------

def _http_get(port: int, path: str) -> int:
    import urllib.request

    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    with urllib.request.urlopen(req, timeout=10) as resp:
        resp.read()
        return resp.status


def _http_post(port: int, path: str, body: dict) -> int:
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            resp.read()
            return resp.status
    except urllib.error.HTTPError as e:
        e.read()
        return e.code


def _mem_storage():
    from ..data.storage import Storage

    return Storage(env={"PIO_STORAGE_SOURCES_MEM_TYPE": "memory"})


def _entry_event_server() -> Callable[[], None]:
    from ..server.eventserver import create_event_server

    from ..data.storage import AccessKey, App

    storage = _mem_storage()
    app_id = storage.apps().insert(App(0, "auditapp"))
    storage.events().init(app_id)
    storage.access_keys().insert(
        AccessKey(key="AUDITKEY", app_id=app_id, events=[]))

    def cycle() -> None:
        srv = create_event_server(storage, "127.0.0.1", 0)
        srv.start_background()
        try:
            _http_post(
                srv.port, "/events.json?accessKey=AUDITKEY",
                {"event": "rate", "entityType": "user", "entityId": "u0",
                 "targetEntityType": "item", "targetEntityId": "i0",
                 "properties": {"rating": 5}})
        finally:
            srv.shutdown()

    return cycle


def _entry_storage_server() -> Callable[[], None]:
    from ..server.storageserver import create_storage_server

    storage = _mem_storage()

    def cycle() -> None:
        srv = create_storage_server(storage, "127.0.0.1", 0)
        srv.start_background()
        try:
            _http_get(srv.port, "/v1/status")
        finally:
            srv.shutdown()

    return cycle


def _trained_recommender():
    """Seed + train the small recommendation fixture once; returns
    everything a cycle needs to bind a QueryServer."""
    import numpy as np

    from ..controller import Context
    from ..data import DataMap, Event
    from ..data.storage import App
    from ..templates.recommendation import (
        default_engine_params,
        recommendation_engine,
    )
    from ..workflow import run_train

    storage = _mem_storage()
    app_id = storage.apps().insert(App(0, "auditapp"))
    es = storage.events()
    es.init(app_id)
    rng = np.random.default_rng(11)
    events = []
    for u in range(16):
        for i in rng.choice(16, size=5, replace=False):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap(
                    {"rating": float(rng.integers(1, 6))})))
    es.insert_batch(events, app_id)
    ctx = Context(app_name="auditapp", _storage=storage)
    engine = recommendation_engine()
    ep = default_engine_params("auditapp", rank=4, num_iterations=2,
                               seed=5)
    run_train(ctx, engine, ep, engine_id="audit", engine_version="1")
    return ctx, engine, ep


def _bind_query_server(ctx, engine, ep, **cfg):
    """One served binding without the deploy() registry ceremony:
    latest COMPLETED instance → models → QueryServer."""
    from ..server.engineserver import QueryServer, ServerConfig
    from ..workflow import core as wf

    instance = ctx.storage.engine_instances().get_latest_completed(
        "audit", "1", "engine.json")
    if instance is None:
        raise AuditError("engine fixture did not train")
    models = wf.load_models_for_deploy(ctx, engine, instance, ep)
    return QueryServer(ctx, engine, ep, models, instance,
                       ServerConfig(warm_start=False, **cfg))


def _entry_engine_server() -> Callable[[], None]:
    from ..server.engineserver import create_engine_server

    ctx, engine, ep = _trained_recommender()

    def cycle() -> None:
        qs = _bind_query_server(ctx, engine, ep)
        srv = create_engine_server(qs, "127.0.0.1", 0)
        srv.start_background()
        try:
            _http_post(srv.port, "/queries.json",
                       {"user": "u1", "num": 3})
        finally:
            srv.shutdown()
            qs.close()

    return cycle


def _entry_stream_trainer() -> Callable[[], None]:
    from ..cache.bus import InvalidationBus
    from ..streaming.trainer import StreamConfig, StreamTrainer

    ctx, engine, ep = _trained_recommender()

    def cycle() -> None:
        qs = _bind_query_server(ctx, engine, ep)
        trainer = StreamTrainer(
            qs, StreamConfig(app_name="auditapp", interval_ms=20,
                             consumer="audit-lifecycle"),
            bus=InvalidationBus())
        trainer.start()
        try:
            trainer.consume_once()
        finally:
            trainer.stop()
            qs.close()

    return cycle


def _entry_fleet() -> Callable[[], None]:
    """Fleet aggregator over two fake replicas behind an injected
    fetch (socket-free): start the scrape loop, let it merge a few
    cycles, stop."""
    from ..fleet.aggregator import FleetAggregator, FleetConfig
    from ..obs import MetricsRegistry

    reg = MetricsRegistry()
    # ptpu: allow[metric-catalog-drift] — fixture registry local to
    # the audit cycle; the family mimics a replica export and never
    # lands on a real /metrics surface
    reg.counter("pio_queries_total", "served queries").inc(7)
    export = reg.export()

    def fetch(url: str, timeout: float) -> Tuple[int, dict]:
        if url.endswith("/metrics.json"):
            return 200, export
        return 200, {"servingWarm": True}

    def cycle() -> None:
        agg = FleetAggregator(
            FleetConfig(replicas=["r0:1", "r1:1"],
                        scrape_interval_sec=0.02,
                        slo_interval_sec=0.0),
            fetch=fetch)
        agg.start()
        try:
            agg.scrape_cycle()
        finally:
            agg.stop()

    return cycle


def _entry_router_autoscaler() -> Callable[[], None]:
    """Replica lifecycle (worker threads per managed replica) + the
    autoscaler control loop, with injected spawn/probe — no sockets,
    no real replicas."""
    from ..router.autoscaler import Autoscaler, AutoscalePolicy
    from ..router.lifecycle import ReplicaLifecycle
    from ..router.router import QueryRouter

    class _Signals:
        slo = None

        def capacity_signals(self):
            return {"qps": 0.0, "kneeQps": 100.0, "headroom": 0.9}

        def replica_health(self, name):
            return "up"

        def add_replica(self, base):
            pass

        def remove_replica(self, name):
            pass

    def cycle() -> None:
        ports = iter(range(9800, 9900))

        def spawn():
            return f"127.0.0.1:{next(ports)}", lambda: None

        signals = _Signals()
        router = QueryRouter()
        lc = ReplicaLifecycle(
            spawn, router=router, aggregator=signals,
            probe=lambda base, t: {"servingWarm": True},
            notify_drain=lambda base, t: None,
            poll_interval_sec=0.01, drain_deadline_sec=0.1)
        asc = Autoscaler(signals, lc,
                         AutoscalePolicy(min_replicas=1, max_replicas=2,
                                         interval_sec=0.02))
        asc.start()
        try:
            lc.scale_out(reason="audit cycle")
            lc.scale_out(reason="audit cycle")
            lc.await_ready(2, timeout_sec=5.0)
        finally:
            asc.stop()
            lc.close(stop_replicas=True)

    return cycle


#: name → (builder, one-line description); ordered — the manifest and
#: the CI artifact list entries in this order
ENTRY_POINTS: Dict[str, Tuple[Callable[[], Callable[[], None]], str]] = {
    "event_server": (
        _entry_event_server,
        "event server bind → ingest one event → shutdown"),
    "storage_server": (
        _entry_storage_server,
        "storage server bind → healthz → shutdown"),
    "engine_server": (
        _entry_engine_server,
        "engine server bind → one query → shutdown + close"),
    "stream_trainer": (
        _entry_stream_trainer,
        "stream trainer start → one consume pass → stop"),
    "fleet": (
        _entry_fleet,
        "fleet aggregator (2 fake replicas) scrape loop start → stop"),
    "router_autoscaler": (
        _entry_router_autoscaler,
        "replica lifecycle scale-out + autoscaler loop start → close"),
}


def run_audit(names: Optional[Sequence[str]] = None,
              cycles: int = DEFAULT_CYCLES,
              settle_sec: float = SETTLE_SEC,
              entry_points: Optional[dict] = None) -> dict:
    """Boot + cycle every (selected) entry point; returns the
    manifest dict. ``entry_points`` overrides the registry (tests
    inject deliberately-leaky fixtures)."""
    registry = ENTRY_POINTS if entry_points is None else entry_points
    unknown = set(names or ()) - set(registry)
    if unknown:
        raise AuditError(f"unknown entry point(s): {sorted(unknown)} "
                         f"(have: {sorted(registry)})")
    entries: Dict[str, Dict[str, int]] = {}
    for name, (builder, _desc) in registry.items():
        if names and name not in names:
            continue
        try:
            cycle = builder()
        except AuditError:
            raise
        except Exception as e:  # noqa: BLE001 — a broken fixture is an
            raise AuditError(    # environment error, not a leak
                f"{name}: entry setup failed: {e}") from e
        cycle()  # warmup: lazy imports, handler/pool one-time costs
        before = _settle(snapshot(), settle_sec)
        for _ in range(max(cycles, 1)):
            cycle()
        after = _settle(before, settle_sec)
        entries[name] = _leak(before, after)
    return {"version": MANIFEST_VERSION, "cycles": max(cycles, 1),
            "entries": entries}


# ---------------------------------------------------------------------------
# manifest I/O + ratchet diff
# ---------------------------------------------------------------------------

def load_manifest(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) \
            or doc.get("version") != MANIFEST_VERSION:
        raise ValueError(f"{path}: not an audit-lifecycle manifest "
                         f"(expected version {MANIFEST_VERSION})")
    return doc


def write_manifest(path: str, manifest: dict,
                   cap: Optional[dict] = None) -> None:
    """Persist the manifest. With ``cap`` (the previously committed
    baseline) the write RATCHETS: entries the old baseline never held
    are dropped and every leak count clamps to the recorded value —
    the allowed leak only ever shrinks (``--baseline-grow`` writes
    as-is)."""
    doc = manifest
    if cap is not None:
        old = cap.get("entries", {})
        entries: Dict[str, Dict[str, int]] = {}
        for name, rec in manifest.get("entries", {}).items():
            if name not in old:
                continue
            orec = old[name]
            entries[name] = {k: min(rec.get(k, 0), orec.get(k, 0))
                             for k in RESOURCES}
        doc = {"version": MANIFEST_VERSION,
               "cycles": manifest.get("cycles", DEFAULT_CYCLES),
               "entries": entries}
    from .baseline import atomic_write_text

    atomic_write_text(
        path, json.dumps(doc, indent=2, sort_keys=True) + "\n")


def diff_manifests(current: dict, baseline: dict
                   ) -> Tuple[List[str], List[str]]:
    """(violations, shrinkable) between a fresh census and the golden
    baseline. Violations name the entry, the resource and both counts
    — the line an operator greps for."""
    violations: List[str] = []
    shrinkable: List[str] = []
    cur = current.get("entries", {})
    base = baseline.get("entries", {})
    cycles = current.get("cycles", DEFAULT_CYCLES)
    for name, rec in cur.items():
        brec = base.get(name)
        if brec is None:
            violations.append(
                f"{name}: entry point not in the baseline — record it "
                f"deliberately with --write-baseline --baseline-grow")
            continue
        for res in RESOURCES:
            c = rec.get(res, 0)
            b = brec.get(res, 0)
            if c > b:
                per_cycle = (f" (~{c / cycles:.1f} per cycle over "
                             f"{cycles} cycles)" if cycles else "")
                violations.append(
                    f"{name}: leaked {c} {res} across the measured "
                    f"cycles, baseline allows {b}{per_cycle} — a "
                    f"start→stop cycle is not releasing everything it "
                    f"started. Find the owner with the static rules "
                    f"(ptpu check: leaked-thread) or py-spy dump, fix "
                    f"its stop/close, or record deliberately with "
                    f"--baseline-grow")
            elif c < b:
                shrinkable.append(
                    f"{name}: {res} leak recorded {b}, found {c}")
    for name in base:
        if name not in cur:
            shrinkable.append(f"{name}: entry point no longer audited")
    return violations, shrinkable


def format_text(manifest: dict) -> str:
    lines: List[str] = []
    cycles = manifest.get("cycles", DEFAULT_CYCLES)
    for name, rec in manifest.get("entries", {}).items():
        leaks = {k: v for k, v in rec.items() if v}
        if leaks:
            detail = ", ".join(f"{k} +{v}"
                               for k, v in sorted(leaks.items()))
            lines.append(f"{name}: LEAKING over {cycles} cycles — "
                         f"{detail}")
        else:
            lines.append(f"{name}: clean over {cycles} cycles")
    return "\n".join(lines)


__all__ = (
    "AuditError",
    "DEFAULT_BASELINE",
    "DEFAULT_CYCLES",
    "ENTRY_POINTS",
    "MANIFEST_VERSION",
    "RESOURCES",
    "SETTLE_SEC",
    "diff_manifests",
    "format_text",
    "load_manifest",
    "run_audit",
    "snapshot",
    "write_manifest",
)
