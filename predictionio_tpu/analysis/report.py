"""Finding output formats for ``ptpu check``.

``text`` (the default, one ``path:line:col: rule: message`` per line)
stays the human surface; this module adds:

- ``json`` — a stable machine shape for scripting
  (``{"findings": [...], "count": N}``).
- ``sarif`` — SARIF 2.1.0, the format GitHub code scanning ingests, so
  a CI run of ``ptpu check --format sarif`` annotates the PR diff with
  each finding at its exact line (upload with
  ``github/codeql-action/upload-sarif``). Interprocedural findings
  carry their call chain as ``relatedLocations`` — the code-scanning
  UI walks from the hot call site down to the helper's direct
  violation.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence

from .core import Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def findings_to_json(findings: Sequence[Finding]) -> str:
    def one(f: Finding) -> dict:
        d = {"rule": f.rule, "path": f.path, "line": f.line,
             "col": f.col, "message": f.message}
        if f.related:
            d["related"] = [{"path": p, "line": ln, "note": note}
                            for p, ln, note in f.related]
        return d

    return json.dumps({
        "count": len(findings),
        "findings": [one(f) for f in findings],
    }, indent=2, sort_keys=True)


def findings_to_sarif(findings: Sequence[Finding],
                      rules: Dict[str, object]) -> str:
    """SARIF run: every registry rule is declared (so suppressed-to-
    zero still uploads a valid catalogue) and each finding becomes a
    ``result`` anchored at its file/line/col."""
    rule_ids = sorted(set(rules) | {f.rule for f in findings})
    driver_rules: List[dict] = []
    for rid in rule_ids:
        rule = rules.get(rid)
        desc = getattr(rule, "description", rid)
        driver_rules.append({
            "id": rid,
            "shortDescription": {"text": desc},
            "helpUri": "https://github.com/predictionio-tpu/"
                       "predictionio-tpu/blob/main/docs/"
                       "static-analysis.md",
        })
    index = {rid: i for i, rid in enumerate(rule_ids)}

    def physical(path: str, line: int, col: int) -> dict:
        return {
            "artifactLocation": {
                "uri": path.replace("\\", "/"),
                "uriBaseId": "%SRCROOT%",
            },
            "region": {
                "startLine": max(line, 1),
                # SARIF columns are 1-based; ast's are 0-based
                "startColumn": col + 1,
            },
        }

    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": "error",
            "message": {"text": f.message},
            "locations": [{"physicalLocation":
                           physical(f.path, f.line, f.col)}],
        }
        if f.related:
            result["relatedLocations"] = [
                {"physicalLocation": physical(p, ln, 0),
                 "message": {"text": note}}
                for p, ln, note in f.related]
        results.append(result)
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "ptpu-check",
                "informationUri": "https://github.com/predictionio-tpu/"
                                  "predictionio-tpu",
                "rules": driver_rules,
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True)
