"""The SPMD sharding-flow rule family behind ``ptpu check``.

PRs 6/7/13 put every hot path through GSPMD — replicated/sharded
serving, shard_map'd fused kernels, mesh-wide training — and the
failure mode that taxes a mesh hardest is *silent*: when the
PartitionSpec a value carries disagrees with the spec its consumer
constrains, XLA does not raise — it inserts an all-gather or
all-to-all at the jit/shard_map boundary and the program quietly pays
ICI bandwidth for every dispatch (the dominant scaling tax of both the
ALX sharded layout, arXiv 2112.02194, and Google's ads-infra fleet
paper, arXiv 2501.10546). Four rules, pure AST like the rest of this
package; their runtime complement is ``ptpu audit-hlo``
(:mod:`.hlo_audit`), which compiles the registered entry points on a
forced 8-device mesh and diffs the *actual* collectives against a
committed golden manifest.

- ``implicit-reshard`` — a value with a known sharding (built by
  ``jax.device_put(x, NamedSharding(mesh, spec))`` or a
  ``*shard*``-named helper taking a spec argument) is passed where the
  callee — directly, or any number of helper calls away — feeds that
  parameter position into a ``shard_map`` whose ``in_specs`` pins a
  *different* spec. The boundary is a hidden collective; the finding
  carries the interprocedural chain down to the shard_map site.
  Constraints are collected as per-function **spec sinks**
  (:class:`~.core.ProjectIndex` effect summaries) so a pragma at the
  shard_map boundary blesses every caller at once (the
  ``_fused_lhs`` replicated-table contract is the canonical case).
- ``shard-map-spec-mismatch`` — ``shard_map`` / ``shard_map_compat`` /
  ``sharded`` sites whose ``in_specs`` arity disagrees with the wrapped
  function's parameter count, whose ``out_specs`` arity disagrees with
  the function's returned tuple, or whose literal axis names (specs +
  the body's lax collectives) mix axes of *different* declared meshes
  (``parallel/mesh.py`` declares the groups — ``(data, model)`` and
  ``(batch, model)``; a site using ``data`` with ``batch`` can run on
  no mesh this framework builds). Undeclared axis names are the
  (generalized) ``sharding-mismatch`` rule's job.
- ``unsharded-capture`` — a shard_map'd (or nested-jitted) function
  **closing over** an array the enclosing scope knows to be sharded:
  a closure capture enters the program replicated, i.e. an implicit
  all-gather of the full table on every dispatch, exactly when a
  row-sharded spec already exists for it. Pass it as an argument with
  a matching in_spec.
- ``missing-donation-sharded`` — ``x = step(x, …)`` where ``x`` is
  known sharded and ``step`` resolves (cross-module, through the
  project index) to a jit-decorated function that does not donate that
  slot: the un-donated buffer doubles peak HBM at exactly the scale
  where the table was sharded because it did not fit. The same-module
  case is ``missing-donation``'s job; this rule covers the boundary
  the per-module pass cannot see.

All four honor ``# ptpu: allow[rule] — justification`` pragmas and ride
``--format sarif`` and the baseline ratchet like every other rule.
``docs/static-analysis.md`` is the operator-facing reference;
``docs/parallelism.md`` documents how to read an ``audit-hlo`` diff.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (
    PRAGMA_RE,
    CheckContext,
    Finding,
    ModuleInfo,
    Witness,
    chain_related,
    chain_text,
    short_name,
)

#: canonical symbol for :func:`parallel.mesh.rows_spec` — the leading
#: axis sharded over EVERY axis of whichever mesh is in scope
ROWS_SPEC = "rows(*)"

#: canonical replicated spec
REPLICATED = "P()"

#: callables that wrap a function with pinned in/out specs
_SHARD_MAP_NAMES = {"shard_map", "shard_map_compat", "sharded"}

#: the sharding rule family (the ``pio_sharding_findings`` gauge and
#: the docs catalogue both key off this tuple)
SHARDING_RULES = (
    "implicit-reshard",
    "shard-map-spec-mismatch",
    "unsharded-capture",
    "missing-donation-sharded",
    "sharding-mismatch",
)


# ---------------------------------------------------------------------------
# PartitionSpec expression parsing → canonical spec strings
# ---------------------------------------------------------------------------

def _is_pspec_call(mod: ModuleInfo, node: ast.AST) -> bool:
    """A ``PartitionSpec(...)`` literal however it is spelled: the
    resolved dotted name, or — when the alias table cannot resolve it
    (star imports, ``jax.P``) — a bare ``P`` / ``PartitionSpec``
    callee name."""
    if not isinstance(node, ast.Call):
        return False
    resolved = mod.resolve(node.func) or ""
    if resolved == "jax.sharding.PartitionSpec":
        return True
    last = resolved.rsplit(".", 1)[-1] if resolved else ""
    if last in ("P", "PartitionSpec"):
        return True
    return isinstance(node.func, ast.Attribute) \
        and node.func.attr in ("P", "PartitionSpec")


def _is_rows_spec_call(mod: ModuleInfo, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    resolved = mod.resolve(node.func) or ""
    return resolved.rsplit(".", 1)[-1] == "rows_spec"


class _Assigns:
    """Name → value-expression chains over (module constants, one
    function's simple assignments) — the same best-effort resolution
    the kernel rules use, for following ``spec = rows_spec(mesh)``
    into ``in_specs=(P(), spec, …)``."""

    def __init__(self, mod: ModuleInfo, fn: Optional[ast.AST] = None):
        self.table: Dict[str, ast.AST] = {}
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                self.table[node.targets[0].id] = node.value
        if fn is not None:
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    self.table[node.targets[0].id] = node.value

    def follow(self, node: ast.AST, depth: int = 0) -> ast.AST:
        while isinstance(node, ast.Name) and depth < 8:
            tgt = self.table.get(node.id)
            if tgt is None or tgt is node:
                break
            node = tgt
            depth += 1
        return node


def parse_spec(mod: ModuleInfo, assigns: _Assigns,
               node: Optional[ast.AST]) -> Optional[str]:
    """Canonical string for one PartitionSpec expression, or None when
    it cannot be pinned down. ``P()``/``P(None)`` → ``"P()"``;
    ``P("x")`` → ``"P(x)"``; ``P(("a","b"))`` → ``"P((a,b))"``;
    ``rows_spec(mesh)`` → :data:`ROWS_SPEC`. Trailing ``None`` entries
    drop (they shard nothing)."""
    if node is None:
        return None
    node = assigns.follow(node)
    if _is_rows_spec_call(mod, node):
        return ROWS_SPEC
    if not _is_pspec_call(mod, node):
        return None
    entries: List[str] = []
    for arg in node.args:
        arg = assigns.follow(arg)
        if isinstance(arg, ast.Constant) and arg.value is None:
            entries.append("None")
        elif isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            entries.append(arg.value)
        elif isinstance(arg, (ast.Tuple, ast.List)):
            names: List[str] = []
            for e in arg.elts:
                e = assigns.follow(e)
                if isinstance(e, ast.Constant) \
                        and isinstance(e.value, str):
                    names.append(e.value)
                else:
                    return None
            entries.append("(" + ",".join(names) + ")")
        else:
            return None
    if node.keywords:
        return None
    while entries and entries[-1] == "None":
        entries.pop()
    return "P(" + ",".join(entries) + ")"


def spec_axes(spec: str) -> Set[str]:
    """Axis names a canonical spec string shards over (empty for
    replicated / rows-symbolic)."""
    if spec in (ROWS_SPEC, REPLICATED):
        return set()
    inner = spec[2:-1] if spec.startswith("P(") else spec
    return {a for a in re.split(r"[(),]", inner)
            if a and a != "None"}


def normalize_spec(spec: str,
                   groups: Set[Tuple[str, ...]]) -> str:
    """Fold a literal spec that row-shards over a FULL declared mesh
    group (``P((data,model))``) into :data:`ROWS_SPEC` — that is
    exactly what ``rows_spec`` evaluates to on that mesh, and the two
    spellings must not count as a reshard."""
    if spec == ROWS_SPEC or not groups:
        return spec
    m = re.fullmatch(r"P\(\(([^()]+)\)\)", spec)
    if m:
        axes = frozenset(a.strip() for a in m.group(1).split(","))
        if any(axes == frozenset(g) for g in groups):
            return ROWS_SPEC
    return spec


def specs_conflict(a: str, b: str,
                   groups: Set[Tuple[str, ...]]) -> bool:
    return normalize_spec(a, groups) != normalize_spec(b, groups)


def _named_sharding_spec(mod: ModuleInfo, assigns: _Assigns,
                         node: ast.AST) -> Optional[str]:
    """Canonical spec of a ``NamedSharding(mesh, spec)`` expression
    (followed through simple assignments)."""
    node = assigns.follow(node)
    if not (isinstance(node, ast.Call)
            and (mod.resolve(node.func) or "").rsplit(".", 1)[-1]
            == "NamedSharding"):
        return None
    spec_node = node.args[1] if len(node.args) > 1 else None
    for kw in node.keywords:
        if kw.arg == "spec":
            spec_node = kw.value
    return parse_spec(mod, assigns, spec_node)


# ---------------------------------------------------------------------------
# shard_map site model
# ---------------------------------------------------------------------------

def _is_shard_map_call(mod: ModuleInfo, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    resolved = mod.resolve(node.func) or ""
    if resolved.rsplit(".", 1)[-1] in _SHARD_MAP_NAMES:
        return True
    return isinstance(node.func, ast.Attribute) \
        and node.func.attr in _SHARD_MAP_NAMES


class ShardMapSite:
    """One ``shard_map(fn, mesh, in_specs, out_specs)`` /
    ``shard_map_compat(…)`` call or ``@sharded(mesh, in_specs,
    out_specs)`` decoration, with its specs parsed to canonical
    strings (None where unparseable)."""

    def __init__(self, mod: ModuleInfo, assigns: _Assigns,
                 call: ast.Call, wrapped: Optional[ast.AST]):
        self.call = call
        self.mod = mod
        resolved = mod.resolve(call.func) or ""
        is_deco = resolved.rsplit(".", 1)[-1] == "sharded" or (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "sharded")
        # sharded(mesh, in, out) decorates; shard_map(fn, mesh, in, out)
        pos = list(call.args)
        if is_deco:
            pos = [None] + pos
        self.wrapped: Optional[ast.AST] = wrapped
        if self.wrapped is None and pos and pos[0] is not None:
            self.wrapped = assigns.follow(pos[0])
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        self.in_specs_node = kw.get("in_specs", pos[2]
                                    if len(pos) > 2 else None)
        self.out_specs_node = kw.get("out_specs", pos[3]
                                     if len(pos) > 3 else None)
        self.in_specs, self.in_specs_is_seq = self._parse_side(
            mod, assigns, self.in_specs_node)
        self.out_specs, self.out_specs_is_seq = self._parse_side(
            mod, assigns, self.out_specs_node)

    @staticmethod
    def _parse_side(mod: ModuleInfo, assigns: _Assigns,
                    node: Optional[ast.AST]
                    ) -> Tuple[Optional[List[Optional[str]]], bool]:
        """(per-leaf canonical specs, was-a-tuple) — None list when the
        expression is absent or unfollowable."""
        if node is None:
            return None, False
        node = assigns.follow(node)
        if isinstance(node, (ast.Tuple, ast.List)):
            return [parse_spec(mod, assigns, e)
                    for e in node.elts], True
        one = parse_spec(mod, assigns, node)
        return ([one], False) if one is not None else (None, False)

    def spec_for_arg(self, i: int) -> Optional[str]:
        if self.in_specs is None:
            return None
        if not self.in_specs_is_seq:
            return self.in_specs[0]
        return self.in_specs[i] if i < len(self.in_specs) else None


def _local_def(fn_scope: Optional[ast.AST], mod: ModuleInfo,
               expr: Optional[ast.AST]) -> Optional[ast.AST]:
    """Resolve a shard_map's wrapped expression to a FunctionDef /
    Lambda: direct, or a Name bound to a def in the enclosing function
    or at module level."""
    if expr is None:
        return None
    if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
        return expr
    if not isinstance(expr, ast.Name):
        return None
    scopes: List[ast.AST] = []
    if fn_scope is not None:
        scopes.append(fn_scope)
    scopes.append(mod.tree)
    for scope in scopes:
        for node in ast.walk(scope):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == expr.id:
                return node
    return None


def _shard_map_sites(mod: ModuleInfo, scope: ast.AST,
                     assigns: _Assigns) -> List[ShardMapSite]:
    """Every shard_map-family call within ``scope``, plus ``@sharded``
    decorations (their wrapped fn is the decorated def)."""
    sites: List[ShardMapSite] = []
    for node in ast.walk(scope):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_shard_map_call(mod, dec):
                    sites.append(ShardMapSite(mod, assigns, dec, node))
        if _is_shard_map_call(mod, node):
            sites.append(ShardMapSite(mod, assigns, node, None))
    return sites


# ---------------------------------------------------------------------------
# known-sharding local dataflow
# ---------------------------------------------------------------------------

def _mentions_sharding(mod: ModuleInfo) -> bool:
    """Cheap text gate: a module that never says ``shard`` or
    ``device_put`` can hold no shard_map boundary and no placed array
    — every rule in this family early-outs on it (the scan is
    O(repo), the AST passes are not)."""
    cached = getattr(mod, "_sharding_hint", None)
    if cached is None:
        cached = ("shard" in mod.source
                  or "device_put" in mod.source)
        mod._sharding_hint = cached
    return cached


def local_spec_map(mod: ModuleInfo, fn: ast.AST,
                   assigns: Optional[_Assigns] = None
                   ) -> Dict[str, Tuple[str, int]]:
    """Variable → (canonical spec, line) facts inside one function:
    ``x = jax.device_put(y, NamedSharding(mesh, spec))`` (sharding
    followed through assignment), and ``x = helper(…, spec, …)`` where
    the helper's name contains ``shard`` and some argument parses as a
    spec (the ``_shard`` / ``_zeros_sharded`` idiom — the framework
    funnels every explicit placement through such helpers)."""
    memo = getattr(mod, "_spec_maps", None)
    if memo is None:
        memo = mod._spec_maps = {}
    cached = memo.get(id(fn))
    if cached is not None:
        return cached
    if not _mentions_sharding(mod):
        memo[id(fn)] = {}
        return {}
    assigns = assigns or _Assigns(mod, fn)
    out: Dict[str, Tuple[str, int]] = {}

    def record(targets: List[ast.expr], spec: str, line: int) -> None:
        for t in targets:
            if isinstance(t, ast.Name):
                out[t.id] = (spec, line)

    for node in ast.walk(fn):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        resolved = mod.resolve(call.func) or ""
        last = resolved.rsplit(".", 1)[-1]
        spec: Optional[str] = None
        if last == "device_put" and len(call.args) >= 2:
            spec = _named_sharding_spec(mod, assigns, call.args[1])
        elif "shard" in last.lower() \
                and not _is_shard_map_call(mod, call):
            for arg in list(call.args) + [k.value for k in
                                          call.keywords]:
                spec = parse_spec(mod, assigns, arg)
                if spec is not None:
                    break
        if spec is not None:
            record(node.targets, spec, node.lineno)
    memo[id(fn)] = out
    return out


# ---------------------------------------------------------------------------
# spec sinks: the interprocedural constraint summaries
# (collected by core.ProjectIndex._collect_direct)
# ---------------------------------------------------------------------------

def collect_spec_sinks(fn_info) -> Dict[int, Tuple[str, Witness]]:
    """Parameter position → (canonical in_spec, witness) for params
    this function feeds into a shard_map boundary: the direct sites of
    ``implicit-reshard``. A ``# ptpu: allow[implicit-reshard]`` pragma
    at the boundary kills the sink — blessing the one documented
    boundary (e.g. ``_fused_lhs``'s replicated table) blesses every
    caller."""
    mod: ModuleInfo = fn_info.mod
    fn = fn_info.node
    params: List[str] = fn_info.params
    if not params or not _mentions_sharding(mod) \
            or "shard_map" not in mod.source \
            and "sharded" not in mod.source:
        return {}
    assigns = _Assigns(mod, fn)
    sites_by_name: Dict[str, ShardMapSite] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _is_shard_map_call(mod, node.value):
            sites_by_name[node.targets[0].id] = ShardMapSite(
                mod, assigns, node.value, None)
    out: Dict[int, Tuple[str, Witness]] = {}

    def consume(call: ast.Call, site: ShardMapSite) -> None:
        for i, a in enumerate(call.args):
            if not (isinstance(a, ast.Name) and a.id in params):
                continue
            spec = site.spec_for_arg(i)
            if spec is None:
                continue
            pos = params.index(a.id)
            if pos in out:
                continue
            probe = Finding("implicit-reshard", mod.path,
                            call.lineno, 0, "")
            if mod.suppressed(probe):
                continue
            out[pos] = (spec, Witness(
                "implicit-reshard", mod.path, call.lineno,
                call.col_offset,
                f"`{a.id}` enters a shard_map boundary with "
                f"in_spec {spec}"))

    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name) \
                and node.func.id in sites_by_name:
            consume(node, sites_by_name[node.func.id])
        elif isinstance(node.func, ast.Call) \
                and _is_shard_map_call(mod, node.func):
            consume(node, ShardMapSite(mod, assigns, node.func, None))
    return out


# ---------------------------------------------------------------------------
# rule: implicit-reshard (project-scoped)
# ---------------------------------------------------------------------------

def _function_nodes(mod: ModuleInfo
                    ) -> List[Tuple[Optional[str], ast.AST]]:
    out: List[Tuple[Optional[str], ast.AST]] = []
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append((None, node))
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    out.append((node.name, sub))
    return out


def rule_implicit_reshard(mods: Sequence[ModuleInfo],
                          ctx: CheckContext) -> List[Finding]:
    """A value with a known sharding passed — directly or through any
    helper chain — into a shard_map boundary whose ``in_specs`` pins a
    different spec: XLA inserts the collective silently. Reported at
    the call site that owns the sharded value, with the chain down to
    the boundary."""
    proj = ctx.project
    if proj is None:
        return []
    groups = ctx.declared_groups
    findings: List[Finding] = []
    for mod in mods:
        if not _mentions_sharding(mod):
            continue
        for cls, fn in _function_nodes(mod):
            specmap = local_spec_map(mod, fn)
            if not specmap:
                continue
            assigns = _Assigns(mod, fn)
            sites_by_name: Dict[str, ShardMapSite] = {}
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and _is_shard_map_call(mod, node.value):
                    sites_by_name[node.targets[0].id] = ShardMapSite(
                        mod, assigns, node.value, None)
            seen: Set[int] = set()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) \
                        or id(node) in seen:
                    continue
                # direct: calling a shard_map'd local with a var whose
                # known spec disagrees with that position's in_spec
                site = None
                if isinstance(node.func, ast.Name) \
                        and node.func.id in sites_by_name:
                    site = sites_by_name[node.func.id]
                elif isinstance(node.func, ast.Call) \
                        and _is_shard_map_call(mod, node.func):
                    site = ShardMapSite(mod, assigns, node.func, None)
                if site is not None:
                    for i, a in enumerate(node.args):
                        if not (isinstance(a, ast.Name)
                                and a.id in specmap):
                            continue
                        want = site.spec_for_arg(i)
                        have = specmap[a.id][0]
                        if want is None \
                                or not specs_conflict(have, want,
                                                      groups):
                            continue
                        seen.add(id(node))
                        findings.append(Finding(
                            "implicit-reshard", mod.path, node.lineno,
                            node.col_offset,
                            f"`{a.id}` carries sharding {have} but "
                            f"this shard_map boundary consumes it "
                            f"with in_spec {want}; XLA inserts a "
                            f"silent collective (all-gather / "
                            f"all-to-all) on every dispatch — align "
                            f"the specs, reshard explicitly, or "
                            f"pragma the boundary with a "
                            f"justification"))
                    continue
                # interprocedural: the callee (transitively) pins a
                # conflicting spec on this parameter position
                qname, bound = proj.resolve_call(mod, cls, node.func)
                callee = proj.functions.get(qname or "")
                if callee is None or not callee.spec_constraints:
                    continue
                off = 1 if bound else 0
                for i, a in enumerate(node.args):
                    if not (isinstance(a, ast.Name)
                            and a.id in specmap):
                        continue
                    want = callee.spec_constraints.get(i + off)
                    have = specmap[a.id][0]
                    if want is None \
                            or not specs_conflict(have, want, groups):
                        continue
                    seen.add(id(node))
                    hops = proj.sink_chain(callee, "spec", i + off)
                    findings.append(Finding(
                        "implicit-reshard", mod.path, node.lineno,
                        node.col_offset,
                        f"`{a.id}` carries sharding {have} but "
                        f"`{short_name(callee.qname)}` consumes it "
                        f"with spec {want} at a shard_map boundary: "
                        f"{chain_text(hops)} — XLA inserts a silent "
                        f"collective at that boundary on every "
                        f"dispatch; align the specs, reshard "
                        f"explicitly, or pragma the boundary (its "
                        f"direct site blesses all callers)",
                        related=chain_related(hops)))
                    break
    return findings


# ---------------------------------------------------------------------------
# rule: shard-map-spec-mismatch
# ---------------------------------------------------------------------------

def _return_tuple_lengths(fn: ast.AST) -> Optional[Set[int]]:
    """Lengths of the tuple literals this function returns — None when
    any return is a non-tuple expression (single output or opaque
    call: not statically checkable)."""
    lengths: Set[int] = set()
    stack = list(fn.body) if isinstance(fn.body, list) else [fn.body]
    returns: List[ast.Return] = []
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Return) and node.value is not None:
            returns.append(node)
        stack.extend(ast.iter_child_nodes(node))
    if isinstance(fn, ast.Lambda):
        returns = []
        if isinstance(fn.body, ast.Tuple):
            lengths.add(len(fn.body.elts))
            return lengths
        return None
    for r in returns:
        if isinstance(r.value, ast.Tuple):
            lengths.add(len(r.value.elts))
        else:
            return None
    return lengths or None


def _collective_axis_literals(mod: ModuleInfo,
                              fn: ast.AST) -> List[Tuple[str, ast.AST]]:
    """(axis name, node) for literal axis arguments of lax collectives
    inside ``fn``."""
    from .rules import _COLLECTIVE_AXIS_ARG, _axis_literals

    out: List[Tuple[str, ast.AST]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        pos = _COLLECTIVE_AXIS_ARG.get(mod.resolve(node.func) or "")
        if pos is None:
            continue
        args = []
        if pos < len(node.args):
            args.append(node.args[pos])
        for kw in node.keywords:
            if kw.arg in ("axis_name", "axis"):
                args.append(kw.value)
        for a in args:
            for name in _axis_literals(a):
                out.append((name, node))
    return out


def rule_shard_map_spec_mismatch(mod: ModuleInfo,
                                 ctx: CheckContext) -> List[Finding]:
    """shard_map sites whose specs cannot agree with the function they
    wrap: in_specs arity ≠ parameter count, out_specs arity ≠ returned
    tuple length, or axis names (specs + body collectives) drawn from
    *different* declared meshes — generalizing the positional-only
    PR 6 ``sharding-mismatch`` collective check to the whole
    boundary."""
    if "shard" not in mod.source:
        return []
    findings: List[Finding] = []
    assigns = _Assigns(mod)
    groups = ctx.declared_groups
    for site in _shard_map_sites(mod, mod.tree, assigns):
        call = site.call
        fn = _local_def(None, mod, site.wrapped)
        # (a) in_specs arity vs wrapped parameter count
        if fn is not None and site.in_specs is not None \
                and site.in_specs_is_seq \
                and all(s is not None for s in site.in_specs):
            a = fn.args
            n_params = len(a.posonlyargs) + len(a.args)
            has_var = a.vararg is not None
            n_required = n_params - len(a.defaults)
            n = len(site.in_specs)
            if not has_var and (n > n_params or n < n_required):
                fname = getattr(fn, "name", "<lambda>")
                findings.append(Finding(
                    "shard-map-spec-mismatch", mod.path, call.lineno,
                    call.col_offset,
                    f"in_specs carries {n} spec(s) but the wrapped "
                    f"`{fname}` takes "
                    f"{n_required if n_required == n_params else f'{n_required}..{n_params}'} "
                    f"argument(s); shard_map will reject the call at "
                    f"trace time on a real mesh — align the spec "
                    f"tuple with the signature"))
        # (b) out_specs arity vs returned tuple length
        if fn is not None and site.out_specs_node is not None:
            lengths = _return_tuple_lengths(fn)
            if lengths is not None and len(lengths) == 1:
                m = next(iter(lengths))
                if site.out_specs is not None \
                        and all(s is not None
                                for s in site.out_specs):
                    n = len(site.out_specs)
                    mismatch = (site.out_specs_is_seq and n != m) or \
                        (not site.out_specs_is_seq and m > 1)
                    if mismatch:
                        fname = getattr(fn, "name", "<lambda>")
                        findings.append(Finding(
                            "shard-map-spec-mismatch", mod.path,
                            call.lineno, call.col_offset,
                            f"out_specs carries "
                            f"{n if site.out_specs_is_seq else 'one'} "
                            f"spec(s) but `{fname}` returns a "
                            f"{m}-tuple; shard_map will reject the "
                            f"output pytree at trace time — one spec "
                            f"per returned leaf"))
        # (c) axis coherence: every literal axis this boundary touches
        # must fit on ONE declared mesh
        if groups:
            axes_used: Dict[str, ast.AST] = {}
            for side in (site.in_specs, site.out_specs):
                for s in side or []:
                    if s is not None:
                        for name in spec_axes(s):
                            axes_used.setdefault(name, call)
            if fn is not None:
                for name, node in _collective_axis_literals(mod, fn):
                    axes_used.setdefault(name, node)
            declared = {a for g in groups for a in g}
            known = {a for a in axes_used if a in declared}
            if known and not any(known <= set(g) for g in groups):
                findings.append(Finding(
                    "shard-map-spec-mismatch", mod.path, call.lineno,
                    call.col_offset,
                    f"this shard_map boundary mixes axes "
                    f"{sorted(known)} that belong to different "
                    f"declared meshes "
                    f"({sorted(tuple(g) for g in ctx.declared_groups)} "
                    f"in parallel/mesh.py); no single mesh carries "
                    f"them all — derive the specs from the mesh "
                    f"(rows_spec) or split the boundary"))
    return findings


# ---------------------------------------------------------------------------
# rule: unsharded-capture
# ---------------------------------------------------------------------------

def rule_unsharded_capture(mod: ModuleInfo,
                           ctx: CheckContext) -> List[Finding]:
    """A shard_map'd (or nested-jitted) function closing over an array
    the enclosing scope placed with a non-replicated NamedSharding:
    the capture enters the program replicated — an implicit
    all-gather of the whole table per dispatch — precisely when a
    sharded spec already exists for it. Pass it as an argument with a
    matching in_spec instead."""
    if not _mentions_sharding(mod):
        return []
    from .rules import _collect_jit, _free_loads

    findings: List[Finding] = []
    flagged: Set[Tuple[int, str]] = set()

    def check_capture(inner: ast.AST, anchor: ast.AST, kind: str,
                      specmap: Dict[str, Tuple[str, int]]) -> None:
        free = _free_loads(inner)
        for name in sorted(free & set(specmap)):
            spec, _line = specmap[name]
            if spec == REPLICATED:
                continue
            key = (id(anchor), name)
            if key in flagged:
                continue
            flagged.add(key)
            iname = getattr(inner, "name", "<lambda>")
            findings.append(Finding(
                "unsharded-capture", mod.path, anchor.lineno,
                anchor.col_offset,
                f"`{iname}` closes over `{name}`, which the enclosing "
                f"scope shards as {spec}; a closure capture enters "
                f"the {kind} replicated — an implicit all-gather of "
                f"the whole array per dispatch. Pass it as an "
                f"argument with a matching spec, or pragma with the "
                f"sizing argument"))

    for _cls, fn in _function_nodes(mod):
        assigns = _Assigns(mod, fn)
        specmap = local_spec_map(mod, fn, assigns)
        if not specmap:
            continue
        for site in _shard_map_sites(mod, fn, assigns):
            inner = _local_def(fn, mod, site.wrapped)
            if inner is not None:
                check_capture(inner, site.call, "shard_map", specmap)
    collector = _collect_jit(mod)
    for site in collector.sites:
        if site.fn is None or not site.scope_stack:
            continue
        for scope in site.scope_stack:
            specmap = local_spec_map(mod, scope)
            if specmap:
                anchor = site.call if site.call is not None else site.fn
                check_capture(site.fn, anchor, "jit trace", specmap)
    return findings


# ---------------------------------------------------------------------------
# rule: missing-donation-sharded (project-scoped)
# ---------------------------------------------------------------------------

def _jit_donations(mod: ModuleInfo, fn: ast.AST
                   ) -> Optional[Tuple[Set[int], Set[str]]]:
    """(donate_argnums, donate_argnames) of a jit-decorated def, or
    None when the def carries no jit decoration."""
    from .rules import _jit_kwargs, _statics_and_donations, _param_names

    params = _param_names(fn)
    for dec in getattr(fn, "decorator_list", []):
        name = mod.resolve(dec)
        if name == "jax.jit":
            return set(), set()
        if isinstance(dec, ast.Call):
            callee = mod.resolve(dec.func)
            if callee == "jax.jit" or (
                    callee == "functools.partial" and dec.args
                    and mod.resolve(dec.args[0]) == "jax.jit"):
                _s, dn, dnm = _statics_and_donations(
                    _jit_kwargs(dec), params)
                return dn, dnm
    return None


def rule_missing_donation_sharded(mods: Sequence[ModuleInfo],
                                  ctx: CheckContext) -> List[Finding]:
    """``x = step(x, …)`` where ``x`` is known SHARDED and ``step``
    resolves cross-module (through the project index) to a
    jit-decorated function that does not donate that slot: the old
    sharded buffer stays live across the dispatch — 2× peak HBM at
    exactly the scale where the table was sharded because one HBM
    could not hold it. The same-module case is ``missing-donation``'s;
    this rule covers the import boundary the per-module pass cannot
    see."""
    proj = ctx.project
    if proj is None:
        return []
    from .rules import _param_names

    findings: List[Finding] = []
    donations_cache: Dict[str, Optional[Tuple[Set[int], Set[str]]]] = {}
    for mod in mods:
        if not _mentions_sharding(mod):
            continue
        for cls, fn in _function_nodes(mod):
            specmap = local_spec_map(mod, fn)
            if not specmap:
                continue
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                call = node.value
                targets: Set[str] = set()
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        targets.add(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        targets |= {e.id for e in t.elts
                                    if isinstance(e, ast.Name)}
                rebound = [(i, a.id) for i, a in enumerate(call.args)
                           if isinstance(a, ast.Name)
                           and a.id in targets and a.id in specmap]
                if not rebound:
                    continue
                qname, bound = proj.resolve_call(mod, cls, call.func)
                callee = proj.functions.get(qname or "")
                if callee is None or callee.mod is mod:
                    continue  # same module: missing-donation's job
                don = donations_cache.get(callee.qname)
                if callee.qname not in donations_cache:
                    don = _jit_donations(callee.mod, callee.node)
                    donations_cache[callee.qname] = don
                if don is None:
                    continue  # not a jit boundary
                dn, dnm = don
                cparams = _param_names(callee.node)
                off = 1 if bound else 0
                for i, name in rebound:
                    pos = i + off
                    pname = cparams[pos] if pos < len(cparams) else ""
                    if pos in dn or pname in dnm:
                        continue
                    spec = specmap[name][0]
                    findings.append(Finding(
                        "missing-donation-sharded", mod.path,
                        node.lineno, node.col_offset,
                        f"sharded buffer `{name}` ({spec}) is "
                        f"re-bound to an output of jitted "
                        f"`{short_name(callee.qname)}` "
                        f"({callee.mod.path}) without donation; the "
                        f"old shards stay live across the step — 2x "
                        f"peak HBM at exactly the scale that forced "
                        f"sharding — add position {pos} to its "
                        f"donate_argnums",
                        related=((callee.mod.path,
                                  callee.node.lineno,
                                  f"`{short_name(callee.qname)}` is "
                                  f"jitted here without donating "
                                  f"`{pname or pos}`"),)))
    return findings


# ---------------------------------------------------------------------------
# pragma census (the pio_sharding_findings info gauge)
# ---------------------------------------------------------------------------

def count_sharding_pragmas(root: Optional[str] = None
                           ) -> Dict[str, int]:
    """Per-rule count of ``# ptpu: allow[...]`` pragmas naming a
    sharding-family rule under ``root`` (default: this installed
    package) — the number of accepted-and-justified sharding findings
    baked into the deployed build, exported by the engine server as
    the ``pio_sharding_findings`` info gauge so a deploy that ships
    new suppressed sharding debt is visible on /metrics. Pure text
    scan: no jax, no AST, milliseconds."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    self_dir = os.path.dirname(os.path.abspath(__file__))
    counts: Dict[str, int] = {}
    for dirpath, dirnames, names in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if not d.startswith(".")
                             and d != "__pycache__")
        if os.path.abspath(dirpath) == self_dir:
            # the checker's own sources DESCRIBE the pragmas; they are
            # not suppressed findings
            continue
        for n in sorted(names):
            if not n.endswith(".py"):
                continue
            try:
                with open(os.path.join(dirpath, n), "r",
                          encoding="utf-8") as fh:
                    text = fh.read()
            except (OSError, UnicodeDecodeError):
                continue
            for m in PRAGMA_RE.finditer(text):
                named = {r.strip() for r in m.group(1).split(",")}
                for rule in SHARDING_RULES:
                    if rule in named:
                        counts[rule] = counts.get(rule, 0) + 1
    return counts


__all__ = (
    "ROWS_SPEC",
    "SHARDING_RULES",
    "collect_spec_sinks",
    "count_sharding_pragmas",
    "parse_spec",
    "rule_implicit_reshard",
    "rule_missing_donation_sharded",
    "rule_shard_map_spec_mismatch",
    "rule_unsharded_capture",
    "specs_conflict",
)
