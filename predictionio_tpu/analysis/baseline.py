"""Finding baselines: gate CI on *no new findings*.

Turning a new rule on over a living codebase surfaces legacy findings
that can't all be fixed in the enabling PR. The baseline workflow
burns them down without blocking the gate:

- ``ptpu check --baseline findings.json --write-baseline`` records the
  current findings;
- ``ptpu check --baseline findings.json`` then fails ONLY on findings
  not in the baseline — pre-existing debt passes, regressions don't;
- as debt is paid down, re-write the baseline (shrinking it is always
  safe; CI can diff the file to prove the burn-down is monotone).

Findings are keyed by ``(path, rule, message)`` — deliberately NOT by
line, so unrelated edits that shift code don't resurrect baselined
findings. Each key carries a count: a second instance of an already-
baselined finding in the same file still fails.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence, Tuple

from .core import Finding

BASELINE_VERSION = 1

Key = Tuple[str, str, str]


def _key(f: Finding) -> Key:
    return (f.path.replace("\\", "/"), f.rule, f.message)


def _counts(findings: Sequence[Finding]) -> Dict[Key, int]:
    out: Dict[Key, int] = {}
    for f in findings:
        out[_key(f)] = out.get(_key(f), 0) + 1
    return out


def write_baseline(path: str, findings: Sequence[Finding]) -> int:
    """Persist the current findings as the accepted debt; returns how
    many entries were recorded."""
    counts = _counts(findings)
    doc = {
        "version": BASELINE_VERSION,
        "entries": [
            {"path": p, "rule": r, "message": m, "count": c}
            for (p, r, m), c in sorted(counts.items())],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return len(counts)


def load_baseline(path: str) -> Dict[Key, int]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) \
            or doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"{path}: not a ptpu check baseline (expected version "
            f"{BASELINE_VERSION})")
    out: Dict[Key, int] = {}
    for e in doc.get("entries", []):
        out[(e["path"], e["rule"], e["message"])] = int(
            e.get("count", 1))
    return out


def new_findings(findings: Sequence[Finding],
                 baseline: Dict[Key, int]) -> List[Finding]:
    """Findings beyond the baseline's per-key budget, in input order
    (the first ``count`` instances of a baselined key pass; extras and
    unknown keys fail)."""
    budget = dict(baseline)
    out: List[Finding] = []
    for f in findings:
        k = _key(f)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            out.append(f)
    return out
