"""Finding baselines: gate CI on *no new findings*.

Turning a new rule on over a living codebase surfaces legacy findings
that can't all be fixed in the enabling PR. The baseline workflow
burns them down without blocking the gate:

- ``ptpu check --baseline findings.json --write-baseline`` records the
  current findings;
- ``ptpu check --baseline findings.json`` then fails ONLY on findings
  not in the baseline — pre-existing debt passes, regressions don't —
  and prints the entries the run no longer reproduces
  (:func:`shrinkable_entries`), so paid-down debt is visible;
- ``--write-baseline`` against an EXISTING baseline auto-tightens: it
  only ever removes or decrements entries (the ratchet — CI re-runs
  it every build, so the recorded debt is monotone non-increasing);
  recording genuinely new debt (enabling a new rule) needs the
  explicit ``--baseline-grow`` flag.

Findings are keyed by ``(path, rule, message)`` — deliberately NOT by
line, so unrelated edits that shift code don't resurrect baselined
findings. Each key carries a count: a second instance of an already-
baselined finding in the same file still fails.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding

BASELINE_VERSION = 1

Key = Tuple[str, str, str]


def atomic_write_text(path: str, text: str) -> None:
    """Temp-file + fsync + rename: the PR 11 durability funnel for
    every committed baseline/manifest this package writes. A crash at
    any instant leaves either the old file or the new one — never a
    torn half-write that the next CI run reads as garbage."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(text)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _key(f: Finding) -> Key:
    return (f.path.replace("\\", "/"), f.rule, f.message)


def _counts(findings: Sequence[Finding]) -> Dict[Key, int]:
    out: Dict[Key, int] = {}
    for f in findings:
        out[_key(f)] = out.get(_key(f), 0) + 1
    return out


def write_baseline(path: str, findings: Sequence[Finding],
                   cap: Optional[Dict[Key, int]] = None) -> int:
    """Persist the current findings as the accepted debt; returns how
    many entries were recorded. With ``cap`` (the previously recorded
    baseline) the write RATCHETS: every entry is clamped to
    ``min(current, recorded)`` and keys the old baseline never held
    are dropped — the file can only shrink, never absorb new debt."""
    counts = _counts(findings)
    if cap is not None:
        counts = {k: min(c, cap[k])
                  for k, c in counts.items() if k in cap}
    doc = {
        "version": BASELINE_VERSION,
        "entries": [
            {"path": p, "rule": r, "message": m, "count": c}
            for (p, r, m), c in sorted(counts.items())],
    }
    atomic_write_text(
        path, json.dumps(doc, indent=2, sort_keys=True) + "\n")
    return len(counts)


def load_baseline(path: str) -> Dict[Key, int]:
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) \
            or doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"{path}: not a ptpu check baseline (expected version "
            f"{BASELINE_VERSION})")
    out: Dict[Key, int] = {}
    for e in doc.get("entries", []):
        out[(e["path"], e["rule"], e["message"])] = int(
            e.get("count", 1))
    return out


def new_findings(findings: Sequence[Finding],
                 baseline: Dict[Key, int]) -> List[Finding]:
    """Findings beyond the baseline's per-key budget, in input order
    (the first ``count`` instances of a baselined key pass; extras and
    unknown keys fail)."""
    budget = dict(baseline)
    out: List[Finding] = []
    for f in findings:
        k = _key(f)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            out.append(f)
    return out


def shrinkable_entries(findings: Sequence[Finding],
                       baseline: Dict[Key, int]
                       ) -> List[Tuple[Key, int, int]]:
    """Baseline entries the current run under-fills: ``(key,
    recorded, actual)`` with ``actual < recorded`` — the debt that has
    been paid down and can ratchet out of the file (sorted for stable
    output)."""
    counts = _counts(findings)
    out = [(k, rec, counts.get(k, 0))
           for k, rec in baseline.items()
           if counts.get(k, 0) < rec]
    return sorted(out)
