"""Metric-catalog drift gate: code ↔ docs/observability.md, both ways.

Fifteen PRs of metrics were never audited against their operator-facing
catalog. This project rule cross-checks:

- **code → docs**: every ``pio_*`` family registered through the
  :class:`~predictionio_tpu.obs.registry.MetricsRegistry` API
  (``.counter("pio_…")`` / ``.gauge`` / ``.histogram`` with a literal
  name) must appear backticked in the catalog tables — an undocumented
  family is invisible to operators and to the SLO tooling that reads
  the catalog.
- **docs → code**: every backticked ``pio_*`` name in the catalog must
  occur somewhere in the scanned sources — a documented family nothing
  emits is a dashboard that silently flatlines.

Dynamically-named registrations (f-strings, variables) are skipped on
the code side; the docs side only requires the name to *occur* in
source (string literal, format template, or export tuple), so custom
render paths like the lock-metrics exporter still count. The gate is
silent unless the scanned set registers at least one ``pio_*`` family
and the catalog file exists — engine-template users running
``ptpu check`` on their own tree are unaffected.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Sequence, Set, Tuple

from .core import CheckContext, Finding, ModuleInfo

#: resolved against the repo root holding this package; tests
#: monkeypatch it to a tmp catalog
CATALOG_PATH = os.path.join(
    os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))),
    "docs", "observability.md")

_REGISTRY_METHODS = {"counter", "gauge", "histogram"}
_NAME_RE = re.compile(r"\bpio_[a-z0-9_]+\b")
_DOC_NAME_RE = re.compile(r"`(pio_[a-z0-9_]+)")

#: ``pio_*`` literals that are event-store vocabulary, not metric
#: families (data/event.py reserved names)
_NON_METRIC = {"pio_pr", "pio_stream", "pio_traceparent", "pio_data",
               "pio_dashboard_session"}


def registered_families(mod: ModuleInfo
                        ) -> List[Tuple[str, int]]:
    """(family, line) for every literal-named registry registration in
    one module."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _REGISTRY_METHODS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        name = node.args[0].value
        if name.startswith("pio_"):
            out.append((name, node.args[0].lineno))
    return out


def documented_families(text: str) -> Dict[str, int]:
    """Backticked ``pio_*`` names in the catalog → first line seen."""
    out: Dict[str, int] = {}
    for i, line in enumerate(text.splitlines(), 1):
        for m in _DOC_NAME_RE.finditer(line):
            name = m.group(1)
            if name.endswith("_"):
                continue  # `pio_lane_*`-style prefix prose, not a row
            out.setdefault(name, i)
    return out


def rule_metric_catalog_drift(mods: Sequence[ModuleInfo],
                              ctx: CheckContext) -> List[Finding]:
    registered: List[Tuple[str, str, int]] = []  # (name, path, line)
    mentioned: Set[str] = set()
    for mod in mods:
        if "pio_" not in mod.source:
            continue
        mentioned |= set(_NAME_RE.findall(mod.source))
        for name, line in registered_families(mod):
            registered.append((name, mod.path, line))
    if not registered or not os.path.exists(CATALOG_PATH):
        return []
    try:
        with open(CATALOG_PATH, encoding="utf-8") as f:
            documented = documented_families(f.read())
    except OSError:
        return []
    doc_display = os.path.join("docs", "observability.md")
    findings: List[Finding] = []
    seen: Set[str] = set()
    for name, path, line in registered:
        if name in documented or name in seen:
            continue
        seen.add(name)
        findings.append(Finding(
            "metric-catalog-drift", path, line, 0,
            f"metric family `{name}` is registered here but missing "
            f"from {doc_display} — undocumented series are invisible "
            f"to operators and to the SLO catalog; add a table row "
            f"(Series/Type/Labels/Meaning)"))
    for name, line in sorted(documented.items()):
        if name in mentioned or name in _NON_METRIC:
            continue
        findings.append(Finding(
            "metric-catalog-drift", doc_display, line, 0,
            f"metric family `{name}` is documented in the catalog "
            f"but never occurs in the scanned sources — a dashboard "
            f"reading it flatlines silently; delete the row or "
            f"restore the emitter"))
    return findings


__all__ = ["CATALOG_PATH", "documented_families",
           "registered_families", "rule_metric_catalog_drift"]
