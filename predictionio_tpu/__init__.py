"""predictionio_tpu — a TPU-native ML server framework.

A brand-new framework with the capability surface of Apache PredictionIO
(event-collection server + pluggable storage, DASE engine templates,
train/deploy/eval/batch-predict lifecycle, CLI) on an idiomatic JAX/XLA
substrate: algorithms are pure functions over pytrees, training is sharded
over a `jax.sharding.Mesh` with collectives compiled by XLA over ICI/DCN,
models persist via Orbax-style checkpoints, and serving keeps models
TPU-resident with batched jit dispatch.
"""

__version__ = "0.1.0"

from .data.datamap import DataMap, PropertyMap
from .data.event import Event
from .data.bimap import BiMap

__all__ = ["DataMap", "PropertyMap", "Event", "BiMap", "__version__"]
