"""Micro-batch fold-in assembly: events → per-entity re-solves.

Turns a batch of freshly consumed events into a NEW serving model:

1. project events to ``(user, item, rating)`` triples with the same
   event→rating weighting the batch DataSource uses (``rate`` reads the
   rating property, ``buy`` implies 4.0, custom maps supported);
2. split the touched entities into existing users (fold-in), new users
   and new items (cold-start insertion);
3. re-fetch each affected entity's FULL history from the event store —
   the correctness move that makes fold-in idempotent under replay (a
   row is a pure function of its history and the fixed opposite
   factors, not of how many times the trainer saw an event);
4. deduplicate repeated (user, item) pairs last-write-wins
   (:func:`~predictionio_tpu.models.als.dedupe_pairs`) so bursts don't
   multiply implicit confidence;
5. solve through :func:`~predictionio_tpu.models.als.fold_in_rows` —
   the jitted device path sharing ``_lhs_fn``/the fused-Gramian
   machinery with the batch trainer — and assemble the updated model
   functionally (the old binding keeps serving until the swap).

New items solve first (against known users), then user rows solve
against the item table that already includes them — so a brand-new
user's first event on a brand-new item lands both rows in one pass.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..data.event import Event
from ..data.storage.base import EventFilter
from ..models.als import (
    ALSModel,
    apply_row_updates,
    dedupe_pairs,
    extend_factor_rows,
    fixed_gramian,
    fold_in_rows,
    table_host_f32,
)

log = logging.getLogger(__name__)

__all__ = ["FoldInReport", "project_ratings", "fold_in_events",
           "DEFAULT_EVENT_WEIGHTS"]

#: event → rating projection, matching RecommendationDataSource's
#: default (None ⇒ read the ``rating`` property)
DEFAULT_EVENT_WEIGHTS: Dict[str, Optional[float]] = {"rate": None,
                                                     "buy": 4.0}


@dataclass
class FoldInReport:
    """What one fold-in pass did — the trainer's metrics/drift input."""

    events_relevant: int = 0
    users_updated: int = 0
    users_inserted: int = 0
    items_inserted: int = 0
    #: mean |u·v − r| over the batch triples AFTER the solve,
    #: normalized by the batch's rating scale — the fold-in residual
    #: the DriftMonitor tracks (None when nothing was solvable)
    residual: Optional[float] = None
    #: projected rating values of the batch (drift's distribution input)
    values: List[float] = field(default_factory=list)
    solve_seconds: float = 0.0


def project_ratings(events: Sequence[Event],
                    weights: Optional[Dict[str, Optional[float]]] = None
                    ) -> List[Tuple[str, str, float]]:
    """``(user_key, item_key, rating)`` triples from raw events, in
    event order; events outside the weight map, without a target item,
    or with an unreadable rating are skipped (counted by the caller via
    the length delta)."""
    weights = DEFAULT_EVENT_WEIGHTS if weights is None else weights
    out: List[Tuple[str, str, float]] = []
    for e in events:
        if e.event not in weights or e.entity_type != "user" \
                or not e.target_entity_id:
            continue
        w = weights[e.event]
        if w is None:
            try:
                w = float(e.properties["rating"])
            except (KeyError, TypeError, ValueError):
                continue
        out.append((e.entity_id, e.target_entity_id, float(w)))
    return out


def _entity_history(storage, app_id: int, channel_id, entity_id: str,
                    event_names: Sequence[str], by_item: bool = False
                    ) -> List[Event]:
    """One entity's full rating history, oldest first. ``by_item``
    scans by target entity (item histories have no indexed column —
    a full-filter scan; cold items are rare and their history short)."""
    if by_item:
        filt = EventFilter(entity_type="user",
                           event_names=list(event_names),
                           target_entity_type="item",
                           target_entity_id=entity_id)
    else:
        filt = EventFilter(entity_type="user", entity_id=entity_id,
                           event_names=list(event_names),
                           target_entity_type="item")
    return list(storage.events().find(app_id, channel_id, filt))


def _pack_histories(triples: List[Tuple[int, float]], max_history: int
                    ) -> Tuple[np.ndarray, np.ndarray, int]:
    """One row's deduped ``(col, value)`` list → fixed arrays, keeping
    the MOST RECENT ``max_history`` entries under skew."""
    if len(triples) > max_history:
        triples = triples[-max_history:]
    idx = np.fromiter((c for c, _ in triples), dtype=np.int32,
                      count=len(triples))
    val = np.fromiter((v for _, v in triples), dtype=np.float32,
                      count=len(triples))
    return idx, val, len(triples)


def _solve_side(model: ALSModel, side: str,
                rows: List[Tuple[str, List[Tuple[int, float]]]],
                max_history: int, G=None) -> Tuple[List[str], np.ndarray]:
    """Batch-solve one side's rows from their (col_idx, value) lists.
    Returns (keys, [B, rank] rows); empty-history rows solve to ~0 via
    the regularized system (count 0 ⇒ b = 0)."""
    keys = [k for k, _ in rows]
    if not keys:
        return keys, np.zeros((0, model.params.rank), np.float32)
    L = max(1, max(len(t) for _, t in rows))
    L = min(L, max_history)
    B = len(rows)
    idx = np.zeros((B, L), dtype=np.int32)
    val = np.zeros((B, L), dtype=np.float32)
    cnt = np.zeros(B, dtype=np.int32)
    for b, (_, triples) in enumerate(rows):
        i, v, n = _pack_histories(triples, L)
        idx[b, :n] = i
        val[b, :n] = v
        cnt[b] = n
    fixed = model.item_factors if side == "user" else model.user_factors
    solved = fold_in_rows(fixed, idx, val, cnt, model.params, G=G)
    return keys, solved


def fold_in_events(model: ALSModel, events: Sequence[Event], storage,
                   app_id: int, channel_id=None,
                   weights: Optional[Dict[str, Optional[float]]] = None,
                   max_history: int = 512,
                   G=None) -> Tuple[ALSModel, FoldInReport]:
    """Fold a consumed event batch into ``model``; returns the NEW
    model plus a :class:`FoldInReport`. The input model is never
    mutated — callers swap the result into the serving binding
    atomically. ``G`` optionally carries the cached fixed-side Gramian
    for implicit models (:func:`~predictionio_tpu.models.als.fixed_gramian`,
    valid until the item table changes)."""
    report = FoldInReport()
    weights = DEFAULT_EVENT_WEIGHTS if weights is None else weights
    triples = project_ratings(events, weights)
    report.events_relevant = len(triples)
    if not triples:
        return model, report
    report.values = [v for _, _, v in triples]
    t0 = time.monotonic()
    event_names = list(weights)

    touched_users = list(dict.fromkeys(u for u, _, _ in triples))
    touched_items = list(dict.fromkeys(i for _, i, _ in triples))
    new_items = [i for i in touched_items
                 if model.item_ids is None or i not in model.item_ids]

    # -- cold-start items first: their rows must exist before user
    # rows solve against the item table -------------------------------------
    if new_items:
        item_rows: List[Tuple[str, List[Tuple[int, float]]]] = []
        for ikey in new_items:
            hist = project_ratings(
                _entity_history(storage, app_id, channel_id, ikey,
                                event_names, by_item=True), weights)
            u, _, v = dedupe_pairs(
                np.array([model.user_ids.get(uu, -1) if model.user_ids
                          else -1 for uu, _, _ in hist], dtype=np.int64),
                np.zeros(len(hist), dtype=np.int64),
                np.array([vv for _, _, vv in hist], dtype=np.float32))
            # only KNOWN users contribute to a new item's row; the
            # unknown ones get their own row solved below, against a
            # table that already includes this item
            known = [(int(uu), float(vv)) for uu, vv in zip(u, v)
                     if uu >= 0]
            item_rows.append((ikey, known))
        keys, solved = _solve_side(model, "item", item_rows, max_history)
        model = extend_factor_rows(model, "item", keys, solved)
        report.items_inserted = len(keys)
        G = None  # the item table changed: a cached implicit Gramian
        # over the old table no longer matches
    if model.params.implicit_prefs and G is None:
        G = fixed_gramian(model.item_factors, model.params)

    # -- user rows: existing fold-in + cold-start insertion ------------------
    user_rows: List[Tuple[str, List[Tuple[int, float]]]] = []
    for ukey in touched_users:
        hist = project_ratings(
            _entity_history(storage, app_id, channel_id, ukey,
                            event_names), weights)
        items = np.array([model.item_ids.get(ii, -1) if model.item_ids
                          else -1 for _, ii, _ in hist], dtype=np.int64)
        vals = np.array([vv for _, _, vv in hist], dtype=np.float32)
        rows_u = np.zeros(len(hist), dtype=np.int64)
        _, items_d, vals_d = dedupe_pairs(rows_u, items, vals)
        known = [(int(ii), float(vv)) for ii, vv in zip(items_d, vals_d)
                 if ii >= 0]
        user_rows.append((ukey, known))
    keys, solved = _solve_side(model, "user", user_rows, max_history, G=G)
    existing_idx, existing_rows = [], []
    new_keys, new_rows = [], []
    for k, row in zip(keys, solved):
        uidx = model.user_ids.get(k) if model.user_ids else None
        if uidx is None:
            new_keys.append(k)
            new_rows.append(row)
        else:
            existing_idx.append(int(uidx))
            existing_rows.append(row)
    if existing_idx:
        model = apply_row_updates(model, "user",
                                  np.asarray(existing_idx),
                                  np.asarray(existing_rows))
        report.users_updated = len(existing_idx)
    if new_keys:
        model = extend_factor_rows(model, "user", new_keys,
                                   np.asarray(new_rows))
        report.users_inserted = len(new_keys)

    report.solve_seconds = time.monotonic() - t0
    report.residual = _batch_residual(model, triples)
    return model, report


def _batch_residual(model: ALSModel, triples) -> Optional[float]:
    """Mean |u·v − r| over the batch, normalized by max(1, |r|) scale —
    how well the folded rows explain the very events they folded. For
    implicit models the target is preference 1 on observed entries."""
    # table_host_f32 dequantizes row-quantized serving tables
    # (ISSUE 13): the residual measures what the table actually serves
    U = table_host_f32(model.user_factors)
    V = table_host_f32(model.item_factors)
    errs = []
    for ukey, ikey, r in triples:
        ui = model.user_ids.get(ukey) if model.user_ids else None
        ii = model.item_ids.get(ikey) if model.item_ids else None
        if ui is None or ii is None:
            continue
        pred = float(U[int(ui)] @ V[int(ii)])
        target = 1.0 if model.params.implicit_prefs else float(r)
        errs.append(abs(pred - target) / max(1.0, abs(target)))
    return float(np.mean(errs)) if errs else None
