"""Durable per-consumer event-log cursor, persisted through EVENTDATA.

The streaming trainer (ISSUE 10) tails the event log; its position must
survive restarts WITH the log it indexes — a cursor stored anywhere
else (a file, a model blob) can desync from the events under backup/
restore or environment cloning. So the cursor itself is an event: a
``$set`` on the reserved ``pio_stream`` entity type, written with a
FIXED explicit ``event_id`` so every save replaces the previous one
(every backend's ``insert`` upserts by id). Training reads filter
``entity_type="user"`` and the fold-in scan filters to its configured
entity type, so cursor records never leak into either.

Position semantics: the event log is totally ordered by
``(event_time, event_id-at-that-time)``. The cursor stores the last
consumed event's time plus the ids of every consumed event SHARING
that timestamp; catch-up reads ``find(start_time=position)`` (the
inclusive side) and drops the seen ids — so a restart replays exactly
the unconsumed suffix: no loss, no double-apply. (Fold-in is
idempotent anyway — rows re-solve from full history — but the cursor
contract holds without leaning on that.)

Known bound: events ingested with an ``eventTime`` EARLIER than the
cursor position (explicit backfills) are behind the cursor and are
picked up by the next full retrain, not the stream (docs/streaming.md).
"""

from __future__ import annotations

import logging
from datetime import datetime, timezone
from typing import List, Optional, Sequence

from ..data.event import Event, to_millis
from ..data.storage.base import ANY, EventFilter

log = logging.getLogger(__name__)

__all__ = ["EventCursor", "CURSOR_ENTITY_TYPE"]

#: reserved entity type carrying cursor records (data/event.py
#: whitelists it next to ``pio_pr``)
CURSOR_ENTITY_TYPE = "pio_stream"

#: epoch start — a fresh cursor consumes the whole log
_EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)


class EventCursor:
    """One consumer's durable position in one app's event log.

    Not thread-safe by itself: the owning trainer serializes
    consume→advance→save on its own loop thread.
    """

    def __init__(self, storage, app_id: int, consumer: str,
                 channel_id: Optional[int] = None):
        self.storage = storage
        self.app_id = int(app_id)
        self.channel_id = channel_id
        self.consumer = consumer
        self.position: datetime = _EPOCH
        #: ids of consumed events whose event_time == position (the
        #: tie-break set; stays tiny — ms-resolution timestamps)
        self.seen: List[str] = []
        #: block-mode row watermark: how many NON-cursor storage-order
        #: rows this consumer has already taken (see
        #: :meth:`pending_block`); independent of the event-wise
        #: time position — a consumer uses one mode or the other
        self.block_rows = 0
        self.consumed_total = 0
        self.saves = 0
        self.load()

    # -- persistence --------------------------------------------------------
    @property
    def cursor_event_id(self) -> str:
        return f"pio:stream:cursor:{self.consumer}"

    def load(self) -> bool:
        """Restore position from the persisted cursor record; False
        when none exists (fresh consumer → start of log)."""
        rec = self.storage.events().get(self.cursor_event_id, self.app_id,
                                        self.channel_id)
        if rec is None:
            return False
        props = rec.properties
        try:
            # NB: DataMap.get's second positional is a TYPE, not a
            # default — keyword `default` is the optional-field form
            self.position = datetime.fromtimestamp(
                float(props["positionMillis"]) / 1000.0, tz=timezone.utc)
            self.seen = [str(s) for s in
                         (props.get("seen", default=None) or [])]
            self.block_rows = int(props.get("blockRows", default=0))
            self.consumed_total = int(props.get("consumed", default=0))
        except (KeyError, TypeError, ValueError) as e:
            log.error("corrupt stream cursor %s: %s; restarting from "
                      "log start", self.cursor_event_id, e)
            self.position, self.seen, self.block_rows = _EPOCH, [], 0
            return False
        return True

    def save(self) -> None:
        """Upsert the cursor record (fixed event_id → replace). The
        cursor event's own event_time is pinned to the epoch so it can
        never enter its own catch-up range."""
        from ..data.datamap import DataMap

        self.storage.events().insert(
            Event(event="$set", entity_type=CURSOR_ENTITY_TYPE,
                  entity_id=self.consumer,
                  properties=DataMap(
                      {"positionMillis": to_millis(self.position),
                       "seen": list(self.seen),
                       "blockRows": self.block_rows,
                       "consumed": self.consumed_total}),
                  event_time=_EPOCH,
                  event_id=self.cursor_event_id),
            self.app_id, self.channel_id)
        self.saves += 1

    # -- reads --------------------------------------------------------------
    def pending(self, event_names: Optional[Sequence[str]] = None,
                entity_type: Optional[str] = None,
                limit: Optional[int] = None) -> List[Event]:
        """Unconsumed events after the cursor, oldest first. The
        ``start_time`` filter is inclusive, so ties at the cursor
        timestamp come back and the seen set drops the consumed ones.
        ``limit`` bounds the batch (the backend caps its scan; ties
        the cursor has partially consumed cost a few extra rows)."""
        filt = EventFilter(
            start_time=None if self.position == _EPOCH else self.position,
            entity_type=entity_type,
            event_names=list(event_names) if event_names else None,
            target_entity_type=ANY, target_entity_id=ANY,
            limit=None if limit is None else int(limit) + len(self.seen))
        seen = set(self.seen)
        out = []
        for e in self.storage.events().find(self.app_id, self.channel_id,
                                            filt):
            if e.entity_type == CURSOR_ENTITY_TYPE:
                continue  # never consume cursor records
            if e.event_id in seen:
                continue
            out.append(e)
            if limit is not None and len(out) >= limit:
                break
        return out

    def lag(self, event_names: Optional[Sequence[str]] = None,
            entity_type: Optional[str] = None, cap: int = 10_000) -> int:
        """How many unconsumed events sit behind the cursor (scan
        capped at ``cap`` — a status signal, not an exact count at
        extreme backlogs)."""
        return len(self.pending(event_names=event_names,
                                entity_type=entity_type, limit=cap))

    # -- block reads --------------------------------------------------------
    def pending_block(self, float_props: Sequence[str] = ("rating",),
                      with_props: bool = False):
        """Block-granularity consumption (the columnar-ingest
        counterpart of :meth:`pending`): the whole unconsumed suffix as
        one zero-copy :class:`~..data.columnar.ColumnarBatch` — no
        per-event ``Event`` objects on the hot fold-in path.

        Position is a ROW WATERMARK counted over NON-cursor rows of the
        backend's storage-order projection (``ordered=False``): the
        cursor record itself is an ``INSERT OR REPLACE`` upsert whose
        row can churn position on every save, so it is masked out
        BEFORE the watermark is applied — its movement can never shift
        which event rows are "new". On an append-only log in storage
        order (SQLite's ``seq``), each row is returned exactly once
        regardless of event timestamps; backends whose bulk projection
        is time-ordered inherit the same append-order bound as
        :meth:`pending` (docs/streaming.md).

        Consume, then ``advance_block(batch.n)`` + :meth:`save`."""
        import numpy as np

        full = self.storage.events().find_columnar(
            self.app_id, self.channel_id, EventFilter(),
            float_props=tuple(float_props), ordered=False,
            with_props=with_props)
        code = full.dicts.entity_types.index.get(CURSOR_ENTITY_TYPE)
        if code is None:
            idx = np.arange(full.n)
        else:
            idx = np.flatnonzero(full.entity_type != code)
        if self.block_rows > len(idx):
            # deletes/compaction shrank the log under the watermark —
            # clamp; the dropped suffix is covered by the next retrain
            log.warning("block cursor %s: watermark %d > %d log rows; "
                        "clamping", self.consumer, self.block_rows,
                        len(idx))
            self.block_rows = len(idx)
        return full.take(idx[self.block_rows:], with_props=with_props)

    def advance_block(self, n_rows: int) -> None:
        """Move the row watermark past ``n_rows`` consumed block rows."""
        self.block_rows += int(n_rows)
        self.consumed_total += int(n_rows)

    # -- writes -------------------------------------------------------------
    def advance(self, events: Sequence[Event]) -> None:
        """Move past ``events`` (consumed, oldest-first). Events at a
        NEW maximum timestamp reset the tie-break set; events tied
        with the current position extend it."""
        if not events:
            return
        max_t = max(e.event_time for e in events)
        if max_t > self.position:
            self.position = max_t
            self.seen = [e.event_id for e in events
                         if e.event_time == max_t and e.event_id]
        else:
            # all ties at (or behind) the current position: extend
            at = [e.event_id for e in events
                  if e.event_time == self.position and e.event_id]
            self.seen = list(dict.fromkeys(self.seen + at))
        self.consumed_total += len(events)

    def status(self) -> dict:
        return {
            "consumer": self.consumer,
            "position": (None if self.position == _EPOCH
                         else self.position.isoformat()),
            "seenAtPosition": len(self.seen),
            "blockRows": self.block_rows,
            "consumed": self.consumed_total,
            "saves": self.saves,
        }
