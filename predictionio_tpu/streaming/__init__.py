"""Streaming incremental training (ISSUE 10): close the event→model loop.

The subsystem that takes model freshness from retrain cadence
(~minutes) to seconds: a :class:`StreamTrainer` daemon tails the event
log behind a durable :class:`EventCursor` (persisted through EVENTDATA,
bus-woken, catch-up-correct), folds micro-batches of fresh events into
the deployed ALS model via per-entity regularized least-squares solves
against the fixed opposite factors
(:func:`~predictionio_tpu.models.als.fold_in_rows` — the same
``_lhs_fn``/fused-Gramian device path the batch trainer uses), canaries
every delta with a :class:`~predictionio_tpu.rollout.HealthPolicy`
probe, and hot-swaps updated rows into the live serving binding. A
:class:`DriftMonitor` demotes full retrains to a drift-triggered
background job. See docs/streaming.md.
"""

from .cursor import CURSOR_ENTITY_TYPE, EventCursor
from .drift import DriftMonitor
from .foldin import (
    DEFAULT_EVENT_WEIGHTS,
    FoldInReport,
    fold_in_events,
    project_ratings,
)
from .trainer import StreamConfig, StreamTrainer

__all__ = [
    "CURSOR_ENTITY_TYPE",
    "DEFAULT_EVENT_WEIGHTS",
    "DriftMonitor",
    "EventCursor",
    "FoldInReport",
    "StreamConfig",
    "StreamTrainer",
    "fold_in_events",
    "project_ratings",
]
