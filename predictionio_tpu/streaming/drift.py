"""Drift detection: when incremental quality decays, ask for a retrain.

Fold-in keeps the deployed model seconds-fresh but holds the OPPOSITE
factor table fixed — over enough distribution shift the fixed side
itself goes stale and per-row solves stop converging to what a full
retrain would produce. The monitor watches two signals:

- **fold-in residual** (EWMA of each batch's mean normalized
  |u·v − r|): how well freshly solved rows explain their own events.
  Rising residuals mean the fixed factors no longer span the new
  preferences.
- **rating-distribution shift**: a Welford baseline over the first
  consumed events vs. a sliding recent window; the score is the
  standardized mean shift (|Δmean| / baseline σ).

``score()`` is the max of both (each normalized so ~0 is healthy and
1.0 is the default retrain trigger). Past the threshold the trainer
flips ``retrain_due``, records it in the release history, and keeps
folding — incremental updates stay better than nothing while the
operator (or an ``on_retrain`` hook) schedules the full retrain. A
rebind to a fresh full retrain resets the monitor.
"""

from __future__ import annotations

import math
from typing import List, Optional

__all__ = ["DriftMonitor"]


class DriftMonitor:
    def __init__(self, threshold: float = 1.0,
                 baseline_min_samples: int = 64,
                 window: int = 512, residual_halflife: int = 16,
                 residual_scale: float = 0.5):
        self.threshold = float(threshold)
        self.baseline_min = int(baseline_min_samples)
        self.window = int(window)
        #: EWMA decay per BATCH for the residual track
        self._alpha = 1.0 - 0.5 ** (1.0 / max(residual_halflife, 1))
        #: residual at which the residual track alone reads 1.0
        self.residual_scale = float(residual_scale)
        # Welford baseline (frozen once baseline_min samples land)
        self._n = 0
        self._mean = 0.0
        self._m2 = 0.0
        self._frozen = False
        self._recent: List[float] = []
        self._residual_ewma: Optional[float] = None
        self.batches = 0

    # -- feeding -------------------------------------------------------------
    def observe(self, values: List[float],
                residual: Optional[float]) -> None:
        """One fold-in batch: its projected rating values and its
        solve residual."""
        self.batches += 1
        for v in values:
            if not self._frozen:
                self._n += 1
                d = v - self._mean
                self._mean += d / self._n
                self._m2 += d * (v - self._mean)
                if self._n >= self.baseline_min:
                    self._frozen = True
            self._recent.append(float(v))
        if len(self._recent) > self.window:
            self._recent = self._recent[-self.window:]
        if residual is not None and math.isfinite(residual):
            if self._residual_ewma is None:
                self._residual_ewma = float(residual)
            else:
                self._residual_ewma += self._alpha * (
                    float(residual) - self._residual_ewma)

    def reset(self) -> None:
        """A fresh full retrain is serving: baseline and tracks restart
        from its distribution."""
        self.__init__(threshold=self.threshold,
                      baseline_min_samples=self.baseline_min,
                      window=self.window,
                      residual_scale=self.residual_scale)

    # -- scoring -------------------------------------------------------------
    def shift_score(self) -> float:
        """|Δmean| of the recent window vs the frozen baseline, in
        baseline standard deviations (0 until both sides have
        samples)."""
        if not self._frozen or len(self._recent) < 8:
            return 0.0
        var = self._m2 / max(self._n - 1, 1)
        sigma = math.sqrt(var) if var > 1e-12 else 1.0
        recent_mean = sum(self._recent) / len(self._recent)
        return abs(recent_mean - self._mean) / sigma

    def residual_score(self) -> float:
        if self._residual_ewma is None:
            return 0.0
        return self._residual_ewma / max(self.residual_scale, 1e-9)

    def score(self) -> float:
        return max(self.shift_score(), self.residual_score())

    @property
    def retrain_due(self) -> bool:
        return self.score() >= self.threshold

    def status(self) -> dict:
        return {
            "score": round(self.score(), 4),
            "shiftScore": round(self.shift_score(), 4),
            "residualScore": round(self.residual_score(), 4),
            "residualEwma": (round(self._residual_ewma, 6)
                             if self._residual_ewma is not None else None),
            "baselineFrozen": self._frozen,
            "baselineSamples": self._n,
            "threshold": self.threshold,
            "retrainDue": self.retrain_due,
            "batches": self.batches,
        }
