"""StreamTrainer: the daemon that closes the event→model loop.

Consumes accepted ingests behind a durable :class:`~.cursor.EventCursor`
(the correctness path — catch-up is a cursor read, so no event is lost
across restarts), with the serving cache's
:class:`~predictionio_tpu.cache.bus.InvalidationBus` as the
low-latency wake signal (the same publish the event server already
makes on every accepted ingest). Each wake folds the pending
micro-batch into the bound ALS model through per-entity least-squares
solves (:mod:`.foldin` →
:func:`~predictionio_tpu.models.als.fold_in_rows`), canaries the
folded model against the serving one with a
:class:`~predictionio_tpu.rollout.HealthPolicy` probe, and hot-swaps
the updated rows into the live ``QueryServer`` binding through its
delta-apply path — invalidating cached results and pinned hot-tier
rows for exactly the touched entities.

A :class:`~.drift.DriftMonitor` watches fold-in residuals and
rating-distribution shift; past threshold it flags ``retrain_due`` (and
fires the optional ``on_retrain`` hook) — full retrains become a
drift-triggered background job instead of the freshness path.

Threading: ONE daemon loop owns consume→fold→apply→advance; the bus
callback only sets a wake event (never does work on the ingest
thread). The loop's model snapshot/swap goes through
``QueryServer.apply_stream_delta``, which re-checks the binding
identity under the server lock — a reload/promote racing a fold-in
aborts the apply and the (unadvanced) cursor retries against the new
base on the next tick.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

import numpy as np

from ..cache.bus import InvalidationBus, default_bus
from ..data.event import to_millis
from ..data.storage.base import StorageError
from ..faults import FaultError, declare, fire
from ..obs import DEFAULT_LATENCY_BOUNDS
from ..rollout.policy import ArmWindow, HealthPolicy
from ..utils.retrying import RetryPolicy, retry_call
from .cursor import EventCursor
from .drift import DriftMonitor
from .foldin import DEFAULT_EVENT_WEIGHTS, fold_in_events

log = logging.getLogger(__name__)

__all__ = ["StreamConfig", "StreamTrainer"]

F_PASS = declare("stream.pass",
                 "entry of one consume→fold→canary→apply→advance pass")

#: transient-storage retry budget for the cursor's log reads/writes
#: (bounded + backed off — docs/reliability.md): a blip in the event
#: store costs one short stall, not a failed pass; a persistent outage
#: surfaces after a finite budget and the loop's own error backoff
#: paces the next try
_STORAGE_RETRY = RetryPolicy(max_attempts=3, base_ms=25.0, cap_ms=500.0)
_STORAGE_ERRORS = (StorageError, FaultError, ConnectionError, OSError)


@dataclass
class StreamConfig:
    """Knobs of the incremental trainer (``ptpu deploy --stream*``)."""

    #: app whose event log is tailed (defaults to the engine's
    #: datasource app at start_stream time)
    app_name: str = ""
    channel_name: Optional[str] = None
    #: durable cursor identity — two trainers with the same consumer
    #: name share (and fight over) one cursor; name them apart
    consumer: str = "stream-trainer"
    #: micro-batch window: the poll fallback when no bus wake arrives
    #: (in-process ingest wakes the loop immediately)
    interval_ms: float = 500.0
    #: events consumed per fold-in pass (backlog drains at this rate)
    max_events: int = 2048
    #: per-entity history cap at fold-in assembly (most recent kept)
    max_history: int = 512
    #: event → rating projection; None = the recommendation template's
    #: default ({"rate": None, "buy": 4.0})
    event_weights: Optional[Dict[str, Optional[float]]] = None
    #: DriftMonitor trigger (docs/streaming.md)
    drift_threshold: float = 1.0
    #: touched-entity probes per canary check (0 disables the gate)
    canary_probes: int = 8
    #: which bound algorithm the deltas apply to
    algo_index: int = 0


class StreamTrainer:
    def __init__(self, server, config: Optional[StreamConfig] = None,
                 bus: Optional[InvalidationBus] = None,
                 policy: Optional[HealthPolicy] = None,
                 on_retrain: Optional[Callable[[dict], None]] = None):
        self.server = server
        self.config = config or StreamConfig()
        storage = server.ctx.storage
        app_name = self.config.app_name
        if not app_name:
            raise ValueError("StreamConfig.app_name required (the app "
                             "whose event log the trainer tails)")
        app = storage.apps().get_by_name(app_name)
        if app is None:
            raise ValueError(f"app {app_name!r} does not exist")
        self.app_id = app.id
        self.channel_id = None
        if self.config.channel_name:
            chans = storage.channels().get_by_app_id(app.id)
            match = next((c for c in chans
                          if c.name == self.config.channel_name), None)
            if match is None:
                raise ValueError(
                    f"channel {self.config.channel_name!r} does not "
                    f"exist in app {app_name!r}")
            self.channel_id = match.id
        self.weights = (dict(self.config.event_weights)
                        if self.config.event_weights
                        else dict(DEFAULT_EVENT_WEIGHTS))
        self.cursor = EventCursor(storage, self.app_id,
                                  self.config.consumer, self.channel_id)
        self.drift = DriftMonitor(threshold=self.config.drift_threshold)
        #: probe-scale gate: one window per fold-in, judged on the
        #: probe set — min_queries=1 so tiny batches still get a verdict
        self.policy = policy or HealthPolicy(min_queries=1)
        self.on_retrain = on_retrain
        self._retrain_fired = False
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._G = None          # cached implicit fixed-side Gramian
        self._base_seen = None  # full-retrain instance the cache is for
        self._last_lag = 0
        self._last_error: Optional[str] = None
        self._last_batch: dict = {}
        self.applies = 0
        self.rejects = 0
        self.events_consumed = 0
        self._register_metrics(server.metrics)
        self.bus = bus if bus is not None else default_bus()
        self.bus.subscribe(self, "on_ingest")

    # -- metrics -------------------------------------------------------------
    def _register_metrics(self, registry) -> None:
        self._m_consumed = registry.counter(
            "pio_stream_events_consumed_total",
            "Events consumed from the log by the streaming trainer")
        self._m_foldin = registry.histogram(
            "pio_stream_foldin_seconds",
            "Wall time of one fold-in pass (assembly + device solves "
            "+ delta apply)", bounds=DEFAULT_LATENCY_BOUNDS)
        self._m_freshness = registry.histogram(
            "pio_stream_freshness_seconds",
            "Event→servable freshness: ingest creation time to the "
            "moment the folded rows were serving",
            bounds=DEFAULT_LATENCY_BOUNDS)
        self._m_applies = registry.counter(
            "pio_stream_applies_total",
            "Fold-in deltas hot-swapped into the serving binding")
        self._m_rows = registry.counter(
            "pio_stream_rows_updated_total",
            "Factor rows written by fold-in, by kind "
            "(updated / user_cold / item_cold)")
        self._m_rejects = registry.counter(
            "pio_stream_canary_rejects_total",
            "Fold-in deltas the HealthPolicy probe gate refused to "
            "swap in")
        registry.gauge(
            "pio_stream_cursor_lag",
            "Unconsumed relevant events behind the durable cursor at "
            "the last pass (scan-capped)",
            fn=lambda: float(self._last_lag))
        registry.gauge(
            "pio_stream_drift_score",
            "DriftMonitor score (>= threshold flags a full retrain)",
            fn=lambda: self.drift.score())
        registry.gauge(
            "pio_stream_running",
            "1 while the streaming trainer loop is alive",
            fn=lambda: 1.0 if self.running else 0.0)

    # -- lifecycle -----------------------------------------------------------
    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "StreamTrainer":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="stream-trainer")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)

    def on_ingest(self, app_id, entity_type: str, entity_id: str,
                  event_name: str = "") -> None:
        """Bus subscriber: an accepted ingest for our app wakes the
        loop NOW (the low-latency path); anything else is covered by
        the interval poll (the correctness path). Never does work on
        the ingest thread."""
        if app_id is not None and app_id != self.app_id:
            return
        if event_name and event_name not in self.weights:
            return
        self._wake.set()

    def _run(self) -> None:
        interval = max(self.config.interval_ms, 1.0) / 1000.0
        error_streak = 0
        while not self._stop.is_set():
            self._wake.wait(timeout=interval)
            if self._stop.is_set():
                break
            self._wake.clear()
            try:
                n = self.consume_once()
                error_streak = 0
                if n >= self.config.max_events:
                    self._wake.set()  # backlog: keep draining
            except Exception as e:  # noqa: BLE001 — the loop survives
                self._last_error = str(e)
                log.exception("stream fold-in pass failed: %s", e)
                # bounded-exponential backoff on consecutive failures:
                # with the bus setting _wake on every ingest, a
                # persistently failing dependency would otherwise spin
                # this loop hot; cap keeps recovery detection prompt
                error_streak += 1
                backoff = min(5.0, 0.05 * (2 ** min(error_streak, 7)))
                self._stop.wait(backoff)

    def _advance_durable(self, events) -> None:
        """Advance + persist the cursor with the bounded storage retry:
        a transient store blip must not strand the cursor behind events
        the model already absorbed (the next pass would re-fold them —
        idempotent, but wasted device work)."""
        self.cursor.advance(events)
        retry_call(self.cursor.save, policy=_STORAGE_RETRY,
                   retry_on=_STORAGE_ERRORS)

    def _begin_pass_trace(self, events):
        """Open the fold-in pass's trace (ISSUE 12, docs/tracing.md),
        ADOPTING the trace id the event server stamped into the first
        traced event (``pio_traceparent``) — the ingest request, this
        fold-in, and the hot-swap that serves it become ONE trace, so
        ``/trace.json?id=<ingest trace id>`` shows event→servable end
        to end. Other events' trace ids ride along as a ``links``
        attribute."""
        tracer = getattr(self.server, "tracer", None)
        if tracer is None:
            return None
        parents = []
        for e in events:
            tp = e.properties.get("pio_traceparent", default=None)
            if tp:
                parents.append(str(tp))
        trace = tracer.begin(
            "stream.foldin", traceparent=parents[0] if parents else None,
            consumer=self.config.consumer, events=len(events))
        if len(parents) > 1:
            from ..obs.trace import parse_traceparent

            links = []
            for tp in parents[1:]:
                parsed = parse_traceparent(tp)
                if parsed and parsed[0] != trace.trace_id:
                    links.append(parsed[0])
            if links:
                trace.set_attr("links", sorted(set(links))[:32])
        return trace

    def _finish_pass_trace(self, trace, outcome: str, **attrs) -> None:
        tracer = getattr(self.server, "tracer", None)
        if trace is None or tracer is None:
            return
        trace.set_attr("outcome", outcome)
        for k, v in attrs.items():
            trace.set_attr(k, v)
        # applied/rejected passes are ALWAYS retained ("stream"): they
        # are rare, and each is the serving-side half of some ingest
        # trace; irrelevant-event passes go through the normal policy
        force = "stream" if outcome in ("applied", "rejected") else None
        tracer.finish(trace, force_reason=force)

    # -- one pass ------------------------------------------------------------
    def consume_once(self) -> int:
        """One consume→fold→canary→apply→advance pass; returns how
        many events were consumed (0 = nothing pending or the apply
        lost a rebind race and will retry)."""
        fire(F_PASS, consumer=self.config.consumer)
        t_consume0 = time.monotonic()
        events = retry_call(
            self.cursor.pending, event_names=list(self.weights),
            entity_type="user", limit=self.config.max_events,
            policy=_STORAGE_RETRY, retry_on=_STORAGE_ERRORS)
        self._last_lag = len(events)
        if not events:
            return 0
        t0 = time.monotonic()
        trace = self._begin_pass_trace(events)
        if trace is not None:
            trace.add_span("consume", t_consume0, t0,
                           events=len(events))
        snap = self.server.stream_snapshot(self.config.algo_index)
        if snap is None:
            self._finish_pass_trace(trace, "no-foldable-model")
            return 0  # no foldable model bound (non-ALS algorithm)
        base_instance, model = snap
        if base_instance != self._base_seen:
            # a new full retrain is serving: its distribution is the
            # new baseline and any cached Gramian is for dead factors
            self._base_seen = base_instance
            self._G = None
            self._retrain_fired = False
            self.drift.reset()
        t_fold0 = time.monotonic()
        new_model, report = fold_in_events(
            model, events, self.server.ctx.storage, self.app_id,
            channel_id=self.channel_id, weights=self.weights,
            max_history=self.config.max_history, G=self._G)
        if trace is not None:
            trace.set_attr("baseInstanceId", base_instance)
            trace.add_span("fold_in", t_fold0, time.monotonic(),
                           usersUpdated=report.users_updated,
                           usersInserted=report.users_inserted,
                           itemsInserted=report.items_inserted)
        if model.params.implicit_prefs and report.items_inserted == 0 \
                and self._G is None:
            from ..models.als import fixed_gramian

            # amortize the fixed-side Gramian across batches that
            # didn't change the item table
            self._G = fixed_gramian(new_model.item_factors,
                                    new_model.params)
        elif report.items_inserted:
            self._G = None
        self.drift.observe(report.values, report.residual)
        touched = sorted({e.entity_id for e in events
                          if e.entity_type == "user"})
        if report.events_relevant == 0:
            # nothing projectable (e.g. unrelated event names that
            # slipped the filter): just move the cursor past them
            self._advance_durable(events)
            self._finish_pass_trace(trace, "no-relevant-events")
            return len(events)
        t_canary0 = time.monotonic()
        verdict = self._canary_check(model, new_model, touched)
        if trace is not None:
            trace.add_span("canary", t_canary0, time.monotonic(),
                           probes=min(len(touched),
                                      self.config.canary_probes),
                           action=(verdict.action if verdict is not None
                                   else "skipped"))
        if verdict is not None and verdict.action == "rollback":
            # refuse the delta, move on (retrying the same solve
            # yields the same rows), and escalate to the drift lane —
            # repeated probe failures are exactly "incremental quality
            # decayed"
            self.rejects += 1
            self._m_rejects.inc()
            self._record_release("stream-reject", base_instance,
                                 verdict.reason)
            self._advance_durable(events)
            self._maybe_retrain()
            self._finish_pass_trace(trace, "rejected",
                                    reason=verdict.reason)
            return len(events)
        t_swap0 = time.monotonic()
        applied = self.server.apply_stream_delta(
            self.config.algo_index, new_model, touched,
            base_instance_id=base_instance,
            rows_updated=report.users_updated,
            rows_inserted=report.users_inserted + report.items_inserted)
        if trace is not None:
            trace.add_span("hot_swap", t_swap0, time.monotonic(),
                           applied=applied,
                           touchedEntities=len(touched))
        if not applied:
            # the binding moved under us (reload/promote): nothing
            # consumed — the next pass re-folds against the new base
            self._wake.set()
            self._finish_pass_trace(trace, "rebind-race")
            return 0
        t_adv0 = time.monotonic()
        self._advance_durable(events)
        if trace is not None:
            trace.add_span("advance", t_adv0, time.monotonic())
        dt = time.monotonic() - t0
        now_ms = time.time() * 1000.0
        for e in events:
            fresh = max(0.0, (now_ms - to_millis(e.creation_time))
                        / 1000.0)
            self._m_freshness.observe(fresh)
        self.events_consumed += len(events)
        self.applies += 1
        self._m_consumed.inc(len(events))
        self._m_applies.inc()
        self._m_foldin.observe(dt)
        self._m_rows.labels(kind="updated").inc(report.users_updated)
        if report.users_inserted:
            self._m_rows.labels(kind="user_cold").inc(
                report.users_inserted)
        if report.items_inserted:
            self._m_rows.labels(kind="item_cold").inc(
                report.items_inserted)
        self._last_batch = {
            "events": len(events),
            "relevant": report.events_relevant,
            "usersUpdated": report.users_updated,
            "usersInserted": report.users_inserted,
            "itemsInserted": report.items_inserted,
            "residual": report.residual,
            "foldinMs": round(dt * 1000, 3),
        }
        self._finish_pass_trace(trace, "applied",
                                foldinMs=round(dt * 1000, 3),
                                generation=self.applies)
        self._maybe_retrain()
        return len(events)

    def _maybe_retrain(self) -> None:
        if not self.drift.retrain_due or self._retrain_fired:
            return
        self._retrain_fired = True  # once per base model
        status = self.drift.status()
        log.warning("stream drift %.3f passed threshold %.3f: full "
                    "retrain due", status["score"], status["threshold"])
        self._record_release(
            "retrain-due", self._base_seen or "",
            f"drift score {status['score']} >= {status['threshold']}")
        if self.on_retrain is not None:
            try:
                self.on_retrain(status)
            except Exception as e:  # noqa: BLE001 — the hook is advisory
                log.error("on_retrain hook failed: %s", e)

    def _record_release(self, action: str, instance_id: str,
                        reason: str) -> None:
        try:
            self.server.releases.record(action, instance_id=instance_id,
                                        actor=f"stream-trainer:"
                                              f"{self.config.consumer}",
                                        reason=reason[:500])
        except Exception as e:  # noqa: BLE001 — history is best-effort
            log.error("release history write failed on %s: %s",
                      action, e)

    # -- canary gate ---------------------------------------------------------
    def _canary_check(self, old_model, new_model, touched):
        """Probe the folded model against the serving one on the
        touched entities (plus padding from the known-user head):
        per-probe latency and failure (exception / non-finite scores /
        empty where the old model answered) build one
        :class:`ArmWindow` per arm, judged by the HealthPolicy — the
        same gate a full-release canary passes, at fold-in scale."""
        n = self.config.canary_probes
        if n <= 0:
            return None
        from ..models.als import recommend_products

        probe_keys = [u for u in touched
                      if new_model.user_ids and u in new_model.user_ids]
        probe_keys = probe_keys[:n]
        if not probe_keys:
            return None

        def probe(model, key) -> tuple:
            """(seconds, bad, answerable, n_results); ``answerable``
            False when the model has no row for the key (a cold-start
            user the OLD model can't serve — not an error, and its
            instant return must not enter the latency window)."""
            t0 = time.monotonic()
            try:
                uidx = model.user_ids.get(key)
                if uidx is None:
                    return time.monotonic() - t0, False, False, 0
                ids, scores = recommend_products(
                    model, int(uidx), min(10, model.n_items))
                bad = not np.all(np.isfinite(np.asarray(scores)))
                return time.monotonic() - t0, bad, True, len(ids)
            except Exception:  # noqa: BLE001 — counted as an error
                return time.monotonic() - t0, True, True, 0

        stable_lats, stable_q, stable_errs = [], 0, 0
        cand_lats, cand_errs = [], 0
        for key in probe_keys:
            o_dt, o_bad, o_can, o_n = probe(old_model, key)
            # probe the candidate twice and keep the faster sample: a
            # grown factor table's FIRST dispatch pays an XLA compile
            # the steady-state serving path never sees — the gate must
            # judge steady-state latency, not one-off tracing
            c_dt0, _, _, _ = probe(new_model, key)
            c_dt, c_bad, c_can, c_n = probe(new_model, key)
            cand_lats.append(min(c_dt0, c_dt))
            # a folded model answering EMPTY (or garbage) where the
            # serving one answered is a regression; a cold-start key
            # the old model can't serve only judges the candidate's
            # absolute health
            if c_bad or (not c_can) or (o_can and o_n and not c_n):
                cand_errs += 1
            if o_can:
                stable_q += 1
                stable_lats.append(o_dt)
                stable_errs += 1 if o_bad else 0
        stable = ArmWindow(
            queries=stable_q, errors=stable_errs,
            p99=max(stable_lats) if stable_lats else None)
        candidate = ArmWindow(
            queries=len(probe_keys), errors=cand_errs,
            p99=max(cand_lats) if cand_lats else None)
        return self.policy.evaluate(stable, candidate)

    # -- observability -------------------------------------------------------
    def status(self) -> dict:
        return {
            "running": self.running,
            "appName": self.config.app_name,
            "consumer": self.config.consumer,
            "intervalMs": self.config.interval_ms,
            "cursor": self.cursor.status(),
            "cursorLag": self._last_lag,
            "eventsConsumed": self.events_consumed,
            "applies": self.applies,
            "canaryRejects": self.rejects,
            "drift": self.drift.status(),
            "lastBatch": self._last_batch,
            "lastError": self._last_error,
        }
