"""Categorical naive Bayes over string features.

Behavior parity with the reference's
``e2/src/main/scala/org/apache/predictionio/e2/engine/CategoricalNaiveBayes.scala``
(train :29-81, logScore :97-135, predict :137-148): per-label log priors
``log(labelCount / total)``, per-(label, feature-slot) log likelihoods
``log(valueCount / labelCount)`` with NO smoothing, missing feature value
→ a caller-supplied default (−inf by default), unknown label → None.

TPU-first design: instead of the reference's nested
``Map[String, Array[Map[String, Double]]]``, the model holds one dense
``[n_labels, n_slots, max_vocab]`` log-likelihood tensor (absent values
hold −inf; a parallel validity mask distinguishes "absent" from a real
−inf) plus BiMap vocabularies. Single-point ``log_score``/``predict``
stay on host (they're dict lookups); ``predict_batch`` gathers the tensor
with one jit-compiled ``jnp.take_along_axis`` + reduce so classifying a
batch is a couple of fused XLA ops.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..data.bimap import BiMap

NEG_INF = float("-inf")


@dataclass(frozen=True)
class LabeledPoint:
    """A label plus one string value per feature slot."""
    label: str
    features: Tuple[str, ...]

    def __init__(self, label: str, features: Sequence[str]):
        object.__setattr__(self, "label", label)
        object.__setattr__(self, "features", tuple(features))


class CategoricalNaiveBayesModel:
    def __init__(self, labels: BiMap, vocabs: List[BiMap],
                 priors: np.ndarray, likelihoods: np.ndarray,
                 present: np.ndarray):
        #: label string → row index
        self.labels = labels
        #: per feature slot: value string → column index
        self.vocabs = vocabs
        #: [L] log priors
        self.priors = priors
        #: [L, F, Vmax] log likelihoods (−inf where absent)
        self.likelihoods = likelihoods
        #: [L, F, Vmax] bool: True where the (label, slot, value) count > 0
        self.present = present
        self.feature_count = likelihoods.shape[1]
        self._batch_scorer = None

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_batch_scorer"] = None  # jitted closure is not picklable
        return state

    def prior(self, label: str) -> float:
        return float(self.priors[self.labels[label]])

    def likelihood(self, label: str, slot: int, value: str
                   ) -> Optional[float]:
        """Log likelihood, or None when the (label, value) pair was never
        observed (parity with ``likelihoods(label)(slot)`` missing keys)."""
        li = self.labels[label]
        vi = self.vocabs[slot].get(value)
        if vi is None or not self.present[li, slot, vi]:
            return None
        return float(self.likelihoods[li, slot, vi])

    def _slot_likelihoods(self, label_idx: int, slot: int) -> List[float]:
        row = self.likelihoods[label_idx, slot]
        mask = self.present[label_idx, slot]
        return [float(v) for v in row[mask]]

    def log_score(self, point: LabeledPoint,
                  default_likelihood: Callable[[Sequence[float]], float]
                  = lambda ls: NEG_INF) -> Optional[float]:
        """Log score of (label, features); None for an unknown label.

        ``default_likelihood`` receives the label's observed likelihoods for
        the slot whenever the feature value is unseen for that label
        (reference ``logScore`` :97-115).
        """
        li = self.labels.get(point.label)
        if li is None:
            return None
        return self._score_internal(li, point.features, default_likelihood)

    def _score_internal(self, label_idx: int, features: Sequence[str],
                        default_likelihood: Callable[[Sequence[float]], float]
                        = lambda ls: NEG_INF) -> float:
        total = float(self.priors[label_idx])
        for slot, value in enumerate(features):
            vi = self.vocabs[slot].get(value)
            if vi is not None and self.present[label_idx, slot, vi]:
                total += float(self.likelihoods[label_idx, slot, vi])
            else:
                total += default_likelihood(
                    self._slot_likelihoods(label_idx, slot))
        return total

    def predict(self, features: Sequence[str]) -> str:
        """Label with the highest log score (−inf default likelihood)."""
        scores = [(self._score_internal(li, features), li)
                  for li in range(len(self.labels))]
        best = max(scores, key=lambda s: s[0])
        return self.labels.inverse[best[1]]

    def encode(self, features_batch: Sequence[Sequence[str]]) -> np.ndarray:
        """[B, F] int32 value indices; unseen values → the padded −inf col."""
        out = np.full((len(features_batch), self.feature_count),
                      self.likelihoods.shape[2] - 1, dtype=np.int32)
        for b, features in enumerate(features_batch):
            for slot, value in enumerate(features):
                vi = self.vocabs[slot].get(value)
                if vi is not None:
                    out[b, slot] = vi
        return out

    def predict_batch(self, features_batch: Sequence[Sequence[str]]
                      ) -> List[str]:
        """Vectorized argmax over labels for a batch of points (jit)."""
        import jax
        import jax.numpy as jnp

        if self._batch_scorer is None:
            lik = jnp.asarray(self.likelihoods)
            pri = jnp.asarray(self.priors)

            @jax.jit
            # ptpu: allow[recompile-hazard] — jit built once per model
            # and cached on self; lik/pri are fixed for its lifetime
            def scorer(idx):  # [B, F] → [B] best-label index
                # gather [L, F, B] then reduce slots
                g = jnp.take_along_axis(
                    lik, idx.T[None, :, :], axis=2)  # [L, F, B]
                scores = pri[:, None] + g.sum(axis=1)  # [L, B]
                return jnp.argmax(scores, axis=0)

            self._batch_scorer = scorer
        idx = jnp.asarray(self.encode(features_batch))
        best = np.asarray(self._batch_scorer(idx))
        inv = self.labels.inverse
        return [inv[int(b)] for b in best]


def train_naive_bayes(points: Sequence[LabeledPoint]
                      ) -> CategoricalNaiveBayesModel:
    """Count-based fit (reference ``CategoricalNaiveBayes.train`` :29-81).

    Counting is host-side (one pass over the log, trivially cheap); the
    output tensors are what the TPU scoring path consumes.
    """
    if not points:
        raise ValueError("cannot train naive Bayes on an empty dataset")
    n_slots = len(points[0].features)
    labels = BiMap.string_int(sorted({p.label for p in points}))
    vocabs = [BiMap.string_int(sorted({p.features[s] for p in points}))
              for s in range(n_slots)]
    n_labels = len(labels)
    # +1 padded column stays −inf / absent so encode() can point unseen
    # values at it
    vmax = max(len(v) for v in vocabs) + 1

    label_counts = np.zeros(n_labels, dtype=np.int64)
    counts = np.zeros((n_labels, n_slots, vmax), dtype=np.int64)
    for p in points:
        li = labels[p.label]
        label_counts[li] += 1
        for slot, value in enumerate(p.features):
            counts[li, slot, vocabs[slot][value]] += 1

    priors = np.log(label_counts / float(len(points)))
    present = counts > 0
    with np.errstate(divide="ignore"):
        likelihoods = np.where(
            present,
            np.log(counts / label_counts[:, None, None].astype(np.float64)),
            NEG_INF)
    return CategoricalNaiveBayesModel(labels, vocabs, priors,
                                      likelihoods, present)
