"""e2 — reusable algorithm library beside the engine templates.

Capability parity with the reference's ``e2/`` sbt module (SURVEY C28:
``e2/src/main/scala/org/apache/predictionio/e2``), re-designed for TPU:
string-keyed RDD combinators become integer-indexed vocabularies
(:class:`~predictionio_tpu.data.bimap.BiMap`) plus dense arrays scored
with jit-compiled jnp ops, so batch scoring runs on the MXU instead of a
per-record Scala closure.
"""

from .naive_bayes import (  # noqa: F401
    CategoricalNaiveBayesModel,
    LabeledPoint,
    train_naive_bayes,
)
from .markov_chain import MarkovChainModel, train_markov_chain  # noqa: F401
from .vectorizer import BinaryVectorizer  # noqa: F401
from .cross_validation import split_data  # noqa: F401
