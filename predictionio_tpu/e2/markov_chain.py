"""Top-N Markov chain over transition tallies.

Behavior parity with
``e2/src/main/scala/org/apache/predictionio/e2/engine/MarkovChain.scala``
(train :33-56, predict :69-87): each row is normalized by its FULL tally
total, then only the top-N probabilities are kept (so a row's kept mass
may sum to < 1 — reference semantics, e.g. row total 25 keeping 9/25 and
8/25). Ties keep the lower column index (the reference's stable
``sortBy`` over column-ordered entries).

TPU-first design: the model is a pair of dense ``[n_states, top_n]``
arrays (column indices + probabilities, −1/0 padding) instead of an RDD
of SparseVectors; ``predict`` is one jit-compiled gather/scatter-add —
a next-state distribution in a single fused XLA op rather than a
collect + per-row Python sum.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


class MarkovChainModel:
    def __init__(self, indices: np.ndarray, probs: np.ndarray,
                 n_states: int, top_n: int):
        #: [S, top_n] destination state per kept transition (−1 = pad)
        self.indices = indices
        #: [S, top_n] transition probability (0 at pads)
        self.probs = probs
        self.n_states = n_states
        self.n = top_n
        self._predictor = None

    def row(self, state: int):
        """Kept (destination, probability) pairs for a state, by column."""
        keep = self.indices[state] >= 0
        return list(zip(self.indices[state][keep].tolist(),
                        self.probs[state][keep].tolist()))

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_predictor"] = None  # jitted closure is not picklable
        return state

    def predict(self, current_state: Sequence[float]) -> np.ndarray:
        """Next-state distribution: currentᵀ · T over the kept entries.

        Computed in float32 (JAX default / TPU-native); expect ~1e-7
        relative error vs the float64 ``row()`` values.
        """
        import jax
        import jax.numpy as jnp

        if self._predictor is None:
            idx = jnp.asarray(np.where(self.indices < 0, 0, self.indices))
            prb = jnp.asarray(self.probs, dtype=jnp.float32)

            @jax.jit
            # ptpu: allow[recompile-hazard] — jit built once per model
            # and cached on self; idx/prb are fixed for its lifetime
            def predictor(cur):  # [S] → [S]
                contrib = prb * cur[:, None]          # [S, top_n]
                return jnp.zeros_like(cur).at[idx.reshape(-1)].add(
                    contrib.reshape(-1))

            self._predictor = predictor
        cur = jnp.asarray(np.asarray(current_state, dtype=np.float32))
        return np.asarray(self._predictor(cur))


def train_markov_chain(rows: Sequence[int], cols: Sequence[int],
                       tallies: Sequence[float], n_states: int,
                       top_n: int) -> MarkovChainModel:
    """Build the model from COO transition tallies (duplicates summed)."""
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    tallies = np.asarray(tallies, dtype=np.float64)

    # O(nnz) duplicate aggregation: unique (row, col) keys, sorted, so each
    # row's entries are contiguous and ascending by column
    keys = rows * np.int64(n_states) + cols
    uniq, inverse = np.unique(keys, return_inverse=True)
    vals = np.zeros(len(uniq), dtype=np.float64)
    np.add.at(vals, inverse, tallies)
    urows = uniq // n_states
    ucols = (uniq % n_states).astype(np.int32)
    row_ids, starts = np.unique(urows, return_index=True)
    ends = np.append(starts[1:], len(uniq))

    indices = np.full((n_states, top_n), -1, dtype=np.int32)
    probs = np.zeros((n_states, top_n), dtype=np.float64)
    for r, s0, s1 in zip(row_ids, starts, ends):
        c, v = ucols[s0:s1], vals[s0:s1]
        total = v.sum()
        # stable sort by descending tally → ties keep lower column index;
        # kept entries re-sorted by column (reference :40-44)
        kept = np.sort(np.argsort(-v, kind="stable")[:top_n])
        indices[r, :kept.size] = c[kept]
        probs[r, :kept.size] = v[kept] / total
    return MarkovChainModel(indices, probs, n_states, top_n)
