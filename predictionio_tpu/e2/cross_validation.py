"""k-fold split utility for evaluation data sources.

Behavior parity with
``e2/src/main/scala/org/apache/predictionio/e2/evaluation/CrossValidation.scala``
(``CommonHelperFunctions.splitData`` :44-75): point i lands in the test
set of fold ``i % k`` and the training set of every other fold.

Host-side by design — fold selection is index arithmetic over the event
log; the heavy lifting happens in the per-fold training that consumes the
split.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple, TypeVar

D = TypeVar("D")
TD = TypeVar("TD")
EI = TypeVar("EI")
Q = TypeVar("Q")
A = TypeVar("A")


def split_data(
        eval_k: int,
        dataset: Sequence[D],
        evaluator_info: EI,
        training_data_creator: Callable[[List[D]], TD],
        query_creator: Callable[[D], Q],
        actual_creator: Callable[[D], A],
) -> List[Tuple[TD, EI, List[Tuple[Q, A]]]]:
    """Split into eval_k (training-data, eval-info, [(query, actual)])."""
    out = []
    for fold in range(eval_k):
        training = [p for i, p in enumerate(dataset) if i % eval_k != fold]
        testing = [p for i, p in enumerate(dataset) if i % eval_k == fold]
        out.append((
            training_data_creator(training),
            evaluator_info,
            [(query_creator(p), actual_creator(p)) for p in testing],
        ))
    return out
