"""One-hot binary vectorizer over (property, value) pairs.

Behavior parity with
``e2/src/main/scala/org/apache/predictionio/e2/engine/BinaryVectorizer.scala``
(:27-63): a fixed (property, value) → column map built from training
data; vectorizing a point sets 1.0 at each known pair's column and
ignores unknown pairs. Where the reference's ``.distinct.collect`` order
is nondeterministic, this build uses first-seen order (deterministic).

TPU-first: ``to_matrix`` emits one dense float32 ``[B, F]`` batch (the
layout downstream classifiers feed the MXU), built by a single scatter.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Mapping, Sequence, Set, Tuple

import numpy as np

Pair = Tuple[str, str]


class BinaryVectorizer:
    def __init__(self, property_map: Dict[Pair, int]):
        self.property_map = dict(property_map)
        self.num_features = len(self.property_map)
        #: column order, for introspection (reference ``properties`` array)
        self.properties: List[Pair] = [
            p for p, _ in sorted(self.property_map.items(),
                                 key=lambda kv: kv[1])]

    def __repr__(self) -> str:
        pairs = ",".join(f"({k}, {v})" for k, v in self.properties)
        return f"BinaryVectorizer({self.num_features}): {pairs}"

    def to_binary(self, pairs: Sequence[Pair]) -> np.ndarray:
        """[F] float32 with 1.0 at each known pair's column."""
        vec = np.zeros(self.num_features, dtype=np.float32)
        for p in pairs:
            idx = self.property_map.get(p)
            if idx is not None:
                vec[idx] = 1.0
        return vec

    def to_matrix(self, batch: Sequence[Sequence[Pair]]) -> np.ndarray:
        """[B, F] float32 one-hot batch."""
        out = np.zeros((len(batch), self.num_features), dtype=np.float32)
        for b, pairs in enumerate(batch):
            for p in pairs:
                idx = self.property_map.get(p)
                if idx is not None:
                    out[b, idx] = 1.0
        return out

    @staticmethod
    def from_maps(maps: Iterable[Mapping[str, str]],
                  properties: Set[str]) -> "BinaryVectorizer":
        """Build from property dicts, keeping only names in ``properties``
        (reference object.apply over RDD[HashMap] :47-57)."""
        seen: Dict[Pair, int] = {}
        for m in maps:
            for k, v in m.items():
                if k in properties and (k, v) not in seen:
                    seen[(k, v)] = len(seen)
        return BinaryVectorizer(seen)

    @staticmethod
    def from_pairs(pairs: Sequence[Pair]) -> "BinaryVectorizer":
        """Build with explicit column order (reference apply(Seq) :59-62)."""
        return BinaryVectorizer({p: i for i, p in enumerate(pairs)})
