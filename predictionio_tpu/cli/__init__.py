"""``ptpu`` console — the framework's CLI.

Capability parity with the reference ``pio`` console
(``tools/src/main/scala/org/apache/predictionio/tools/console/
Console.scala:80-650`` subcommands; command objects under
``tools/.../commands/``): app/accesskey/channel management, build (a
no-op venv check here — no sbt), train, eval, deploy, undeploy,
batchpredict, eventserver, adminserver, dashboard, status, export,
import, version, template stubs.

Where the reference shells out to ``spark-submit`` (``Runner.scala:185``),
this console runs the workflow in-process against the JAX mesh — there is
no separate driver JVM to launch.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import time
from typing import Any, List, Optional

from .. import __version__
from ..data.storage.base import AccessKey, App, Channel
from ..data.storage.registry import Storage, get_storage


def _out(msg: str) -> None:
    print(msg)


def _err(msg: str) -> None:
    print(msg, file=sys.stderr)


# ---------------------------------------------------------------------------
# engine.json loading (the reference's engine variant,
# WorkflowUtils.getEngine + jValueToEngineParams)
# ---------------------------------------------------------------------------

def load_variant(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def load_engine_factory(spec: str):
    """Resolve ``module.path:callable`` (the reflective ``EngineFactory``
    lookup, ``WorkflowUtils.scala:53-88``)."""
    if ":" not in spec:
        raise SystemExit(f"engineFactory must look like "
                         f"'package.module:factory', got {spec!r}")
    mod_name, attr = spec.split(":", 1)
    try:
        mod = importlib.import_module(mod_name)
    except ImportError as e:
        raise SystemExit(f"Cannot import engine factory module "
                         f"{mod_name!r}: {e}")
    try:
        factory = getattr(mod, attr)
    except AttributeError:
        raise SystemExit(f"Module {mod_name!r} has no attribute {attr!r}")
    return factory


def engine_from_variant(variant: dict):
    factory = load_engine_factory(variant.get("engineFactory", ""))
    engine = factory() if callable(factory) else factory
    engine_params = engine.params_from_variant(variant)
    return engine, engine_params


# ---------------------------------------------------------------------------
# subcommand implementations (tools/.../commands/*.scala)
# ---------------------------------------------------------------------------

def cmd_app(args, storage: Storage) -> int:
    apps = storage.apps()
    keys = storage.access_keys()
    chans = storage.channels()
    sub = args.app_command
    if sub == "new":
        if apps.get_by_name(args.name) is not None:
            _err(f"App {args.name} already exists. Aborting.")
            return 1
        app_id = apps.insert(App(id=args.id or 0, name=args.name,
                                 description=args.description))
        if app_id is None:
            _err(f"Unable to create app {args.name} (ID conflict?). "
                 f"Aborting.")
            return 1
        storage.events().init(app_id)
        key = keys.insert(AccessKey(key=args.access_key or "",
                                    app_id=app_id, events=()))
        if key is None:
            _err(f"Unable to create access key (duplicate?). Aborting.")
            return 1
        _out(f"Initialized Event Store for this app ID: {app_id}.")
        _out(f"Created new app:")
        _out(f"      Name: {args.name}")
        _out(f"        ID: {app_id}")
        _out(f"Access Key: {key}")
        return 0
    if sub == "list":
        _out(f"{'Name':20} |   ID | Access Key")
        for a in sorted(apps.get_all(), key=lambda a: a.name):
            for k in keys.get_by_app_id(a.id) or [None]:
                key = k.key if k else ""
                allowed = (",".join(k.events) if k and k.events
                           else "(all)")
                _out(f"{a.name:20} | {a.id:4} | {key} | {allowed}")
        _out(f"Finished listing {len(apps.get_all())} app(s).")
        return 0
    if sub == "show":
        a = apps.get_by_name(args.name)
        if a is None:
            _err(f"App {args.name} does not exist. Aborting.")
            return 1
        _out(f"    App Name: {a.name}")
        _out(f"      App ID: {a.id}")
        _out(f" Description: {a.description or ''}")
        for k in keys.get_by_app_id(a.id):
            allowed = ",".join(k.events) if k.events else "(all)"
            _out(f"  Access Key: {k.key} | {allowed}")
        for c in chans.get_by_app_id(a.id):
            _out(f"     Channel: {c.name} (ID {c.id})")
        return 0
    if sub == "delete":
        a = apps.get_by_name(args.name)
        if a is None:
            _err(f"App {args.name} does not exist. Aborting.")
            return 1
        if not args.force and not _confirm(
                f"Delete app {args.name} and ALL its data?"):
            return 1
        for c in chans.get_by_app_id(a.id):
            storage.events().remove(a.id, c.id)
            chans.delete(c.id)
        storage.events().remove(a.id)
        for k in keys.get_by_app_id(a.id):
            keys.delete(k.key)
        apps.delete(a.id)
        _out(f"Deleted app {args.name}.")
        return 0
    if sub == "data-delete":
        a = apps.get_by_name(args.name)
        if a is None:
            _err(f"App {args.name} does not exist. Aborting.")
            return 1
        if not args.force and not _confirm(
                f"Delete ALL data of app {args.name}?"):
            return 1
        channel_id = None
        if args.channel:
            ch = _find_channel(storage, a, args.channel)
            if ch is None:
                _err(f"Channel {args.channel} does not exist. Aborting.")
                return 1
            channel_id = ch.id
        storage.events().remove(a.id, channel_id)
        storage.events().init(a.id, channel_id)
        _out(f"Removed Event Store for the app ID: {a.id}")
        return 0
    if sub == "channel-new":
        a = apps.get_by_name(args.name)
        if a is None:
            _err(f"App {args.name} does not exist. Aborting.")
            return 1
        if not Channel.is_valid_name(args.channel):
            _err(f"Channel name {args.channel} is invalid (1-16 "
                 f"alphanumeric/dash characters). Aborting.")
            return 1
        if any(c.name == args.channel for c in chans.get_by_app_id(a.id)):
            _err(f"Channel {args.channel} already exists. Aborting.")
            return 1
        cid = chans.insert(Channel(id=0, name=args.channel, app_id=a.id))
        storage.events().init(a.id, cid)
        _out(f"Created channel {args.channel} (ID {cid}) for app "
             f"{args.name}.")
        return 0
    if sub == "channel-delete":
        a = apps.get_by_name(args.name)
        if a is None:
            _err(f"App {args.name} does not exist. Aborting.")
            return 1
        ch = _find_channel(storage, a, args.channel)
        if ch is None:
            _err(f"Channel {args.channel} does not exist. Aborting.")
            return 1
        if not args.force and not _confirm(
                f"Delete channel {args.channel} and its data?"):
            return 1
        storage.events().remove(a.id, ch.id)
        chans.delete(ch.id)
        _out(f"Deleted channel {args.channel}.")
        return 0
    _err(f"Unknown app subcommand {sub!r}")
    return 1


def cmd_accesskey(args, storage: Storage) -> int:
    keys = storage.access_keys()
    apps = storage.apps()
    sub = args.ak_command
    if sub == "new":
        a = apps.get_by_name(args.app)
        if a is None:
            _err(f"App {args.app} does not exist. Aborting.")
            return 1
        key = keys.insert(AccessKey(key=args.key or "", app_id=a.id,
                                    events=tuple(args.events or ())))
        if key is None:
            _err("Unable to create access key (duplicate?). Aborting.")
            return 1
        _out(f"Created new access key: {key}")
        return 0
    if sub == "list":
        rows = keys.get_all()
        if args.app:
            a = apps.get_by_name(args.app)
            if a is None:
                _err(f"App {args.app} does not exist. Aborting.")
                return 1
            rows = keys.get_by_app_id(a.id)
        for k in rows:
            allowed = ",".join(k.events) if k.events else "(all)"
            _out(f"{k.key} | app {k.app_id} | {allowed}")
        _out(f"Finished listing {len(rows)} access key(s).")
        return 0
    if sub == "delete":
        keys.delete(args.key)
        _out(f"Deleted access key {args.key}.")
        return 0
    _err(f"Unknown accesskey subcommand {sub!r}")
    return 1


def _make_ctx(storage: Storage, app_name: str = ""):
    from ..controller.context import Context
    return Context(app_name=app_name, _storage=storage)


def cmd_train(args, storage: Storage) -> int:
    from ..workflow import run_train

    variant = load_variant(args.engine_json)
    engine, engine_params = engine_from_variant(variant)
    ctx = _make_ctx(storage)
    ctx = ctx.copy(skip_sanity_check=args.skip_sanity_check,
                   stop_after_read=args.stop_after_read,
                   stop_after_prepare=args.stop_after_prepare)
    instance_id = run_train(
        ctx, engine, engine_params,
        engine_id=args.engine_id or variant.get("id", "default"),
        engine_version=args.engine_version or variant.get("version", "1"),
        engine_variant=args.engine_json,
        engine_factory=variant.get("engineFactory", ""))
    if args.stop_after_read or args.stop_after_prepare:
        stage = "read" if args.stop_after_read else "prepare"
        _out(f"Workflow stopped after {stage} (instance {instance_id} "
             f"left in INIT).")
    else:
        if ctx.stage_timings:
            _out(f"Train stages: {json.dumps(ctx.stage_timings)}")
        _out(f"Training completed. Engine instance ID: {instance_id}")
    return 0


def cmd_eval(args, storage: Storage) -> int:
    from ..workflow import run_evaluation

    evaluation = load_engine_factory(args.evaluation)
    if callable(evaluation) and not hasattr(evaluation, "engine"):
        evaluation = evaluation()
    params_list = None
    if args.engine_params_generator:
        gen = load_engine_factory(args.engine_params_generator)
        if callable(gen) and not hasattr(gen, "engine_params_list"):
            gen = gen()
        params_list = list(gen.engine_params_list)
    elif getattr(evaluation, "engine_params_list", None):
        params_list = list(evaluation.engine_params_list)
    if not params_list:
        _err("No engine params to evaluate; provide an engine params "
             "generator.")
        return 1
    ctx = _make_ctx(storage)
    result = run_evaluation(
        ctx, evaluation, params_list,
        evaluation_class=args.evaluation,
        params_generator_class=args.engine_params_generator or "",
        parallelism=max(1, args.parallelism))
    _out(result.to_one_liner())
    return 0


def cmd_deploy(args, storage: Storage) -> int:
    from ..server.engineserver import ServerConfig, deploy

    variant = load_variant(args.engine_json)
    engine, engine_params = engine_from_variant(variant)
    ctx = _make_ctx(storage)
    from ..server.http import ssl_context_from

    config = ServerConfig(
        feedback=args.feedback,
        feedback_app_name=args.feedback_app_name or None,
        accesskey=args.accesskey or None,
        batching=args.batching,
        max_batch=args.max_batch,
        batch_window_ms=args.batch_window_ms,
        batch_pipeline=args.batch_pipeline,
        serving_pipeline=args.pipeline,
        queue_deadline_ms=args.queue_deadline_ms,
        assemble_workers=args.assemble_workers,
        readback_workers=args.readback_workers,
        pipeline_depth=args.pipeline_depth,
        serving_cache=args.cache,
        cache_entries=args.cache_entries,
        cache_ttl_sec=args.cache_ttl,
        feature_ttl_sec=args.feature_ttl,
        hot_entities=args.hot_entities,
        debug_locks=args.debug_locks,
        serving_mode=args.serving_mode,
        serving_quant=args.serving_quant,
        serving_topk=args.serving_topk,
        streaming=args.stream,
        stream_app_name=args.stream_app or None,
        stream_interval_ms=args.stream_interval_ms,
        stream_max_events=args.stream_max_events,
        stream_consumer=args.stream_consumer,
        stream_drift_threshold=args.stream_drift_threshold,
        stream_canary_probes=args.stream_canary_probes,
        faults=args.faults or None,
        tracing=not args.no_trace,
        trace_ring=args.trace_ring,
        trace_slow_ms=args.trace_slow_ms,
        access_log_sample=args.access_log_sample,
        profile_dir=args.profile_dir or None,
        slo_specs=args.slo_specs or None,
        slo_interval_ms=args.slo_interval_ms,
        hot_keys_k=args.hot_keys_k,
        artifact_dir=args.artifact_dir or None)
    ssl_ctx = ssl_context_from(args.cert or None, args.key or None)
    scheme = "https" if ssl_ctx else "http"
    if args.fleet_of > 1:
        # fleet deploy (ISSUE 17 + 18, docs/fleet.md,
        # docs/autoscaling.md): N replicas on consecutive ports, each
        # a full engine server, fronted by the entity-affinity query
        # router AND the fleet aggregator (merged metrics, fleet SLO,
        # cross-replica traces). With --autoscale, the replica
        # lifecycle manager + control loop grow/shrink the fleet
        # between --min-replicas and --max-replicas. The aggregator
        # holds the foreground; everything else runs in background
        # threads of this process.
        from ..fleet import FleetConfig, create_fleet_server
        from ..router import (
            Autoscaler,
            AutoscalePolicy,
            QueryRouter,
            ReplicaLifecycle,
            RouterConfig,
            create_router_server,
        )

        def _boot_replica(port: int):
            srv = deploy(
                ctx, engine, engine_params,
                engine_id=args.engine_id or variant.get("id", "default"),
                engine_version=(args.engine_version
                                or variant.get("version", "1")),
                engine_variant=args.engine_json,
                config=config, host=args.ip, port=port,
                ssl_context=ssl_ctx)
            srv.start_background()
            return srv

        servers = [_boot_replica(args.port + i)
                   for i in range(args.fleet_of)]
        for srv in servers:
            _out(f"Replica live at {scheme}://{args.ip}:{srv.port}.")
        fleet_cfg = FleetConfig(
            replicas=[f"{scheme}://127.0.0.1:{srv.port}"
                      for srv in servers],
            scrape_interval_sec=args.fleet_scrape_interval_ms / 1000.0,
            slo_specs=args.slo_specs or None,
            slo_interval_sec=args.slo_interval_ms / 1000.0,
            capacity_path=args.capacity or None,
            accesskey=args.accesskey or None)
        agg, fleet_srv = create_fleet_server(
            fleet_cfg, host=args.ip, port=args.fleet_port,
            ssl_context=ssl_ctx)
        # the router registers its pio_router_* families on the
        # aggregator's registry so they ride the fleet /metrics
        # alongside the merged replica series and pio_autoscale_*
        router = QueryRouter(
            RouterConfig(accesskey=args.accesskey or None),
            registry=agg.registry)
        router_srv = create_router_server(router, host=args.ip,
                                          port=args.router_port,
                                          ssl_context=ssl_ctx)
        router_srv.start_background()
        agg.attach_router(router)
        # the aggregator's liveness view vetoes routing candidates;
        # "unknown"/"absent" (not yet scraped) is no opinion, so a
        # fresh replica isn't vetoed during its first scrape window
        router.set_health(
            lambda name: {"up": True, "down": False}.get(
                agg.replica_health(name)))
        lifecycle = ReplicaLifecycle(
            spawn=lambda: ((lambda srv:
                            (f"{scheme}://127.0.0.1:{srv.port}",
                             srv.shutdown))(_boot_replica(0))),
            router=router, aggregator=agg,
            registry=agg.registry,
            accesskey=args.accesskey or None)
        for srv in servers:
            lifecycle.adopt(f"{scheme}://127.0.0.1:{srv.port}",
                            stop_fn=srv.shutdown)
        autoscaler = None
        if args.autoscale:
            autoscaler = Autoscaler(
                agg, lifecycle,
                AutoscalePolicy(min_replicas=args.min_replicas,
                                max_replicas=args.max_replicas),
                registry=agg.registry).start()
            agg.attach_autoscaler(autoscaler)
            _out(f"Autoscaler running: {args.min_replicas}-"
                 f"{args.max_replicas} replicas, knee model "
                 f"{'loaded' if agg.capacity_signals()['kneeQps'] else 'ABSENT'}.")
        _out(f"Query router live at "
             f"{scheme}://{args.ip}:{router_srv.port} — send "
             f"/queries.json here (entity-affinity + retry + spill).")
        _out(f"Fleet aggregator live at "
             f"{scheme}://{args.ip}:{fleet_srv.port} — merged "
             f"/metrics, /fleet.json, /route.json, /trace.json, "
             f"/hotkeys.json.")
        try:
            fleet_srv.serve_forever()
        except KeyboardInterrupt:
            _out("Shutting down.")
            if autoscaler is not None:
                autoscaler.stop()
            lifecycle.close(stop_replicas=True)
            router_srv.shutdown()
            agg.stop()
        return 0
    server = deploy(
        ctx, engine, engine_params,
        engine_id=args.engine_id or variant.get("id", "default"),
        engine_version=args.engine_version or variant.get("version", "1"),
        engine_variant=args.engine_json,
        config=config, host=args.ip, port=args.port, ssl_context=ssl_ctx)
    _out(f"Engine is deployed and running. Engine API is live at "
         f"{scheme}://{args.ip}:{server.port}.")
    _out(f"Telemetry: {scheme}://{args.ip}:{server.port}/metrics "
         f"(Prometheus) and /status.json.")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        _out("Shutting down.")
    return 0


def _server_ssl_kwargs(args) -> dict:
    import ssl as _ssl

    kw = {}
    if getattr(args, "https", False):
        ctx = _ssl.create_default_context()
        if getattr(args, "insecure", False):
            # opt-in for self-signed local certs; the accessKey rides
            # this URL, so verification stays on by default
            ctx.check_hostname = False
            ctx.verify_mode = _ssl.CERT_NONE
        kw["context"] = ctx
    return kw


def _server_call(args, path: str, method: str = "GET",
                 body: Optional[dict] = None, timeout: float = 30.0):
    """One control-plane round trip to the deployed engine server;
    returns the parsed JSON body (raises on transport errors; HTTP
    error responses raise urllib's HTTPError with the JSON body)."""
    import urllib.request

    scheme = "https" if getattr(args, "https", False) else "http"
    url = f"{scheme}://{args.ip}:{args.port}{path}"
    if getattr(args, "accesskey", ""):
        sep = "&" if "?" in url else "?"
        url += f"{sep}accessKey={args.accesskey}"
    data = json.dumps(body).encode("utf-8") if body is not None else \
        (b"" if method == "POST" else None)
    req = urllib.request.Request(url, method=method, data=data)
    with urllib.request.urlopen(req, timeout=timeout,
                                **_server_ssl_kwargs(args)) as resp:
        raw = resp.read()
    return json.loads(raw) if raw else None


def cmd_undeploy(args, storage: Storage) -> int:
    # learn which release is being taken off traffic BEFORE stopping it
    # (the undeploy must land in the release history — ISSUE 3)
    info = None
    try:
        info = _server_call(args, "/status.json")
    except Exception:  # noqa: BLE001 — liveness is checked by /stop below
        pass
    try:
        _server_call(args, "/stop", method="POST", timeout=10)
    except Exception as e:  # noqa: BLE001 — report, don't traceback
        _err(f"Cannot undeploy {args.ip}:{args.port}: {e}")
        return 1
    if info and info.get("engineId"):
        _out(f"Undeployed engine server at {args.ip}:{args.port} "
             f"(engine {info['engineId']}, release instance "
             f"{info.get('engineInstanceId', '?')}).")
        try:
            from ..rollout import ReleaseRegistry

            reg = ReleaseRegistry(
                storage, info["engineId"],
                info.get("engineVersion", "1"),
                info.get("engineVariant", "engine.json"))
            reg.record("undeploy",
                       instance_id=info.get("engineInstanceId", ""),
                       actor="ptpu undeploy",
                       reason=f"stopped {args.ip}:{args.port}")
        except Exception as e:  # noqa: BLE001 — history is best-effort
            _err(f"release history write failed: {e}")
    else:
        _out(f"Undeployed engine server at {args.ip}:{args.port}.")
    return 0


def cmd_batchpredict(args, storage: Storage) -> int:
    from ..workflow.batch_predict import run_batch_predict

    variant = load_variant(args.engine_json)
    engine, engine_params = engine_from_variant(variant)
    ctx = _make_ctx(storage)
    n = run_batch_predict(
        ctx, engine, engine_params,
        input_path=args.input, output_path=args.output,
        engine_id=args.engine_id or variant.get("id", "default"),
        engine_version=args.engine_version or variant.get("version", "1"),
        engine_variant=args.engine_json)
    _out(f"Wrote {n} prediction(s) to {args.output}.")
    return 0


def cmd_eventserver(args, storage: Storage) -> int:
    from ..server.eventserver import build_app
    from ..server.http import AppServer, ssl_context_from

    ssl_ctx = ssl_context_from(args.cert or None, args.key or None)
    server = AppServer(build_app(storage, stats=args.stats),
                       host=args.ip, port=args.port, ssl_context=ssl_ctx)
    scheme = "https" if ssl_ctx else "http"
    _out(f"Event Server is listening at {scheme}://{args.ip}:{server.port}.")
    if not args.stats:
        _out("Per-app /stats.json is OFF (enable with --stats); "
             "aggregate telemetry is always on at /metrics.")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        _out("Shutting down.")
    return 0


def cmd_storageserver(args, storage: Storage) -> int:
    """Serve this host's storage to REMOTE-backend clients (the pod
    topology: TPU hosts → storage server for events/metadata/models, no
    shared filesystem required)."""
    from ..server.http import AppServer, ssl_context_from
    from ..server.storageserver import build_app

    ssl_ctx = ssl_context_from(args.cert or None, args.key or None)
    server = AppServer(build_app(storage, secret=args.secret or None),
                       host=args.ip, port=args.port, ssl_context=ssl_ctx)
    scheme = "https" if ssl_ctx else "http"
    _out(f"Storage Server is listening at "
         f"{scheme}://{args.ip}:{server.port}. "
         f"Telemetry at /metrics.")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        _out("Shutting down.")
    return 0


def cmd_adminserver(args, storage: Storage) -> int:
    from ..server.adminserver import create_admin_server
    from ..server.http import ssl_context_from

    ssl_ctx = ssl_context_from(args.cert or None, args.key or None)
    server = create_admin_server(
        storage, host=args.ip, port=args.port,
        accesskey=args.accesskey or None, ssl_context=ssl_ctx)
    scheme = "https" if ssl_ctx else "http"
    _out(f"Admin server is listening at {scheme}://{args.ip}:{server.port}.")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        _out("Shutting down.")
    return 0


def cmd_dashboard(args, storage: Storage) -> int:
    from ..server.dashboard import create_dashboard
    from ..server.http import ssl_context_from

    ssl_ctx = ssl_context_from(args.cert or None, args.key or None)
    server = create_dashboard(
        storage, host=args.ip, port=args.port,
        accesskey=args.accesskey or None, ssl_context=ssl_ctx)
    scheme = "https" if ssl_ctx else "http"
    _out(f"Dashboard is listening at {scheme}://{args.ip}:{server.port}.")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        _out("Shutting down.")
    return 0


#: servers `start-all` supervises: name → (default port, needs_secret)
_START_ALL = {
    "eventserver": (7070, False),
    "adminserver": (7071, False),
    "dashboard": (9000, False),
    "storageserver": (7077, True),
}


def _pid_dir(args) -> str:
    d = os.path.expanduser(getattr(args, "pid_dir", "") or
                           os.environ.get("PIO_PID_DIR", "~/.ptpu"))
    os.makedirs(d, exist_ok=True)
    return d


def _pid_alive(pid: int) -> bool:
    # if the process is OUR child, reap a potential zombie first —
    # kill(pid, 0) succeeds on zombies, which would read as "alive"
    # forever when start-all and stop-all share a process (tests,
    # embedding); standalone CLIs never are the parent and the
    # waitpid is a cheap no-op error
    try:
        os.waitpid(pid, os.WNOHANG)
    except ChildProcessError:
        pass
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def cmd_start_all(args, storage: Storage) -> int:
    """``ptpu start-all`` — the ``bin/pio-start-all`` role
    (``/root/reference/bin/pio-start-all:1-30``) for bare-metal
    operators: spawn the long-running servers as daemons with pidfiles
    and per-server logs, wait for each to answer its port, report.
    Docker users get the same topology from docker/docker-compose.yml;
    this is the no-docker path."""
    import socket
    import subprocess

    d = _pid_dir(args)
    names = ["eventserver", "adminserver", "dashboard"]
    if args.with_storageserver:
        names.insert(0, "storageserver")
    started, failed = [], []
    ports = {"eventserver": args.event_port,
             "adminserver": args.admin_port,
             "dashboard": args.dash_port,
             "storageserver": args.storage_port}
    for name in names:
        port = ports[name] or _START_ALL[name][0]
        pidfile = os.path.join(d, f"{name}.pid")
        if os.path.exists(pidfile):
            try:
                old = int(open(pidfile).read().strip())
            except ValueError:
                old = -1
            if old > 0 and _pid_alive(old):
                _err(f"{name} already running (pid {old}, {pidfile}); "
                     f"run stop-all first")
                failed.append(name)
                continue
            os.unlink(pidfile)  # stale pidfile from a dead process
        cmd = [sys.executable, "-m", "predictionio_tpu.cli", name,
               "--ip", args.ip, "--port", str(port)]
        if name == "storageserver" and args.storage_secret:
            cmd += ["--secret", args.storage_secret]
        log_path = os.path.join(d, f"{name}.log")
        with open(log_path, "ab") as log_f:
            proc = subprocess.Popen(
                cmd, stdout=log_f, stderr=subprocess.STDOUT,
                start_new_session=True)  # survives this CLI's exit
        with open(pidfile, "w") as f:
            f.write(str(proc.pid))
        # wait for the port to answer (the server binds before serving)
        deadline = time.monotonic() + args.start_timeout
        up = False
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break  # died during startup; log has the reason
            try:
                with socket.create_connection(
                        ("127.0.0.1" if args.ip == "0.0.0.0"
                         else args.ip, port), timeout=1.0):
                    up = True
                    break
            except OSError:
                time.sleep(0.1)
        if up:
            # the port answering is not proof OUR child owns it: a
            # foreign listener (port collision) answers while the
            # child dies on bind-EADDRINUSE a beat later
            time.sleep(0.3)
            if proc.poll() is not None:
                up = False
        if up:
            _out(f"{name}: up on port {port} (pid {proc.pid}, "
                 f"log {log_path})")
            started.append(name)
        else:
            _err(f"{name}: failed to come up on port {port} within "
                 f"{args.start_timeout}s — see {log_path}")
            if proc.poll() is None:
                # escalate and CONFIRM death before dropping the
                # pidfile: a server stuck in native init ignores
                # SIGTERM and would otherwise survive as an orphan
                # no stop-all can find
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    try:
                        proc.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        _err(f"{name}: pid {proc.pid} survived "
                             f"SIGKILL; keeping pidfile for stop-all")
                        failed.append(name)
                        continue
            os.unlink(pidfile)
            failed.append(name)
    if failed:
        return 1
    _out(f"All servers up ({', '.join(started)}). "
         f"`ptpu stop-all` stops them.")
    return 0


def cmd_stop_all(args, storage: Storage) -> int:
    """``ptpu stop-all`` — SIGTERM every pidfile'd server, escalate to
    SIGKILL after a grace period, clean up pidfiles (the
    ``bin/pio-stop-all`` role)."""
    import signal as _signal

    d = _pid_dir(args)
    stopped = 0
    for name in _START_ALL:
        pidfile = os.path.join(d, f"{name}.pid")
        if not os.path.exists(pidfile):
            continue
        try:
            pid = int(open(pidfile).read().strip())
        except ValueError:
            os.unlink(pidfile)
            continue
        if _pid_alive(pid):
            try:
                os.kill(pid, _signal.SIGTERM)
            except ProcessLookupError:
                # exited between the aliveness check and the signal —
                # already what we wanted; fall through to cleanup
                pass
            except PermissionError:
                # we spawned our servers as this user; a pid we cannot
                # signal was recycled by someone else's process after a
                # crash/reboot — stale pidfile, nothing of ours to stop
                _out(f"{name}: pid {pid} now belongs to a foreign "
                     f"process (recycled after crash?); dropping "
                     f"stale pidfile")
                os.unlink(pidfile)
                continue
            deadline = time.monotonic() + args.stop_timeout
            while time.monotonic() < deadline and _pid_alive(pid):
                time.sleep(0.1)
            if _pid_alive(pid):
                _err(f"{name} (pid {pid}) ignored SIGTERM; killing")
                try:
                    os.kill(pid, _signal.SIGKILL)
                except ProcessLookupError:
                    pass  # exited in the TERM→KILL window
                kill_deadline = time.monotonic() + 10.0
                while _pid_alive(pid) and \
                        time.monotonic() < kill_deadline:
                    time.sleep(0.05)
                if _pid_alive(pid):
                    _err(f"{name} (pid {pid}) survived SIGKILL "
                         f"(unreaped?); leaving pidfile")
                    continue
            _out(f"{name}: stopped (pid {pid})")
            stopped += 1
        else:
            _out(f"{name}: not running (stale pidfile)")
        os.unlink(pidfile)
    if stopped == 0:
        _out("Nothing to stop.")
    return 0


def cmd_status(args, storage: Storage) -> int:
    """``pio status`` (``commands/Management.scala:99``): environment +
    storage smoke check + the active release per tracked engine (not
    just process liveness — the RELEASE is what serves traffic)."""
    _out(f"PredictionIO-TPU {__version__}")
    try:
        import jax
        _out(f"JAX {jax.__version__}; devices: "
             f"{[str(d) for d in jax.devices()]}")
    except Exception as e:  # noqa: BLE001 — report, don't crash status
        _err(f"JAX initialization failed: {e}")
        return 1
    try:
        storage.verify_all_data_objects()
        _out("Storage: all data objects verified.")
    except Exception as e:  # noqa: BLE001
        _err(f"Storage check failed: {e}")
        return 1
    try:
        from ..rollout import ReleaseRegistry

        tracked = ReleaseRegistry.list_tracked(storage)
    except Exception as e:  # noqa: BLE001 — release state is advisory
        _err(f"release registry read failed: {e}")
        tracked = []
    for engine_id, engine_version, engine_variant in tracked:
        from ..rollout import ReleaseRegistry

        st = ReleaseRegistry(storage, engine_id, engine_version,
                             engine_variant).state()
        line = (f"Release [{engine_id} v{engine_version}]: "
                f"stable={st.get('stable') or '(none)'}")
        if st.get("pinned"):
            line += f" pinned={st['pinned']}"
        if st.get("candidate"):
            line += (f" candidate={st['candidate']} "
                     f"({st.get('candidateMode')} at "
                     f"{float(st.get('fraction') or 0) * 100:.0f}%)")
        _out(line)
    if getattr(args, "ip", ""):
        # model-lineage satellite (ISSUE 10): when pointed at a live
        # engine server, show what blend of batch + stream is actually
        # serving — base retrain, fold-in generations, staleness
        try:
            status_payload = _server_call(args, "/status.json")
        except Exception as e:  # noqa: BLE001 — liveness is optional
            _err(f"engine server at {args.ip}:{args.port} unreachable "
                 f"({e}); skipping lineage")
            status_payload = None
        lin = (status_payload or {}).get("lineage") or {}
        if lin:
            line = (f"Serving [{status_payload.get('engineId', '?')}]: "
                    f"base {lin.get('baseInstanceId', '?')} "
                    f"+{lin.get('incrementalGeneration', 0)} fold-ins "
                    f"({lin.get('incrementalRows', 0)} rows), "
                    f"staleness {lin.get('stalenessSec', '?')}s"
                    + (", stream live" if lin.get("streaming") else ""))
            _out(line)
    _out("(sleeping 0 seconds) Your system is all ready to go.")
    return 0


def cmd_release(args, storage: Storage) -> int:
    """``ptpu release`` — the progressive-delivery console (ISSUE 3):
    list/show release state and history from storage; pin releases;
    drive a running engine server's canary/promote/rollback/status
    over its control routes."""
    from ..rollout import ReleaseRegistry
    from ..rollout.splitter import parse_fraction

    sub = args.release_command

    if sub == "list":
        tracked = ReleaseRegistry.list_tracked(storage)
        if not tracked:
            _out("No releases recorded yet (deploy to create one).")
            return 0
        for engine_id, engine_version, engine_variant in sorted(tracked):
            st = ReleaseRegistry(storage, engine_id, engine_version,
                                 engine_variant).state()
            _out(f"{engine_id} v{engine_version} ({engine_variant}): "
                 f"stable={st.get('stable') or '(none)'} "
                 f"pinned={st.get('pinned') or '-'} "
                 f"candidate={st.get('candidate') or '-'}")
        return 0

    reg = ReleaseRegistry(storage, args.engine_id or "default",
                          args.engine_version or "1",
                          args.engine_json)

    if sub == "show":
        payload = reg.to_json(history_limit=args.limit)
        _out(json.dumps(payload, indent=2))
        return 0

    if sub == "pin":
        if args.clear:
            reg.unpin(actor="ptpu release", reason=args.reason)
            _out("Unpinned; deploy/reload bind the latest COMPLETED "
                 "instance again.")
            return 0
        if not args.instance_id:
            _err("instance_id required (or --clear).")
            return 1
        try:
            reg.pin(args.instance_id, actor="ptpu release",
                    reason=args.reason)
        except ValueError as e:
            _err(str(e))
            return 1
        _out(f"Pinned release {args.instance_id}; deploy/reload now "
             f"bind it (POST /reload to apply on a live server).")
        return 0

    if sub == "status":
        try:
            payload = _server_call(args, "/release.json")
        except Exception as e:  # noqa: BLE001 — fall back to storage
            _err(f"engine server at {args.ip}:{args.port} unreachable "
                 f"({e}); showing storage state")
            _out(json.dumps(reg.to_json(history_limit=10), indent=2))
            return 0
        _out(json.dumps(payload, indent=2))
        return 0

    if sub == "canary":
        try:
            fraction = (parse_fraction(args.fraction)
                        if args.fraction else None)
        except ValueError as e:
            _err(str(e))
            return 1
        body = {"instanceId": args.instance_id, "shadow": args.shadow,
                "actor": "ptpu release", "reason": args.reason}
        if fraction is not None:
            body["fraction"] = fraction
        try:
            resp = _server_call(args, "/release/canary", method="POST",
                                body=body)
        except Exception as e:  # noqa: BLE001 — report, don't traceback
            _err(f"canary start failed: {_http_err_detail(e)}")
            return 1
        ro = (resp or {}).get("rollout") or {}
        _out(f"{'Shadow' if args.shadow else 'Canary'} rollout of "
             f"{args.instance_id} started at "
             f"{float(ro.get('fraction') or 0) * 100:.0f}% "
             f"(watch: ptpu release status).")
        return 0

    if sub in ("promote", "rollback"):
        try:
            resp = _server_call(args, f"/release/{sub}", method="POST",
                                body={"reason": args.reason})
        except Exception as e:  # noqa: BLE001 — report, don't traceback
            _err(f"{sub} failed: {_http_err_detail(e)}")
            return 1
        _out(f"{resp.get('message', 'OK')} Serving instance: "
             f"{resp.get('engineInstanceId', '?')}")
        return 0

    _err(f"Unknown release subcommand {sub!r}")
    return 1


def cmd_cache(args, storage: Storage) -> int:
    """``ptpu cache`` — operate a running engine server's serving
    cache hierarchy (ISSUE 4): per-tier stats, operator flush."""
    sub = args.cache_command
    if sub == "stats":
        try:
            payload = _server_call(args, "/cache.json")
        except Exception as e:  # noqa: BLE001 — report, don't traceback
            _err(f"engine server at {args.ip}:{args.port} unreachable: "
                 f"{_http_err_detail(e)}")
            return 1
        if not (payload or {}).get("enabled"):
            _out("Serving cache is OFF on this server "
                 "(deploy with --cache).")
            return 0
        _out(json.dumps(payload, indent=2))
        tiers = payload.get("tiers") or {}
        for name, t in tiers.items():
            total = t.get("hits", 0) + t.get("misses", 0)
            _out(f"{name}: {t.get('entries', 0)} entries, "
                 f"{t.get('hitRatio', 0) * 100:.1f}% hit ratio over "
                 f"{total} lookups, {t.get('invalidations', 0)} "
                 f"invalidations")
        return 0
    if sub == "flush":
        try:
            payload = _server_call(args, "/cache/flush", method="POST")
        except Exception as e:  # noqa: BLE001 — report, don't traceback
            _err(f"cache flush failed: {_http_err_detail(e)}")
            return 1
        removed = (payload or {}).get("removed") or {}
        _out("Flushed: " + ", ".join(f"{k}={v}"
                                     for k, v in removed.items()))
        return 0
    _err(f"Unknown cache subcommand {sub!r}")
    return 1


def cmd_stream(args, storage: Storage) -> int:
    """``ptpu stream`` — operate a running engine server's streaming
    incremental trainer (ISSUE 10, docs/streaming.md): attach, stop,
    and inspect the event→model loop."""
    sub = args.stream_command
    if sub == "status":
        try:
            payload = _server_call(args, "/stream.json")
        except Exception as e:  # noqa: BLE001 — report, don't traceback
            _err(f"engine server at {args.ip}:{args.port} unreachable: "
                 f"{_http_err_detail(e)}")
            return 1
        _out(json.dumps(payload, indent=2))
        lin = (payload or {}).get("lineage") or {}
        if lin:
            line = (f"serving: base {lin.get('baseInstanceId', '?')} "
                    f"+{lin.get('incrementalGeneration', 0)} fold-ins "
                    f"({lin.get('incrementalRows', 0)} rows), "
                    f"staleness {lin.get('stalenessSec', '?')}s")
            _out(line)
        if not (payload or {}).get("running"):
            _out("Streaming trainer is OFF (ptpu stream start --app "
                 "<app>, or deploy with --stream).")
        return 0
    if sub == "start":
        body = {}
        if args.app:
            body["appName"] = args.app
        if args.channel:
            body["channelName"] = args.channel
        if args.consumer:
            body["consumer"] = args.consumer
        if args.interval_ms is not None:
            body["intervalMs"] = args.interval_ms
        if args.max_events is not None:
            body["maxEvents"] = args.max_events
        if args.drift_threshold is not None:
            body["driftThreshold"] = args.drift_threshold
        if args.canary_probes is not None:
            body["canaryProbes"] = args.canary_probes
        try:
            resp = _server_call(args, "/stream/start", method="POST",
                                body=body)
        except Exception as e:  # noqa: BLE001 — report, don't traceback
            _err(f"stream start failed: {_http_err_detail(e)}")
            return 1
        st = (resp or {}).get("stream") or {}
        _out(f"Streaming trainer started (app "
             f"{st.get('appName', '?')}, consumer "
             f"{st.get('consumer', '?')}, interval "
             f"{st.get('intervalMs', '?')}ms). Watch: ptpu stream "
             f"status.")
        return 0
    if sub == "stop":
        try:
            resp = _server_call(args, "/stream/stop", method="POST")
        except Exception as e:  # noqa: BLE001 — report, don't traceback
            _err(f"stream stop failed: {_http_err_detail(e)}")
            return 1
        _out((resp or {}).get("message", "Stopped."))
        _out("The durable cursor keeps its position; a later start "
             "with the same consumer resumes exactly there.")
        return 0
    _err(f"Unknown stream subcommand {sub!r}")
    return 1


def _print_slo_payload(payload: Optional[dict]) -> int:
    """One line per spec from a ``/slo.json`` body (shared by ``ptpu
    slo status`` and ``ptpu fleet slo``); exit 1 while burning."""
    p = payload or {}
    if not p.get("enabled", False):
        _out("SLO engine is disabled on this server "
             f"({p.get('hint', '')})")
        return 0
    burning = p.get("burning") or []
    for sp in p.get("specs") or []:
        budget = sp.get("budgetRemaining")
        bits = [f"{sp['name']:<28} {sp['state']:<18}"]
        for key, label in (("burnFast", "fast"),
                           ("burnSlow", "slow")):
            v = sp.get(key)
            bits.append(f"burn[{label}] "
                        + (f"{v:6.2f}x" if v is not None
                           else "     ?"))
        bits.append("budget "
                    + (f"{budget * 100:6.1f}%" if budget is not None
                       else "     ?"))
        bits.append(f"violations {sp.get('violations', 0)}")
        _out("  ".join(bits))
    _out(f"{len(p.get('specs') or [])} spec(s), "
         + (f"BURNING: {', '.join(burning)}" if burning
            else "none burning")
         + f" ({p.get('ticks', 0)} evaluation ticks)")
    return 1 if burning else 0


def cmd_slo(args, storage: Storage) -> int:
    """``ptpu slo`` (ISSUE 15, docs/slo.md):

    - ``status`` — a running server's live burn rates / budgets
      (``GET /slo.json``), one line per spec;
    - ``check`` — the CI capacity gate: diff a ``load_harness``
      ``CAPACITY.json`` against the committed spec file with ratchet
      semantics (regressions fail naming the spec, the measurement
      window, and the measured value; ``--update`` tightens the
      committed gates toward a better run, never loosens them).
    """
    if args.slo_command == "status":
        try:
            payload = _server_call(args, "/slo.json")
        except Exception as e:  # noqa: BLE001 — report, don't traceback
            _err(f"server at {args.ip}:{args.port} unreachable: "
                 f"{_http_err_detail(e)}")
            return 1
        return _print_slo_payload(payload)
    # check: gate CAPACITY.json against the committed spec file
    from ..slo import (
        gate_capacity,
        load_specs,
        ratchet_gates,
        write_gates,
    )

    try:
        with open(args.capacity, encoding="utf-8") as f:
            capacity = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        _err(f"cannot read capacity model {args.capacity}: {e}")
        return 1
    try:
        _specs, gates = load_specs(args.specs)
    except (OSError, ValueError) as e:
        _err(f"cannot read SLO spec file {args.specs}: {e}")
        return 1
    if not gates:
        _err(f"{args.specs} commits no capacity gates; add a "
             f"'capacity' section (docs/slo.md)")
        return 1
    failures = gate_capacity(capacity, gates)
    for line in failures:
        _err(f"FAIL {line}")
    if failures:
        _err(f"{len(failures)} capacity regression(s) vs {args.specs} "
             f"— fix the regression or, for an accepted trade-off, "
             f"loosen the committed gate in an explicit commit")
        return 1
    n_checked = sum(len(g) for g in gates.values())
    _out(f"capacity gate PASS: {n_checked} committed limit(s) over "
         f"{len(gates)} config(s) hold for {args.capacity}")
    if args.update:
        new_gates, changes = ratchet_gates(capacity, gates)
        if changes:
            write_gates(args.specs, new_gates)
            for c in changes:
                _out(f"ratchet {c}")
            _out(f"tightened {len(changes)} gate(s) in {args.specs} — "
                 f"commit the file")
        else:
            _out("no gate beat its committed value; nothing to ratchet")
    return 0


def cmd_trace(args, storage: Storage) -> int:
    """``ptpu trace`` — read a running server's tail-sampled flight
    recorder (ISSUE 12, docs/tracing.md): recorder status, the N
    slowest retained traces, or one trace exported as Chrome/Perfetto
    trace-event JSON (load the file at ui.perfetto.dev)."""
    try:
        if args.id:
            payload = _server_call(args, f"/trace.json?id={args.id}")
        elif args.slowest is not None:
            payload = _server_call(
                args, f"/trace.json?slowest={args.slowest}")
        else:
            payload = _server_call(args, "/trace.json")
    except Exception as e:  # noqa: BLE001 — report, don't traceback
        _err(f"server at {args.ip}:{args.port} unreachable: "
             f"{_http_err_detail(e)}")
        return 1
    if args.id:
        out_path = args.output or f"trace-{args.id[:12]}.json"
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        n = len((payload or {}).get("traceEvents") or [])
        _out(f"Wrote {n} trace events to {out_path} — load it at "
             f"https://ui.perfetto.dev (or chrome://tracing).")
        return 0
    if args.slowest is not None:
        traces = (payload or {}).get("traces") or []
        if not traces:
            _out("No retained traces yet (only slow / errored / "
                 "deadline-503'd / fault-injected requests are kept).")
            return 0
        for t in traces:
            _out(f"{t.get('traceId')}  {t.get('durationMs', '?')}ms  "
                 f"status={t.get('status')}  "
                 f"reason={t.get('reason')}  {t.get('name', '')}")
        _out(f"Export one: ptpu trace --id {traces[0]['traceId']}")
        return 0
    _out(json.dumps(payload, indent=2))
    p = payload or {}
    _out(f"flight recorder: {p.get('retained', 0)}/"
         f"{p.get('ringCapacity', '?')} retained of "
         f"{p.get('requests', 0)} traced requests"
         + (f", slow ≥ {p['slowThresholdMs']}ms"
            if p.get("slowThresholdMs") is not None else ""))
    return 0


def _http_err_detail(e: Exception) -> str:
    """Surface the server's JSON error message instead of a bare
    'HTTP Error 409'."""
    import urllib.error

    if isinstance(e, urllib.error.HTTPError):
        try:
            body = json.loads(e.read() or b"{}")
            return f"{e.code}: {body.get('message', '')}"
        except Exception:  # noqa: BLE001 — fall back to the bare error
            return str(e)
    return str(e)


def cmd_fleet(args) -> int:
    """``ptpu fleet`` (ISSUE 17, docs/fleet.md) — the fleet
    observability plane:

    - ``serve`` — run the aggregator: scrape every ``--replicas``
      member's ``/metrics.json``, merge exactly (counters sum,
      histograms pool buckets, gauges gain replica labels + rollups),
      evaluate fleet-scoped SLOs over the MERGED series, and serve
      the fleet surface (``/``, ``/fleet.json``, ``/metrics``,
      ``/slo.json``, ``/trace.json``, ``/hotkeys.json``);
    - ``status`` — per-replica liveness/lag/flags + fleet headroom
      from a running aggregator (exit 1 when replicas are down or a
      fleet SLO burns);
    - ``slo`` — the fleet SLO engine's burn rates (merged-series
      verdicts, one line per spec);
    - ``trace`` — cross-replica flight-recorder lookup: ``--id``
      fans out to every replica and exports the hit, ``--slowest N``
      merges fleet-wide;
    - ``hotkeys`` — the fleet-wide Space-Saving top-K (and each
      replica's own view);
    - ``route`` — the query router's view (ISSUE 18): ring
      membership, per-backend state, where a ``--key`` would land;
    - ``scale`` — hand the autoscaler a manual replica-count target
      (clamped to its policy bounds, logged in the decision log).

    Pure HTTP: needs neither storage nor jax.
    """
    if args.fleet_command == "serve":
        from ..fleet import FleetConfig, create_fleet_server
        from ..server.http import ssl_context_from

        cfg = FleetConfig(
            replicas=[r.strip() for r in args.replicas.split(",")
                      if r.strip()],
            scrape_interval_sec=args.scrape_interval_ms / 1000.0,
            stale_after_sec=(args.stale_after_ms / 1000.0
                             if args.stale_after_ms else None),
            slo_specs=args.slo_specs or None,
            slo_interval_sec=args.slo_interval_ms / 1000.0,
            capacity_path=args.capacity or None,
            hot_keys_k=args.hot_keys_k,
            timeout_sec=args.timeout_sec,
            accesskey=args.accesskey or None)
        ssl_ctx = ssl_context_from(args.cert or None, args.key or None)
        agg, server = create_fleet_server(cfg, host=args.ip,
                                          port=args.port,
                                          ssl_context=ssl_ctx)
        scheme = "https" if ssl_ctx else "http"
        _out(f"Fleet aggregator live at {scheme}://{args.ip}:"
             f"{server.port} over {len(cfg.replicas)} replica(s).")
        _out(f"Merged telemetry: {scheme}://{args.ip}:{server.port}"
             f"/metrics · /fleet.json · /slo.json · /trace.json · "
             f"/hotkeys.json")
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            _out("Shutting down.")
            agg.stop()
        return 0
    try:
        if args.fleet_command == "status":
            payload = _server_call(args, "/fleet.json") or {}
        elif args.fleet_command == "slo":
            return _print_slo_payload(_server_call(args, "/slo.json"))
        elif args.fleet_command == "hotkeys":
            payload = _server_call(
                args, f"/hotkeys.json?n={args.top}") or {}
        elif args.fleet_command == "route":
            import urllib.parse as _up

            path = "/route.json"
            if args.key:
                path += "?key=" + _up.quote(args.key)
            payload = _server_call(args, path) or {}
        elif args.fleet_command == "scale":
            import urllib.parse as _up

            path = f"/scale?to={int(args.to)}"
            if args.reason:
                path += "&reason=" + _up.quote(args.reason)
            payload = _server_call(args, path, method="POST") or {}
        else:  # trace
            if args.id:
                payload = _server_call(args,
                                       f"/trace.json?id={args.id}")
            elif args.slowest is not None:
                payload = _server_call(
                    args, f"/trace.json?slowest={args.slowest}")
            else:
                payload = _server_call(args, "/trace.json")
    except Exception as e:  # noqa: BLE001 — report, don't traceback
        _err(f"fleet aggregator at {args.ip}:{args.port} unreachable: "
             f"{_http_err_detail(e)}")
        return 1
    if args.fleet_command == "status":
        # the autoscaler's decision log tells an INTENTIONAL exit
        # (scale-in terminate) from a corpse: a replica it removed —
        # or one mid-drain — is not a failure and must not flip the
        # exit code (ISSUE 18 satellite)
        autoscale = payload.get("autoscale") or {}
        removed = set(autoscale.get("removed") or [])
        down = 0
        for r in payload.get("replicas") or []:
            up = r.get("up")
            lifecycle = r.get("lifecycle")
            if up:
                state = ("draining" if lifecycle == "draining"
                         else "up")
            elif (r.get("replica") in removed
                  or lifecycle == "draining"):
                state = "removed"   # scale-in, not an outage
            else:
                state = "DOWN"
                down += 1
            flags = []
            if r.get("degraded"):
                flags.append("DEGRADED")
            if r.get("nonfinite"):
                flags.append("NONFINITE")
            if r.get("sloBurning"):
                flags.append("burning:" + ",".join(r["sloBurning"]))
            age = r.get("lastScrapeAgeSec")
            _out(f"{r.get('replica', '?'):<24} "
                 f"{state:<9} "
                 f"age {age if age is not None else '?':>7}s  "
                 f"requests {r.get('requestCount') or 0:>8}  "
                 f"{' '.join(flags)}")
        headroom = payload.get("capacityHeadroom")
        burning = (payload.get("slo") or {}).get("burning") or []
        _out(f"{payload.get('replicasUp', 0)}/"
             f"{payload.get('replicasConfigured', 0)} replicas up, "
             f"qps {payload.get('qps', 0.0):.2f}, headroom "
             + (f"{headroom:.3f}" if headroom is not None else "?")
             + (f", fleet SLO BURNING: {', '.join(burning)}"
                if burning else ", fleet SLO ok")
             + f" ({payload.get('cycles', 0)} scrape cycles)")
        if autoscale.get("enabled"):
            decisions = autoscale.get("decisions") or []
            last = decisions[-1] if decisions else {}
            _out(f"autoscale: target {autoscale.get('target')}, "
                 f"{len(removed)} scaled-in, last decision "
                 f"{last.get('action', 'none')}"
                 + (f" ({last.get('reason')})"
                    if last.get("reason") else ""))
        return 1 if (down or burning) else 0
    if args.fleet_command == "hotkeys":
        for k in payload.get("fleet") or []:
            _out(f"{k['key']:<32} {k['count']:>12.0f} "
                 f"(±{k['error']:.0f})")
        if not payload.get("fleet"):
            _out("No hot keys observed yet (the sketch fills from "
                 "query-path entity ids).")
        return 0
    if args.fleet_command == "route":
        for b in payload.get("replicas") or []:
            _out(f"{b.get('replica', '?'):<24} "
                 f"{b.get('state', '?'):<9} "
                 f"inflight {b.get('inflight', 0):>4}  "
                 f"requests {b.get('requests', 0):>8}  "
                 f"failures {b.get('consecutiveFailures', 0)}")
        if args.key:
            _out(f"key {args.key!r} → {payload.get('affinity')} "
                 f"(preference: "
                 f"{', '.join(payload.get('preference') or [])})")
        ring = payload.get("ring") or {}
        _out(f"{len(payload.get('replicas') or [])} backend(s), "
             f"{ring.get('vnodes', '?')} vnodes each; retries "
             f"{payload.get('retries')}; spill "
             f"{(payload.get('spill') or {}).get('share')}")
        return 0
    if args.fleet_command == "scale":
        _out(f"requested {payload.get('requested')} → target "
             f"{payload.get('target')} (clamped to policy bounds); "
             f"the control loop converges on its next tick.")
        return 0
    # trace
    if args.id:
        trace = (payload or {}).get("trace")
        replica = (payload or {}).get("replica", "?")
        out_path = args.output or f"trace-{args.id[:12]}.json"
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(trace, f)
        n = len((trace or {}).get("traceEvents") or [])
        _out(f"Trace found on replica {replica}; wrote {n} trace "
             f"events to {out_path} — load it at "
             f"https://ui.perfetto.dev.")
        return 0
    if args.slowest is not None:
        traces = (payload or {}).get("traces") or []
        if not traces:
            _out("No retained traces anywhere in the fleet yet.")
            return 0
        for t in traces:
            _out(f"{t.get('traceId')}  {t.get('durationMs', '?')}ms  "
                 f"replica={t.get('replica')}  "
                 f"status={t.get('status')}  "
                 f"reason={t.get('reason')}  {t.get('name', '')}")
        _out(f"Export one: ptpu fleet trace --id "
             f"{traces[0]['traceId']} --port {args.port}")
        return 0
    _out(json.dumps(payload, indent=2))
    return 0


def cmd_export(args, storage: Storage) -> int:
    """``pio export`` (``tools/export/EventsToFile.scala``): events →
    JSON-lines file."""
    from ..data.storage.base import EventFilter

    a = storage.apps().get_by_name(args.app) if args.app else \
        storage.apps().get(args.appid)
    if a is None:
        _err("App does not exist. Aborting.")
        return 1
    channel_id = None
    if args.channel:
        ch = _find_channel(storage, a, args.channel)
        if ch is None:
            _err(f"Channel {args.channel} does not exist. Aborting.")
            return 1
        channel_id = ch.id
    n = 0
    with open(args.output, "w", encoding="utf-8") as f:
        for e in storage.events().find(a.id, channel_id, EventFilter()):
            f.write(json.dumps(e.to_json()) + "\n")
            n += 1
    _out(f"Exported {n} event(s) to {args.output}.")
    return 0


def cmd_import(args, storage: Storage) -> int:
    """``pio import`` (``tools/imprt/FileToEvents.scala``): JSON-lines →
    event store."""
    a = storage.apps().get_by_name(args.app) if args.app else \
        storage.apps().get(args.appid)
    if a is None:
        _err("App does not exist. Aborting.")
        return 1
    channel_id = None
    if args.channel:
        ch = _find_channel(storage, a, args.channel)
        if ch is None:
            _err(f"Channel {args.channel} does not exist. Aborting.")
            return 1
        channel_id = ch.id
    # import streams in chunks (a 20M-line file must not materialize
    # every Event at once), each committed all-or-nothing — backends
    # with a native bulk lane (segmentfs) override import_jsonl with a
    # one-pass C++ encode. A mid-file failure reports exactly which
    # durable prefix is committed instead of dying with a traceback
    # and an unknown amount of half-imported data.
    from ..data.storage.base import JsonlImportError

    chunk = int(os.environ.get("PIO_IMPORT_BATCH", "100000"))
    try:
        total = storage.events().import_jsonl(
            args.input, a.id, channel_id, chunk=chunk)
    except JsonlImportError as err:
        _err(f"Import failed near line {err.lineno}: {err.cause}")
        app_flag = f"--app {args.app}" if args.app \
            else f"--appid {args.appid}"
        _err(f"{err.committed_events} event(s) (input lines "
             f"1-{err.committed_lines}) are already committed. "
             f"Re-importing this file would DUPLICATE them — resume "
             f"with the remainder only, e.g.: "
             f"tail -n +{err.committed_lines + 1} {args.input} > rest."
             f"jsonl && ptpu import {app_flag} --input rest.jsonl "
             f"(or app data-delete to start over).")
        return 1
    except OSError as e:
        _err(f"Import failed: {e}")
        return 1
    _out(f"Imported {total} event(s).")
    # pay the one-time columnar-sidecar encode HERE (ingest already
    # parsed every byte) instead of surprising the first `ptpu train`
    # with it — measured 176s of a 299s first train at ML-20M scale
    t0 = time.monotonic()
    try:
        warmed = storage.events().warm_columnar(a.id, channel_id)
    except Exception as e:  # noqa: BLE001 — warm is advisory, never
        _err(f"columnar warm failed (first read will pay the "
             f"encode): {e}")
        warmed = False
    if warmed:
        _out(f"Columnar sidecar ready ({time.monotonic() - t0:.1f}s).")
    return 0


def artifact_root(arg: str = "") -> str:
    """Resolve the AOT artifact store root: explicit flag, then
    $PTPU_ARTIFACT_DIR, then ~/.ptpu/artifacts."""
    return (arg or os.environ.get("PTPU_ARTIFACT_DIR", "")
            or os.path.join(os.path.expanduser("~"), ".ptpu",
                            "artifacts"))


def cmd_build(args, storage: Storage) -> int:
    """No sbt here: 'build' verifies the engine variant is loadable
    (``commands/Engine.scala:66-139`` becomes an import check). With
    ``--aot`` (ISSUE 19) it additionally compiles the serving entry
    points for the latest COMPLETED instance and serializes the
    executables into the artifact store, so a matching deploy warms by
    loading them (docs/cold-start.md)."""
    variant = load_variant(args.engine_json)
    engine, engine_params = engine_from_variant(variant)
    n_algos = len(engine_params.algorithms)
    _out(f"Engine factory {variant.get('engineFactory')} loads OK "
         f"({n_algos} algorithm(s) configured).")
    if getattr(args, "aot", False):
        from ..server.engineserver import ServerConfig, build_artifacts

        ctx = _make_ctx(storage)
        config = ServerConfig(
            batching=args.batching,
            max_batch=args.max_batch,
            serving_mode=args.serving_mode,
            serving_quant=args.serving_quant,
            serving_topk=args.serving_topk)
        result = build_artifacts(
            ctx, engine, engine_params,
            artifact_root(args.artifact_dir),
            engine_id=args.engine_id or variant.get("id", "default"),
            engine_version=(args.engine_version
                            or variant.get("version", "1")),
            engine_variant=args.engine_json,
            config=config)
        _out(f"AOT artifacts: {result['entries']} serving "
             f"executable(s) for instance {result['instance']} in "
             f"{result['seconds']:.1f}s -> {result['path']}")
        _out(f"Deploy with --artifact-dir "
             f"{artifact_root(args.artifact_dir)} (and the same "
             f"serving flags) to warm from them.")
    _out("Build finished successfully.")
    return 0


def cmd_shell(args, storage: Storage) -> int:
    """Interactive shell with the framework preloaded
    (``bin/pio-shell`` role; pypio is native here)."""
    import code

    from ..controller.context import Context
    from ..data.store import EventStoreFacade
    from ..pypio import PEventStore

    ns = {
        "storage": storage,
        "event_store": EventStoreFacade(storage),
        "p_event_store": PEventStore(EventStoreFacade(storage)),
        "Context": Context,
    }
    banner = ("PredictionIO-TPU shell. Preloaded: storage, event_store, "
              "p_event_store, Context.")
    try:
        import IPython

        IPython.start_ipython(argv=[], user_ns=ns)
    except ImportError:
        code.interact(banner=banner, local=ns)
    return 0


def cmd_run(args, storage: Storage) -> int:
    """Run a user entry point with storage configured
    (``pio run`` / ``commands/Engine.scala:332``)."""
    from ..data.storage import registry as _registry
    from ..data.storage.registry import set_storage

    fn = load_engine_factory(args.target)
    if not callable(fn):
        raise SystemExit(f"{args.target!r} is not callable")
    prior = _registry._global
    set_storage(storage)
    try:
        result = fn(*args.args)
        if result is not None:
            _out(str(result))
        return 0
    finally:
        set_storage(prior)


def cmd_check(args) -> int:
    """``ptpu check`` — JAX-aware + concurrency + Pallas-kernel static
    analysis, interprocedural over the scanned set (pure AST, no
    jax/storage import: safe on any host, fast enough for a pre-commit
    hook). Non-zero exit on findings — or, with ``--baseline``, on
    findings NOT in the baseline (which only ever ratchets down; see
    --baseline-grow). ``--format json|sarif`` for machines (sarif
    feeds GitHub code-scanning PR annotations, interprocedural call
    chains as relatedLocations); see docs/static-analysis.md."""
    from ..analysis import (
        RULES,
        findings_to_json,
        findings_to_sarif,
        load_baseline,
        new_findings,
        run_check,
        shrinkable_entries,
        write_baseline,
    )

    if args.list_rules:
        for name, rule in sorted(RULES.items()):
            _out(f"{name}: {rule.description}")
        return 0
    try:
        findings = run_check(args.paths or ["predictionio_tpu"],
                             rule_names=args.rule or None)
    except ValueError as e:
        _err(str(e))
        return 2
    if args.write_baseline:
        if not args.baseline:
            _err("--write-baseline requires --baseline FILE")
            return 2
        cap = None
        if not args.baseline_grow and os.path.exists(args.baseline):
            try:
                cap = load_baseline(args.baseline)
            except (OSError, ValueError, KeyError, TypeError) as e:
                _err(f"ptpu check: cannot read baseline: {e}")
                return 2
        n = write_baseline(args.baseline, findings, cap=cap)
        _err(f"ptpu check: wrote {n} baseline entr"
             f"{'y' if n == 1 else 'ies'} "
             f"({len(findings)} finding(s)) to {args.baseline}"
             f"{' (ratchet: shrink-only)' if cap is not None else ''}.")
        if cap is not None:
            overflow = new_findings(findings, cap)
            if overflow:
                _err(f"ptpu check: {len(overflow)} finding(s) exceed "
                     f"the recorded baseline and were NOT absorbed "
                     f"(the baseline only ratchets down; fix them or "
                     f"re-record deliberately with --baseline-grow):")
                for f in overflow:
                    _err(f"  {f.format()}")
                return 1
        return 0
    gating = findings
    baselined = 0
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, KeyError, TypeError) as e:
            _err(f"ptpu check: cannot read baseline: {e}")
            return 2
        gating = new_findings(findings, baseline)
        baselined = len(findings) - len(gating)
        shrinkable = shrinkable_entries(findings, baseline)
        if shrinkable:
            _err(f"ptpu check: {len(shrinkable)} baseline entr"
                 f"{'y is' if len(shrinkable) == 1 else 'ies are'} "
                 f"no longer fully reproduced — the baseline can "
                 f"ratchet down (re-run with --write-baseline):")
            for (path, rule, _msg), rec, act in shrinkable:
                _err(f"  {path}: {rule}: recorded {rec}, found {act}")
    if args.format == "json":
        _out(findings_to_json(gating))
    elif args.format == "sarif":
        _out(findings_to_sarif(gating, RULES))
    else:
        for f in gating:
            _out(f.format())
    suffix = (f" ({baselined} baselined finding(s) not counted)"
              if baselined else "")
    if gating:
        _err(f"ptpu check: {len(gating)} "
             f"{'new ' if args.baseline else ''}finding(s){suffix}. "
             f"Fix them or suppress with "
             f"'# ptpu: allow[rule] — justification'.")
        return 1
    if args.format == "text":
        _out(f"ptpu check: clean.{suffix}")
    return 0


def cmd_audit_hlo(args) -> int:
    """``ptpu audit-hlo`` — compile the registered SPMD entry points
    on a forced 8-device CPU mesh, parse the optimized HLO for
    collective ops + temp allocations, and gate against the committed
    golden manifest (``analysis/hlo_baseline.json``) with the same
    ratchet semantics as ``ptpu check --baseline``. The static
    sharding rules catch spec disagreements the AST can see; this
    catches the collectives only XLA sees. Non-zero exit on new
    collectives / grown temps (see --baseline-grow);
    docs/parallelism.md has the diff-reading runbook."""
    from ..analysis import hlo_audit as ha

    if args.list_entries:
        for name, (_b, desc) in ha.ENTRY_POINTS.items():
            _out(f"{name}: {desc}")
        return 0
    try:
        manifest = ha.run_audit(args.entry or None)
    except ha.AuditError as e:
        _err(f"ptpu audit-hlo: {e}")
        return 2
    baseline_path = args.baseline or ha.DEFAULT_BASELINE
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=2, sort_keys=True)
            fh.write("\n")
    if args.write_baseline:
        cap = None
        if not args.baseline_grow and os.path.exists(baseline_path):
            try:
                cap = ha.load_manifest(baseline_path)
            except (OSError, ValueError) as e:
                _err(f"ptpu audit-hlo: cannot read baseline: {e}")
                return 2
        ha.write_manifest(baseline_path, manifest, cap=cap)
        _err(f"ptpu audit-hlo: wrote "
             f"{len(manifest['entries'])} entry point(s) to "
             f"{baseline_path}"
             f"{' (ratchet: shrink-only)' if cap is not None else ''}.")
        if cap is not None:
            violations, _ = ha.diff_manifests(manifest, cap)
            if violations:
                _err(f"ptpu audit-hlo: {len(violations)} regression(s) "
                     f"were NOT absorbed (the baseline only ratchets "
                     f"down; fix them or re-record deliberately with "
                     f"--baseline-grow):")
                for v in violations:
                    _err(f"  {v}")
                return 1
        return 0
    if args.format == "json":
        _out(json.dumps(manifest, indent=2, sort_keys=True))
    else:
        _out(ha.format_text(manifest))
    if not os.path.exists(baseline_path):
        _err(f"ptpu audit-hlo: no baseline at {baseline_path} — "
             f"record one with --write-baseline (gate skipped).")
        return 0
    try:
        baseline = ha.load_manifest(baseline_path)
    except (OSError, ValueError) as e:
        _err(f"ptpu audit-hlo: cannot read baseline: {e}")
        return 2
    if args.entry:
        # a subset run gates only the audited entries — the others are
        # not "no longer reproduced", they were not compiled
        keep = set(args.entry)
        baseline = {**baseline,
                    "entries": {k: v
                                for k, v in baseline["entries"].items()
                                if k in keep}}
    violations, shrinkable = ha.diff_manifests(manifest, baseline)
    if shrinkable:
        _err(f"ptpu audit-hlo: {len(shrinkable)} baseline entr"
             f"{'y is' if len(shrinkable) == 1 else 'ies are'} no "
             f"longer fully reproduced — ratchet down with "
             f"--write-baseline:")
        for s in shrinkable:
            _err(f"  {s}")
    if violations:
        _err(f"ptpu audit-hlo: {len(violations)} collective/temp "
             f"regression(s) vs {baseline_path}:")
        for v in violations:
            _err(f"  {v}")
        return 1
    _err("ptpu audit-hlo: compiled collectives match the golden "
         "manifest.")
    return 0


def cmd_audit_numerics(args) -> int:
    """``ptpu audit-numerics`` — abstract-interpret the registered
    numeric entry points (a jaxpr walk, no device execution), extract
    the per-entry dtype census (op counts, cast inventory,
    accumulation dtypes, bytes by dtype) and gate against the
    committed golden manifest (``analysis/numerics_baseline.json``)
    with the same ratchet semantics as ``audit-hlo``. The static
    dtype-flow rules catch the narrowings the AST can see; this
    catches the ones only the traced program sees. Non-zero exit on
    new casts / narrowed accumulators / grown bytes (see
    --baseline-grow); docs/static-analysis.md has the diff-reading
    runbook."""
    from ..analysis import numerics_audit as na

    if args.list_entries:
        for name, (_b, desc) in na.ENTRY_POINTS.items():
            _out(f"{name}: {desc}")
        return 0
    try:
        manifest = na.run_audit(args.entry or None)
    except na.AuditError as e:
        _err(f"ptpu audit-numerics: {e}")
        return 2
    baseline_path = args.baseline or na.DEFAULT_BASELINE
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=2, sort_keys=True)
            fh.write("\n")
    if args.write_baseline:
        cap = None
        if not args.baseline_grow and os.path.exists(baseline_path):
            try:
                cap = na.load_manifest(baseline_path)
            except (OSError, ValueError) as e:
                _err(f"ptpu audit-numerics: cannot read baseline: {e}")
                return 2
        na.write_manifest(baseline_path, manifest, cap=cap)
        _err(f"ptpu audit-numerics: wrote "
             f"{len(manifest['entries'])} entry point(s) to "
             f"{baseline_path}"
             f"{' (ratchet: shrink-only)' if cap is not None else ''}.")
        if cap is not None:
            violations, _ = na.diff_manifests(manifest, cap)
            if violations:
                _err(f"ptpu audit-numerics: {len(violations)} "
                     f"regression(s) were NOT absorbed (the baseline "
                     f"only ratchets down; fix them or re-record "
                     f"deliberately with --baseline-grow):")
                for v in violations:
                    _err(f"  {v}")
                return 1
        return 0
    if args.format == "json":
        _out(json.dumps(manifest, indent=2, sort_keys=True))
    else:
        _out(na.format_text(manifest))
    if not os.path.exists(baseline_path):
        _err(f"ptpu audit-numerics: no baseline at {baseline_path} — "
             f"record one with --write-baseline (gate skipped).")
        return 0
    try:
        baseline = na.load_manifest(baseline_path)
    except (OSError, ValueError) as e:
        _err(f"ptpu audit-numerics: cannot read baseline: {e}")
        return 2
    if args.entry:
        # a subset run gates only the audited entries — the others
        # were not traced, not "no longer reproduced"
        keep = set(args.entry)
        baseline = {**baseline,
                    "entries": {k: v
                                for k, v in baseline["entries"].items()
                                if k in keep}}
    violations, shrinkable = na.diff_manifests(manifest, baseline)
    if shrinkable:
        _err(f"ptpu audit-numerics: {len(shrinkable)} baseline entr"
             f"{'y is' if len(shrinkable) == 1 else 'ies are'} no "
             f"longer fully reproduced — ratchet down with "
             f"--write-baseline:")
        for s in shrinkable:
            _err(f"  {s}")
    if violations:
        _err(f"ptpu audit-numerics: {len(violations)} precision "
             f"regression(s) vs {baseline_path}:")
        for v in violations:
            _err(f"  {v}")
        return 1
    _err("ptpu audit-numerics: traced dtype census matches the "
         "golden manifest.")
    return 0


def cmd_audit_lifecycle(args) -> int:
    """``ptpu audit-lifecycle`` — boot each subsystem (event / storage
    / engine servers, stream trainer, fleet aggregator, router
    autoscaler), drive start→serve→stop cycles, snapshot
    ``/proc/self`` threads/fds/sockets around them and gate the leak
    census against the committed golden manifest
    (``analysis/lifecycle_baseline.json``) with the same ratchet
    semantics as ``audit-hlo``/``audit-numerics``. The static
    lifecycle rules catch the leaks the AST can see; this catches the
    ones only a running process shows. Non-zero exit on any leak above
    the recorded allowance (see --baseline-grow);
    docs/static-analysis.md has the triage runbook."""
    from ..analysis import lifecycle_audit as la

    if args.list_entries:
        for name, (_b, desc) in la.ENTRY_POINTS.items():
            _out(f"{name}: {desc}")
        return 0
    try:
        manifest = la.run_audit(args.entry or None, cycles=args.cycles)
    except la.AuditError as e:
        _err(f"ptpu audit-lifecycle: {e}")
        return 2
    baseline_path = args.baseline or la.DEFAULT_BASELINE
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(manifest, fh, indent=2, sort_keys=True)
            fh.write("\n")
    if args.write_baseline:
        cap = None
        if not args.baseline_grow and os.path.exists(baseline_path):
            try:
                cap = la.load_manifest(baseline_path)
            except (OSError, ValueError) as e:
                _err(f"ptpu audit-lifecycle: cannot read baseline: {e}")
                return 2
        la.write_manifest(baseline_path, manifest, cap=cap)
        _err(f"ptpu audit-lifecycle: wrote "
             f"{len(manifest['entries'])} entry point(s) to "
             f"{baseline_path}"
             f"{' (ratchet: shrink-only)' if cap is not None else ''}.")
        if cap is not None:
            violations, _ = la.diff_manifests(manifest, cap)
            if violations:
                _err(f"ptpu audit-lifecycle: {len(violations)} "
                     f"leak(s) were NOT absorbed (the baseline only "
                     f"ratchets down; fix them or re-record "
                     f"deliberately with --baseline-grow):")
                for v in violations:
                    _err(f"  {v}")
                return 1
        return 0
    if args.format == "json":
        _out(json.dumps(manifest, indent=2, sort_keys=True))
    else:
        _out(la.format_text(manifest))
    if not os.path.exists(baseline_path):
        _err(f"ptpu audit-lifecycle: no baseline at {baseline_path} — "
             f"record one with --write-baseline (gate skipped).")
        return 0
    try:
        baseline = la.load_manifest(baseline_path)
    except (OSError, ValueError) as e:
        _err(f"ptpu audit-lifecycle: cannot read baseline: {e}")
        return 2
    if args.entry:
        # a subset run gates only the audited entries — the others
        # were not cycled, not "no longer reproduced"
        keep = set(args.entry)
        baseline = {**baseline,
                    "entries": {k: v
                                for k, v in baseline["entries"].items()
                                if k in keep}}
    violations, shrinkable = la.diff_manifests(manifest, baseline)
    if shrinkable:
        _err(f"ptpu audit-lifecycle: {len(shrinkable)} baseline entr"
             f"{'y is' if len(shrinkable) == 1 else 'ies are'} no "
             f"longer fully reproduced — ratchet down with "
             f"--write-baseline:")
        for s in shrinkable:
            _err(f"  {s}")
    if violations:
        _err(f"ptpu audit-lifecycle: {len(violations)} resource "
             f"leak(s) vs {baseline_path}:")
        for v in violations:
            _err(f"  {v}")
        return 1
    _err("ptpu audit-lifecycle: every start->stop cycle released its "
         "threads, fds and sockets.")
    return 0


def cmd_template(args, storage: Storage) -> int:
    _out("Bundled engine templates (predictionio_tpu.templates):")
    _out("  recommendation  — ALS top-N (module: "
         "predictionio_tpu.templates.recommendation:recommendation_engine)")
    _out("  classification  — naive Bayes / random forest (…"
         "classification:classification_engine)")
    _out("  similarproduct  — ALS cosine / cooccurrence / like (…"
         "similarproduct:similarproduct_engine)")
    _out("  ecommerce       — ALS + popularity + filters (…"
         "ecommerce:ecommerce_engine)")
    return 0


def _find_channel(storage: Storage, app: App, name: str):
    """Resolve a channel by name within an app; None when absent."""
    return next((c for c in storage.channels().get_by_app_id(app.id)
                 if c.name == name), None)


def _confirm(prompt: str) -> bool:
    try:
        return input(f"{prompt} (y/N) ").strip().lower() == "y"
    except EOFError:
        return False


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ptpu",
        description="PredictionIO-TPU console (the reference's `pio`)")
    p.add_argument("--version", action="version",
                   version=f"%(prog)s {__version__}")
    sub = p.add_subparsers(dest="command", required=True)

    def add_engine_flags(sp):
        sp.add_argument("--engine-json", default="engine.json")
        sp.add_argument("--engine-id", default="")
        sp.add_argument("--engine-version", default="")

    sp = sub.add_parser("app", help="manage apps")
    app_sub = sp.add_subparsers(dest="app_command", required=True)
    s = app_sub.add_parser("new")
    s.add_argument("name")
    s.add_argument("--id", type=int, default=0)
    s.add_argument("--description")
    s.add_argument("--access-key", default="")
    app_sub.add_parser("list")
    s = app_sub.add_parser("show")
    s.add_argument("name")
    s = app_sub.add_parser("delete")
    s.add_argument("name")
    s.add_argument("-f", "--force", action="store_true")
    s = app_sub.add_parser("data-delete")
    s.add_argument("name")
    s.add_argument("--channel", default="")
    s.add_argument("-f", "--force", action="store_true")
    s = app_sub.add_parser("channel-new")
    s.add_argument("name")
    s.add_argument("channel")
    s = app_sub.add_parser("channel-delete")
    s.add_argument("name")
    s.add_argument("channel")
    s.add_argument("-f", "--force", action="store_true")

    sp = sub.add_parser("accesskey", help="manage access keys")
    ak_sub = sp.add_subparsers(dest="ak_command", required=True)
    s = ak_sub.add_parser("new")
    s.add_argument("app")
    s.add_argument("events", nargs="*")
    s.add_argument("--key", default="")
    s = ak_sub.add_parser("list")
    s.add_argument("--app", default="")
    s = ak_sub.add_parser("delete")
    s.add_argument("key")

    s = sub.add_parser("build", help="verify the engine variant loads")
    add_engine_flags(s)
    # AOT compile artifacts (ISSUE 19, docs/cold-start.md): serialize
    # the serving executables at build time so deploy warms by loading
    # them. The serving-envelope flags below are key-bearing and must
    # match the eventual `ptpu deploy` invocation.
    s.add_argument("--aot", action="store_true",
                   help="ahead-of-time compile the serving entry "
                        "points for the latest COMPLETED instance and "
                        "serialize them into --artifact-dir; a deploy "
                        "passing the same dir + serving flags warms "
                        "from the artifacts in milliseconds")
    s.add_argument("--artifact-dir", default="",
                   help="AOT artifact store root (default "
                        "$PTPU_ARTIFACT_DIR or ~/.ptpu/artifacts)")
    s.add_argument("--batching", action="store_true",
                   help="capture for a --batching deploy (pow2 batch "
                        "ladder up to --max-batch)")
    s.add_argument("--max-batch", type=int, default=128,
                   help="max queries per coalesced dispatch")
    s.add_argument("--serving-mode", default="single",
                   choices=["auto", "single", "replicated", "sharded"],
                   help="serving placement the deploy will use")
    s.add_argument("--serving-quant", default="off",
                   choices=["off", "bf16", "int8"],
                   help="serving-table quantization the deploy will "
                        "use")
    s.add_argument("--serving-topk", default="auto",
                   choices=["auto", "einsum", "fused"],
                   help="top-k realization the deploy will use")

    s = sub.add_parser("train", help="train an engine")
    add_engine_flags(s)
    s.add_argument("--skip-sanity-check", action="store_true")
    s.add_argument("--stop-after-read", action="store_true")
    s.add_argument("--stop-after-prepare", action="store_true")

    s = sub.add_parser("eval", help="run an evaluation")
    s.add_argument("evaluation",
                   help="module.path:evaluation_object")
    s.add_argument("engine_params_generator", nargs="?", default="",
                   help="module.path:params_generator (optional)")
    s.add_argument("--parallelism", type=int, default=1,
                   help="grid-walk thread pool size (packing and fold "
                        "prefixes are shared; >1 overlaps host work "
                        "with device dispatches)")

    s = sub.add_parser("deploy", help="deploy the latest trained engine")
    add_engine_flags(s)
    s.add_argument("--ip", default="0.0.0.0")
    s.add_argument("--port", type=int, default=8000)
    s.add_argument("--feedback", action="store_true")
    s.add_argument("--feedback-app-name", default="")
    s.add_argument("--accesskey", default="")
    s.add_argument("--cert", default="", help="PEM cert to serve HTTPS")
    s.add_argument("--key", default="", help="PEM private key")
    # literals, NOT `ServerConfig.<field>`: importing the server stack
    # here would pull jax into every storage-only CLI command. The
    # values are asserted equal to ServerConfig's defaults by
    # tests/test_cli.py::test_deploy_batching_defaults_match_config.
    s.add_argument("--batching", action="store_true",
                   help="coalesce concurrent queries into batched "
                        "device dispatches (the serving micro-batcher)")
    s.add_argument("--max-batch", type=int, default=128,
                   help="max queries per coalesced dispatch")
    s.add_argument("--batch-window-ms", type=float, default=2.0,
                   help="wait for a lone query before serving it solo")
    s.add_argument("--batch-pipeline", type=int, default=4,
                   help="concurrent batch dispatches in flight "
                        "(serial pipeline only)")
    s.add_argument("--pipeline", default="staged",
                   choices=["staged", "serial"],
                   help="serving batch-path architecture "
                        "(docs/serving-pipeline.md): staged = "
                        "continuous-batching pipeline overlapping host "
                        "assembly, device dispatch and readback; "
                        "serial = the pre-pipeline drainer threads")
    s.add_argument("--queue-deadline-ms", type=float, default=30000.0,
                   help="per-query deadline covering queue wait "
                        "through readback; exceeded queries shed with "
                        "503 (pio_query_deadline_exceeded_total). "
                        "0 disables")
    s.add_argument("--assemble-workers", type=int, default=1,
                   help="staged pipeline: host threads parsing/"
                        "supplementing the next batch (raise for "
                        "storage-heavy supplements)")
    s.add_argument("--readback-workers", type=int, default=4,
                   help="staged pipeline: host threads blocking on "
                        "device results + serializing")
    s.add_argument("--pipeline-depth", type=int, default=0,
                   help="staged pipeline: bounded in-flight batches "
                        "per lane (the backpressure knob); 0 = auto "
                        "(1 on CPU, 4 on accelerators)")
    s.add_argument("--cache", action="store_true",
                   help="serving cache hierarchy: query-result + "
                        "feature caches and the device-resident "
                        "hot-entity tier (docs/serving-cache.md)")
    s.add_argument("--cache-entries", type=int, default=8192,
                   help="query-result cache capacity (entries)")
    s.add_argument("--cache-ttl", type=float, default=30.0,
                   help="query-result staleness bound (seconds)")
    s.add_argument("--feature-ttl", type=float, default=5.0,
                   help="serving-time event-store read staleness "
                        "bound (seconds)")
    s.add_argument("--hot-entities", type=int, default=512,
                   help="hottest entities pinned on device (0 off)")
    s.add_argument("--debug-locks", action="store_true",
                   help="instrument every serving-stack lock: live "
                        "lock-order/re-entry detection, pio_lock_* "
                        "series, deadlock watchdog (staging tool; "
                        "PTPU_DEBUG_LOCKS=1 works too)")
    s.add_argument("--serving-mode", default="single",
                   choices=["auto", "single", "replicated", "sharded"],
                   help="mesh-wide serving (docs/sharded-serving.md): "
                        "replicated = full model copy per device, "
                        "micro-batches fan out per-device (~Nx qps); "
                        "sharded = factor tables row-sharded over the "
                        "(batch, model) mesh (models > one HBM); "
                        "auto = sharded when the model exceeds the "
                        "per-device HBM headroom, else replicated")
    s.add_argument("--serving-quant", default="off",
                   choices=["off", "bf16", "int8"],
                   help="row-quantized serving factor tables "
                        "(docs/kernels.md): int8 = per-row-scaled "
                        "int8 storage (~4x users per HBM, ~4x less "
                        "bandwidth per scored batch) with f32 "
                        "accumulation; bf16 halves both; guarded by "
                        "a deploy-time NDCG@10 parity probe that "
                        "auto-falls-back to f32")
    s.add_argument("--serving-topk", default="auto",
                   choices=["auto", "einsum", "fused"],
                   help="batched-lane top-k realization: fused = the "
                        "Pallas gather->score->top-k kernel (the "
                        "[B, I] score matrix never lands in HBM), "
                        "einsum = the XLA baseline, auto = the "
                        "support-gated autotune table")
    s.add_argument("--stream", action="store_true",
                   help="streaming incremental training "
                        "(docs/streaming.md): a trainer daemon tails "
                        "the event log and folds fresh events into "
                        "the serving model within seconds")
    s.add_argument("--stream-app", default="",
                   help="app whose event log the trainer tails "
                        "(defaults to --feedback-app-name)")
    s.add_argument("--stream-interval-ms", type=float, default=500.0,
                   help="fold-in poll fallback; in-process ingest "
                        "wakes the trainer immediately via the bus")
    s.add_argument("--stream-max-events", type=int, default=2048,
                   help="events consumed per fold-in micro-batch")
    s.add_argument("--stream-consumer", default="stream-trainer",
                   help="durable cursor identity (resume point "
                        "survives restarts under this name)")
    s.add_argument("--stream-drift-threshold", type=float, default=1.0,
                   help="DriftMonitor score that flags a full retrain")
    s.add_argument("--stream-canary-probes", type=int, default=8,
                   help="touched-entity probes gating each fold-in "
                        "delta (0 disables the canary gate)")
    s.add_argument("--faults", default="",
                   help="fault-injection spec for failure drills "
                        "(docs/reliability.md), e.g. "
                        "'serving.lane=error,lane=1,times=5'; the "
                        "PTPU_FAULTS env var works on every server")
    s.add_argument("--no-trace", action="store_true",
                   help="disable end-to-end request tracing "
                        "(docs/tracing.md; on by default — every "
                        "request traced, only slow/error/503/fault "
                        "traces retained)")
    s.add_argument("--trace-ring", type=int, default=512,
                   help="retained traces the flight-recorder ring "
                        "holds (oldest evicted)")
    s.add_argument("--trace-slow-ms", type=float, default=0.0,
                   help="fixed slow-retention threshold in ms; 0 = "
                        "adaptive (live p99 of traced durations)")
    s.add_argument("--access-log-sample", type=float, default=1.0,
                   help="fraction of successful requests written to "
                        "the JSON access log (errors/503s always "
                        "log); 1.0 = every request")
    s.add_argument("--profile-dir", default="",
                   help="artifact dir for POST /profile device "
                        "captures (default $PTPU_PROFILE_DIR or "
                        "<tmp>/ptpu-profiles)")
    s.add_argument("--slo-specs", default="",
                   help="SLO spec file (docs/slo.md) evaluated "
                        "continuously against this server's metrics; "
                        "default: the built-in availability/latency/"
                        "freshness objectives")
    s.add_argument("--slo-interval-ms", type=float, default=1000.0,
                   help="SLO evaluation tick; 0 disables the engine")
    s.add_argument("--hot-keys-k", type=int, default=128,
                   help="Space-Saving hot-key sketch capacity: every "
                        "entity hotter than 1/k of query traffic is "
                        "guaranteed tracked (pio_hot_keys, the "
                        "/status.json hotKeys block; docs/fleet.md). "
                        "0 disables")
    s.add_argument("--fleet-of", type=int, default=1,
                   help="deploy N replicas on consecutive ports "
                        "fronted by the fleet aggregator "
                        "(docs/fleet.md): merged metrics, fleet-scoped "
                        "SLOs, cross-replica trace lookup")
    s.add_argument("--fleet-port", type=int, default=8200,
                   help="port the fleet aggregator listens on "
                        "(--fleet-of > 1)")
    s.add_argument("--fleet-scrape-interval-ms", type=float,
                   default=5000.0,
                   help="aggregator scrape cadence over the replicas")
    s.add_argument("--router-port", type=int, default=8100,
                   help="port the entity-affinity query router "
                        "listens on (--fleet-of > 1; "
                        "docs/autoscaling.md). Clients send "
                        "/queries.json here instead of to a replica")
    s.add_argument("--autoscale", action="store_true",
                   help="run the SLO-driven autoscaler: scale out on "
                        "fast-window burn or low capacity headroom, "
                        "in against the CAPACITY.json knee with "
                        "hysteresis + cooldown (docs/autoscaling.md)")
    s.add_argument("--min-replicas", type=int, default=1,
                   help="autoscaler floor (--autoscale)")
    s.add_argument("--max-replicas", type=int, default=8,
                   help="autoscaler ceiling (--autoscale)")
    s.add_argument("--capacity", default="",
                   help="CAPACITY.json for the fleet headroom gauge "
                        "and the autoscaler's knee model "
                        "(benchmarks/load_harness.py output)")
    s.add_argument("--artifact-dir", default="",
                   help="warm from the AOT artifact store `ptpu build "
                        "--aot` wrote there (docs/cold-start.md): "
                        "deploy loads serialized serving executables "
                        "instead of compiling, with automatic "
                        "fallback to compile on any key mismatch. "
                        "Empty disables (the compile warm)")

    s = sub.add_parser("undeploy", help="stop a deployed engine")
    s.add_argument("--ip", default="127.0.0.1")
    s.add_argument("--port", type=int, default=8000)
    s.add_argument("--accesskey", default="",
                   help="access key if the server was deployed with one")
    s.add_argument("--https", action="store_true",
                   help="the server was deployed with --cert/--key")
    s.add_argument("--insecure", action="store_true",
                   help="skip TLS certificate verification (self-signed "
                        "local certs only)")

    s = sub.add_parser(
        "release",
        help="progressive delivery: list/show/pin releases, drive "
             "canary/shadow rollouts, promote, roll back")
    rel_sub = s.add_subparsers(dest="release_command", required=True)

    def add_release_flags(sp, server: bool = False):
        add_engine_flags(sp)
        sp.add_argument("--reason", default="",
                        help="recorded in the release history")
        if server:
            sp.add_argument("--ip", default="127.0.0.1")
            sp.add_argument("--port", type=int, default=8000)
            sp.add_argument("--accesskey", default="")
            sp.add_argument("--https", action="store_true")
            sp.add_argument("--insecure", action="store_true")

    rel_sub.add_parser("list", help="every engine with release state")
    r = rel_sub.add_parser("show", help="full state + history (JSON)")
    add_release_flags(r)
    r.add_argument("--limit", type=int, default=50,
                   help="history entries to include")
    r = rel_sub.add_parser(
        "pin", help="pin deploy/reload to an instance id")
    add_release_flags(r)
    r.add_argument("instance_id", nargs="?", default="")
    r.add_argument("--clear", action="store_true",
                   help="unpin (bind latest COMPLETED again)")
    r = rel_sub.add_parser(
        "canary", help="start a health-gated canary of an instance "
                       "on the running engine server")
    add_release_flags(r, server=True)
    r.add_argument("instance_id")
    r.add_argument("--fraction", default="",
                   help="initial candidate traffic fraction "
                        "(e.g. 0.05 or 5%%; default: first ramp step)")
    r.add_argument("--shadow", action="store_true",
                   help="mirror queries to the candidate without "
                        "returning its answers (never auto-promotes)")
    r = rel_sub.add_parser(
        "promote", help="promote the live candidate to pinned stable")
    add_release_flags(r, server=True)
    r = rel_sub.add_parser(
        "rollback", help="abort the live candidate (or revert stable "
                         "to the previous release)")
    add_release_flags(r, server=True)
    r = rel_sub.add_parser(
        "status", help="live /release.json from the engine server "
                       "(falls back to storage state)")
    add_release_flags(r, server=True)

    s = sub.add_parser(
        "cache", help="serving cache: per-tier stats, operator flush")
    cache_sub = s.add_subparsers(dest="cache_command", required=True)
    for name, helptext in (("stats", "per-tier hit/miss/eviction/"
                                     "invalidation stats"),
                           ("flush", "flush every cache tier")):
        c = cache_sub.add_parser(name, help=helptext)
        c.add_argument("--ip", default="127.0.0.1")
        c.add_argument("--port", type=int, default=8000)
        c.add_argument("--accesskey", default="")
        c.add_argument("--https", action="store_true")
        c.add_argument("--insecure", action="store_true")

    s = sub.add_parser(
        "stream", help="streaming incremental training: attach/stop/"
                       "inspect the event→model loop on a running "
                       "engine server (docs/streaming.md)")
    stream_sub = s.add_subparsers(dest="stream_command", required=True)
    for name, helptext in (
            ("start", "attach the incremental trainer"),
            ("status", "trainer state, cursor, drift, model lineage"),
            ("stop", "stop the trainer (the durable cursor stays)")):
        c = stream_sub.add_parser(name, help=helptext)
        c.add_argument("--ip", default="127.0.0.1")
        c.add_argument("--port", type=int, default=8000)
        c.add_argument("--accesskey", default="")
        c.add_argument("--https", action="store_true")
        c.add_argument("--insecure", action="store_true")
        if name == "start":
            c.add_argument("--app", default="",
                           help="app whose event log to tail (falls "
                                "back to the server's deploy config)")
            c.add_argument("--channel", default="")
            c.add_argument("--consumer", default="",
                           help="durable cursor identity")
            c.add_argument("--interval-ms", type=float, default=None)
            c.add_argument("--max-events", type=int, default=None)
            c.add_argument("--drift-threshold", type=float,
                           default=None)
            c.add_argument("--canary-probes", type=int, default=None)

    s = sub.add_parser(
        "slo", help="service-level objectives: live burn rates from a "
                    "running server, or capacity-gate a load_harness "
                    "run against committed SLOs (docs/slo.md)")
    slo_sub = s.add_subparsers(dest="slo_command", required=True)
    c = slo_sub.add_parser(
        "status", help="per-spec burn rates, budgets, breach state "
                       "from GET /slo.json (exit 1 while burning)")
    c.add_argument("--ip", default="127.0.0.1")
    c.add_argument("--port", type=int, default=8000)
    c.add_argument("--accesskey", default="")
    c.add_argument("--https", action="store_true")
    c.add_argument("--insecure", action="store_true")
    c = slo_sub.add_parser(
        "check", help="gate a CAPACITY.json against the committed "
                      "spec file's capacity section (the CI merge "
                      "gate; regressions fail naming spec, window, "
                      "and measured value)")
    c.add_argument("--capacity", default="CAPACITY.json",
                   help="capacity model emitted by "
                        "benchmarks/load_harness.py")
    c.add_argument("--specs", default="slo/specs/ci.json",
                   help="committed SLO spec file with the capacity "
                        "gates")
    c.add_argument("--update", action="store_true",
                   help="ratchet: tighten committed gates toward a "
                        "better measurement (never loosens)")

    s = sub.add_parser(
        "trace", help="flight recorder: list the slowest retained "
                      "traces or export one as Perfetto JSON "
                      "(docs/tracing.md)")
    s.add_argument("--ip", default="127.0.0.1")
    s.add_argument("--port", type=int, default=8000)
    s.add_argument("--accesskey", default="")
    s.add_argument("--https", action="store_true")
    s.add_argument("--insecure", action="store_true")
    s.add_argument("--id", default="",
                   help="export this retained trace as Chrome/"
                        "Perfetto trace-event JSON")
    s.add_argument("--slowest", type=int, default=None,
                   help="list the N slowest retained traces")
    s.add_argument("-o", "--output", default="",
                   help="output file for --id (default "
                        "trace-<id>.json)")

    s = sub.add_parser(
        "fleet", help="fleet observability plane (docs/fleet.md): run "
                      "the aggregator that merges N replicas' metrics "
                      "exactly, or query a running one")
    fleet_sub = s.add_subparsers(dest="fleet_command", required=True)
    c = fleet_sub.add_parser(
        "serve", help="run the aggregator over --replicas: merged "
                      "/metrics, fleet SLOs, cross-replica traces, "
                      "hot keys")
    c.add_argument("--replicas", required=True,
                   help="comma-separated replica addresses "
                        "(host:port or full URLs)")
    c.add_argument("--ip", default="0.0.0.0")
    c.add_argument("--port", type=int, default=8200)
    c.add_argument("--scrape-interval-ms", type=float, default=5000.0,
                   help="how often each replica's /metrics.json and "
                        "/status.json are pulled and merged")
    c.add_argument("--stale-after-ms", type=float, default=0.0,
                   help="a replica unscraped this long is DOWN "
                        "(default: 3x the scrape interval)")
    c.add_argument("--slo-specs", default="",
                   help="SLO spec file evaluated against the MERGED "
                        "series (fleet-scoped burn rates); default: "
                        "built-in availability/latency objectives")
    c.add_argument("--slo-interval-ms", type=float, default=1000.0,
                   help="fleet SLO evaluation tick; 0 disables")
    c.add_argument("--capacity", default="",
                   help="CAPACITY.json (load_harness output); its "
                        "knee qps feeds pio_fleet_capacity_headroom")
    c.add_argument("--hot-keys-k", type=int, default=128,
                   help="fleet-wide merged hot-key sketch capacity")
    c.add_argument("--timeout-sec", type=float, default=5.0,
                   help="per-replica scrape/fan-out timeout")
    c.add_argument("--accesskey", default="",
                   help="require ?accessKey= on POST /scrape and "
                        "POST /stop")
    c.add_argument("--cert", default="", help="PEM cert to serve HTTPS")
    c.add_argument("--key", default="", help="PEM private key")
    for name, helptext in (
            ("status", "per-replica liveness/lag/flags + fleet "
                       "headroom (exit 1 on down replicas or a "
                       "burning fleet SLO; a replica the autoscaler "
                       "removed on purpose is NOT down)"),
            ("slo", "fleet SLO burn rates from the merged series"),
            ("trace", "cross-replica flight-recorder lookup"),
            ("hotkeys", "fleet-wide hot-key top-K"),
            ("route", "query-router view: ring membership, per-"
                      "backend state/inflight, hot-key spill "
                      "(--key shows one entity's placement)"),
            ("scale", "ask the autoscaler for a replica count "
                      "(clamped to --min/--max-replicas)")):
        c = fleet_sub.add_parser(name, help=helptext)
        c.add_argument("--ip", default="127.0.0.1")
        c.add_argument("--port", type=int, default=8200)
        c.add_argument("--accesskey", default="")
        c.add_argument("--https", action="store_true")
        c.add_argument("--insecure", action="store_true")
        if name == "trace":
            c.add_argument("--id", default="",
                           help="fan the id out to every replica and "
                                "export the hit as Perfetto JSON")
            c.add_argument("--slowest", type=int, default=None,
                           help="the fleet's N slowest retained "
                                "traces, merged")
            c.add_argument("-o", "--output", default="",
                           help="output file for --id")
        if name == "hotkeys":
            c.add_argument("--top", type=int, default=16,
                           help="keys to list")
        if name == "route":
            c.add_argument("--key", default="",
                           help="show where this entity id routes "
                                "(affinity + preference list)")
        if name == "scale":
            c.add_argument("--to", type=int, required=True,
                           help="desired replica count")
            c.add_argument("--reason", default="",
                           help="recorded in the decision log")

    s = sub.add_parser("batchpredict", help="bulk predict JSON lines")
    add_engine_flags(s)
    s.add_argument("--input", required=True)
    s.add_argument("--output", required=True)

    s = sub.add_parser("eventserver", help="start the Event Server")
    s.add_argument("--ip", default="0.0.0.0")
    s.add_argument("--port", type=int, default=7070)
    s.add_argument("--stats", action="store_true")
    s.add_argument("--cert", default="", help="PEM cert to serve HTTPS")
    s.add_argument("--key", default="", help="PEM private key")

    s = sub.add_parser("storageserver",
                       help="serve storage to REMOTE-backend clients")
    s.add_argument("--ip", default="0.0.0.0")
    s.add_argument("--port", type=int, default=7077)
    s.add_argument("--secret", default="",
                   help="shared secret clients must send")
    s.add_argument("--cert", default="", help="PEM cert to serve HTTPS")
    s.add_argument("--key", default="", help="PEM private key")

    s = sub.add_parser("adminserver", help="start the admin API")
    s.add_argument("--ip", default="127.0.0.1")
    s.add_argument("--port", type=int, default=7071)
    s.add_argument("--accesskey", default="")
    s.add_argument("--cert", default="")
    s.add_argument("--key", default="")

    s = sub.add_parser("dashboard", help="start the evaluation dashboard")
    s.add_argument("--ip", default="127.0.0.1")
    s.add_argument("--port", type=int, default=9000)
    s.add_argument("--accesskey", default="")
    s.add_argument("--cert", default="")
    s.add_argument("--key", default="")

    s = sub.add_parser("start-all", help="start event/admin/dashboard "
                       "(and optionally storage) servers as daemons "
                       "with pidfiles")
    s.add_argument("--ip", default="0.0.0.0")
    s.add_argument("--pid-dir", default="",
                   help="pidfile/log dir (default ~/.ptpu or "
                        "$PIO_PID_DIR)")
    s.add_argument("--eventserver-port", dest="event_port", type=int,
                   default=0)
    s.add_argument("--adminserver-port", dest="admin_port", type=int,
                   default=0)
    s.add_argument("--dashboard-port", dest="dash_port", type=int,
                   default=0)
    s.add_argument("--with-storageserver", action="store_true",
                   help="also start the remote-backend storage server")
    s.add_argument("--storageserver-port", dest="storage_port",
                   type=int, default=0)
    s.add_argument("--storage-secret", default="")
    s.add_argument("--start-timeout", type=float, default=30.0)

    s = sub.add_parser("stop-all", help="stop every start-all daemon")
    s.add_argument("--pid-dir", default="")
    s.add_argument("--stop-timeout", type=float, default=10.0)

    s = sub.add_parser("status", help="check environment and storage")
    s.add_argument("--ip", default="",
                   help="also query a live engine server's "
                        "/status.json for serving model lineage")
    s.add_argument("--port", type=int, default=8000)
    s.add_argument("--accesskey", default="")
    s.add_argument("--https", action="store_true")
    s.add_argument("--insecure", action="store_true")

    s = sub.add_parser("export", help="export events to a JSON-lines file")
    s.add_argument("--appid", type=int, default=0)
    s.add_argument("--app", default="")
    s.add_argument("--channel", default="")
    s.add_argument("--output", required=True)

    s = sub.add_parser("import", help="import events from JSON lines")
    s.add_argument("--appid", type=int, default=0)
    s.add_argument("--app", default="")
    s.add_argument("--channel", default="")
    s.add_argument("--input", required=True)

    s = sub.add_parser("check", help="JAX-aware + concurrency + Pallas"
                       "-kernel static analysis, interprocedural "
                       "(host-sync, recompile, donation, sharding, "
                       "config, lock-discipline, VMEM-budget, DMA, "
                       "accumulator-precision lints)")
    s.add_argument("paths", nargs="*",
                   help="files/dirs to check (default: predictionio_tpu)")
    s.add_argument("--rule", action="append", default=[],
                   help="run only the named rule (repeatable)")
    s.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    s.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text",
                   help="output format (sarif for GitHub code-scanning "
                        "PR annotations)")
    s.add_argument("--baseline", default="",
                   help="baseline file: exit 1 only on findings NOT "
                        "recorded in it (legacy-debt burn-down)")
    s.add_argument("--write-baseline", action="store_true",
                   help="record current findings into --baseline FILE; "
                        "against an existing baseline this only "
                        "RATCHETS (removes/decrements entries) and "
                        "fails on findings beyond the recorded debt")
    s.add_argument("--baseline-grow", action="store_true",
                   help="with --write-baseline: allow recording NEW "
                        "debt (e.g. when enabling a rule) instead of "
                        "the default shrink-only ratchet")

    s = sub.add_parser("audit-hlo", help="compile the SPMD entry "
                       "points on a forced 8-device CPU mesh and diff "
                       "the HLO collectives against the committed "
                       "golden manifest (the runtime complement of "
                       "the ptpu check sharding rules)")
    s.add_argument("--entry", action="append", default=[],
                   help="audit only the named entry point (repeatable)")
    s.add_argument("--list-entries", action="store_true",
                   help="print the entry-point catalogue and exit")
    s.add_argument("--format", choices=("text", "json"), default="text",
                   help="output format for the fresh manifest")
    s.add_argument("--out", default="",
                   help="also write the fresh manifest JSON to FILE "
                        "(the CI artifact)")
    s.add_argument("--baseline", default="",
                   help="golden manifest to gate against (default: "
                        "the committed analysis/hlo_baseline.json)")
    s.add_argument("--write-baseline", action="store_true",
                   help="record the fresh manifest as the baseline; "
                        "against an existing one this only RATCHETS "
                        "(shrinks counts/temps) and fails on growth")
    s.add_argument("--baseline-grow", action="store_true",
                   help="with --write-baseline: allow recording new "
                        "collectives/entries (deliberate schedule "
                        "changes) instead of the shrink-only ratchet")

    s = sub.add_parser("audit-numerics", help="abstract-interpret the "
                       "registered numeric entry points and diff the "
                       "dtype census (casts, accumulation dtypes, "
                       "bytes) against the committed golden manifest "
                       "(the runtime complement of the ptpu check "
                       "dtype-flow rules)")
    s.add_argument("--entry", action="append", default=[],
                   help="audit only the named entry point (repeatable)")
    s.add_argument("--list-entries", action="store_true",
                   help="print the entry-point catalogue and exit")
    s.add_argument("--format", choices=("text", "json"), default="text",
                   help="output format for the fresh manifest")
    s.add_argument("--out", default="",
                   help="also write the fresh manifest JSON to FILE "
                        "(the CI artifact)")
    s.add_argument("--baseline", default="",
                   help="golden manifest to gate against (default: the "
                        "committed analysis/numerics_baseline.json)")
    s.add_argument("--write-baseline", action="store_true",
                   help="record the fresh manifest as the baseline; "
                        "against an existing one this only RATCHETS "
                        "(shrinks counts/bytes) and fails on growth")
    s.add_argument("--baseline-grow", action="store_true",
                   help="with --write-baseline: allow recording new "
                        "casts/entries (deliberate precision changes) "
                        "instead of the shrink-only ratchet")

    s = sub.add_parser("audit-lifecycle", help="boot each subsystem, "
                       "drive start->serve->stop cycles, snapshot "
                       "/proc threads/fds/sockets around them and "
                       "gate the leak census against the committed "
                       "golden manifest (the runtime complement of "
                       "the ptpu check lifecycle rules)")
    s.add_argument("--entry", action="append", default=[],
                   help="audit only the named entry point (repeatable)")
    s.add_argument("--list-entries", action="store_true",
                   help="print the entry-point catalogue and exit")
    s.add_argument("--cycles", type=int, default=3,
                   help="measured start->stop cycles per entry "
                        "(default 3; one extra warmup cycle always "
                        "runs unmeasured)")
    s.add_argument("--format", choices=("text", "json"), default="text",
                   help="output format for the fresh manifest")
    s.add_argument("--out", default="",
                   help="also write the fresh manifest JSON to FILE "
                        "(the CI artifact)")
    s.add_argument("--baseline", default="",
                   help="golden manifest to gate against (default: the "
                        "committed analysis/lifecycle_baseline.json)")
    s.add_argument("--write-baseline", action="store_true",
                   help="record the fresh manifest as the baseline; "
                        "against an existing one this only RATCHETS "
                        "(shrinks the allowed leaks) and fails on "
                        "growth")
    s.add_argument("--baseline-grow", action="store_true",
                   help="with --write-baseline: allow recording new "
                        "entries / larger allowances (deliberate "
                        "daemon changes) instead of the shrink-only "
                        "ratchet")

    sub.add_parser("template", help="list bundled engine templates")
    sub.add_parser("shell", help="interactive shell with storage preloaded")
    s = sub.add_parser("run", help="run module.path:callable with storage "
                                   "configured")
    s.add_argument("target")
    s.add_argument("args", nargs="*")
    sub.add_parser("version", help="print version")
    return p


COMMANDS = {
    "app": cmd_app,
    "accesskey": cmd_accesskey,
    "build": cmd_build,
    "train": cmd_train,
    "eval": cmd_eval,
    "deploy": cmd_deploy,
    "undeploy": cmd_undeploy,
    "release": cmd_release,
    "cache": cmd_cache,
    "stream": cmd_stream,
    "slo": cmd_slo,
    "trace": cmd_trace,
    "batchpredict": cmd_batchpredict,
    "start-all": cmd_start_all,
    "stop-all": cmd_stop_all,
    "eventserver": cmd_eventserver,
    "storageserver": cmd_storageserver,
    "adminserver": cmd_adminserver,
    "dashboard": cmd_dashboard,
    "status": cmd_status,
    "shell": cmd_shell,
    "run": cmd_run,
    "export": cmd_export,
    "import": cmd_import,
    "template": cmd_template,
}


def main(argv: Optional[List[str]] = None,
         storage: Optional[Storage] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "version":
        _out(__version__)
        return 0
    if args.command == "check":
        # pure-AST lint: needs neither storage nor jax
        return cmd_check(args)
    if args.command == "fleet":
        # pure HTTP against replicas/aggregator: no storage, no jax
        return cmd_fleet(args)
    if args.command == "audit-hlo":
        # needs jax on a forced virtual mesh, but no storage; the
        # device topology MUST be pinned before the first jax import
        from ..analysis.hlo_audit import ensure_cpu_devices

        ensure_cpu_devices()
        return cmd_audit_hlo(args)
    if args.command == "audit-numerics":
        # jaxpr tracing only (no compile), but half the entries trace
        # through 8-device meshes — same topology pin as audit-hlo
        from ..analysis.numerics_audit import ensure_cpu_devices

        ensure_cpu_devices()
        return cmd_audit_numerics(args)
    if args.command == "audit-lifecycle":
        # boots real (loopback) servers; the engine entries train and
        # serve a tiny model — pin host devices before the first jax
        # import so the audit never waits on an accelerator runtime
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return cmd_audit_lifecycle(args)
    if args.command in ("train", "eval", "deploy", "batchpredict",
                        "run", "shell", "status"):
        # device-using commands share one persistent XLA program cache
        # (the JVM-warmup analogue); storage-only commands skip it so
        # they never pay the jax import
        from ..utils.platform import enable_compilation_cache
        enable_compilation_cache()
    if os.environ.get("PIO_COORDINATOR") \
            or os.environ.get("PIO_NUM_PROCESSES"):
        # join the multi-controller system before any device use (the
        # spark-submit --master role; TPU pods auto-detect without these)
        from ..parallel.multihost import initialize_distributed

        initialize_distributed()
    st = storage if storage is not None else get_storage()
    return COMMANDS[args.command](args, st)


if __name__ == "__main__":
    sys.exit(main())
