"""``python -m predictionio_tpu.cli`` — the ``bin/pio`` entry point."""

import sys

from . import main

sys.exit(main())
