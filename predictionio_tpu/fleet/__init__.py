"""Fleet observability plane (ISSUE 17, docs/fleet.md).

Makes N QueryServer replicas legible as ONE system: a
:class:`FleetAggregator` scrapes every replica's full-fidelity
``/metrics.json`` exposition and merges it exactly — counters sum
(reset-compensated), gauges gain per-replica labels plus min/max/sum
rollups, histograms add per-bucket counts so every merged quantile is
the pooled-population quantile at bucket resolution. On top: a
fleet-scoped SLO engine over the merged series, cross-replica trace
lookup, fleet-wide hot-key telemetry, and capacity headroom against
the committed CAPACITY.json knee. ``ptpu fleet serve`` (or
``ptpu deploy --fleet-of N``) boots one.
"""

from .aggregator import (
    FleetAggregator,
    FleetConfig,
    build_fleet_app,
    create_fleet_server,
)

__all__ = [
    "FleetAggregator",
    "FleetConfig",
    "build_fleet_app",
    "create_fleet_server",
]
