"""Fleet aggregator: N replica registries merged into one, exactly.

The scrape loop pulls every replica's ``GET /metrics.json`` (the
full-fidelity JSON exposition — raw cumulative histogram buckets, not
percentile summaries) plus ``GET /status.json``, and folds them into
ONE local :class:`~predictionio_tpu.obs.MetricsRegistry` under exact
merge semantics:

- **counters sum** — each replica contributes the DELTA since its last
  scrape, reset-compensated: a replica restart (raw value regressed)
  contributes its full new value instead of a negative delta, so the
  merged series stays monotone and equals the sum of per-replica
  lifetimes (``pio_fleet_counter_resets_total`` counts the splices);
- **gauges get per-replica labels** (``replica="host:port"``) plus
  ``agg="min"|"max"|"sum"`` rollup children recomputed over the
  currently-live replicas each cycle;
- **histograms merge losslessly** at bucket resolution — per-bucket
  cumulative-count deltas are themselves valid histograms
  (:func:`~predictionio_tpu.obs.histogram.window_quantile`'s identity),
  rebuilt via ``StreamingHistogram.from_buckets`` and added into the
  fleet child with ``StreamingHistogram.merge``. A quantile of the
  merged child is therefore the POOLED-POPULATION quantile of every
  observation any replica recorded — never an average of per-replica
  percentiles, which has no statistical meaning (docs/fleet.md walks
  the two-replica counterexample).

On top of the merged registry ride the fleet services: a fleet-scoped
:class:`~predictionio_tpu.slo.SLOEngine` (burn rates finally mean "the
service", not "one process"), ``GET /fleet.json`` (liveness, staleness,
degraded/nonfinite flags, capacity headroom vs the committed
CAPACITY.json knee), cross-replica ``GET /trace.json?id=`` fan-out, and
the fleet-wide hot-key top-K (per-replica Space-Saving sketches merged
each cycle).
"""

from __future__ import annotations

import json
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs import MetricsRegistry, SpaceSaving, StreamingHistogram
from ..obs.hotkeys import mount_hot_key_metrics
from ..obs.runtime import register_process_metrics
from ..server.http import (
    AppServer,
    HTTPApp,
    HTTPError,
    Request,
    Response,
    json_response,
    make_key_auth,
    mount_metrics,
)

__all__ = ["FleetConfig", "FleetAggregator", "build_fleet_app",
           "create_fleet_server"]

#: Families NEVER merged from replicas: the pio_slo_* series on the
#: fleet registry belong to the fleet's OWN SLOEngine (evaluated over
#: the merged series — THE fleet verdict); a replica's local verdicts
#: would collide with it child-for-child and mean something else
#: entirely. Per-replica SLO state still surfaces through /fleet.json.
_MERGE_SKIP = frozenset({
    "pio_slo_burn_rate",
    "pio_slo_budget_remaining",
    "pio_slo_breach",
    "pio_slo_violations_total",
})


@dataclass
class FleetConfig:
    """Knobs of the fleet observability plane (``ptpu fleet serve``)."""

    #: replica base addresses: ``host:port`` or full ``http://`` URLs
    replicas: List[str] = field(default_factory=list)
    scrape_interval_sec: float = 5.0
    #: a replica with no successful scrape for this long is DOWN
    #: (drops out of gauge rollups, hot-key merge, and headroom
    #: denominators); None = 3x the scrape interval
    stale_after_sec: Optional[float] = None
    #: SLO spec file evaluated against the MERGED registry
    #: (slo/specs/*.json); None = the built-in default specs
    slo_specs: Optional[str] = None
    #: fleet SLO evaluation tick; 0 disables the fleet SLO engine
    slo_interval_sec: float = 1.0
    #: committed capacity model (benchmarks/load_harness.py output);
    #: the knee qps feeds the fleet headroom gauge
    capacity_path: Optional[str] = None
    #: capacity of the fleet-wide merged hot-key sketch
    hot_keys_k: int = 128
    #: per-request timeout for replica scrapes/fan-outs
    timeout_sec: float = 5.0
    #: ?accessKey= guard on the control routes (POST /scrape, /stop)
    accesskey: Optional[str] = None

    @property
    def stale_after(self) -> float:
        if self.stale_after_sec is not None:
            return self.stale_after_sec
        return 3.0 * max(self.scrape_interval_sec, 0.25)


def _normalize(replica: str) -> Tuple[str, str]:
    """``(name, base_url)`` for a replica spec: the label keeps the
    compact host:port form, the base URL gains a scheme if absent."""
    r = replica.strip().rstrip("/")
    if "://" in r:
        name = r.split("://", 1)[1]
        return name, r
    return r, "http://" + r


def _default_fetch(url: str, timeout: float) -> Tuple[int, Any]:
    """``(status, parsed-json)`` for a GET; non-2xx returns its code
    with whatever body parsed (the trace fan-out needs clean 404s)."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.getcode(), json.loads(
                resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:  # non-2xx, NOT a dead replica
        try:
            body = json.loads(e.read().decode("utf-8"))
        except Exception:  # noqa: BLE001 — non-JSON error body
            body = None
        return e.code, body


class _ReplicaState:
    """Per-replica scrape bookkeeping: last raw counter/histogram
    readings (the reset-compensation anchors), last gauge values (the
    rollup inputs), and the last /status.json body."""

    def __init__(self, name: str, base: str) -> None:
        self.name = name
        self.base = base
        self.last_ok: Optional[float] = None     # monotonic
        self.last_err: Optional[str] = None
        self.scrape_sec = 0.0
        self.status: Dict[str, Any] = {}
        # (family, label items) → last raw reading
        self.counters: Dict[Tuple[str, Tuple], float] = {}
        # (family, label items) → (per-bucket counts, sum)
        self.hists: Dict[Tuple[str, Tuple], Tuple[List[int], float]] = {}
        self.gauges: Dict[Tuple[str, Tuple], float] = {}

    def up(self, now: float, stale_after: float) -> bool:
        return (self.last_ok is not None
                and now - self.last_ok <= stale_after)

    @property
    def draining(self) -> bool:
        """The replica announced lifecycle=draining in /status.json:
        it is finishing in-flight work and will exit — alive, but no
        longer part of the fleet's capacity."""
        return (self.status or {}).get("lifecycle") == "draining"

    def serving(self, now: float, stale_after: float) -> bool:
        """Up AND not draining — the population gauge rollups, the
        hot-key union, and the headroom denominator are computed
        over."""
        return self.up(now, stale_after) and not self.draining


class FleetAggregator:
    """Owns the merged registry, the scrape loop, and the fleet SLO
    engine. ``fetch(url, timeout) -> (status, json)`` is injectable so
    tests drive merges without sockets."""

    def __init__(self, config: FleetConfig,
                 fetch: Optional[Callable[[str, float],
                                          Tuple[int, Any]]] = None
                 ) -> None:
        if not config.replicas:
            raise ValueError("FleetConfig needs at least one replica")
        self.config = config
        self.fetch = fetch or _default_fetch
        self.registry = MetricsRegistry()
        self._states = {}
        for r in config.replicas:
            name, base = _normalize(r)
            self._states[name] = _ReplicaState(name, base)
        # merge anchors of replicas that left (scale-in): if the same
        # name rejoins — a restart on the same port — its counters
        # resume from the last raw reading instead of re-contributing
        # their whole lifetime to the merged series
        self._anchor_tombstones: Dict[str, Tuple[Dict, Dict]] = {}
        # attached control plane (deploy --autoscale wires these)
        self.autoscaler = None
        self.router = None
        # one cycle at a time: the interval loop and POST /scrape must
        # not interleave half-applied deltas
        self._cycle_lock = threading.Lock()
        self._cycles = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # fleet qps estimate: merged /queries.json request total
        # deltas between cycles
        self._last_queries: Optional[Tuple[float, float]] = None
        self._knee_qps = self._load_knee(config.capacity_path)

        reg = self.registry
        self._scrapes = reg.counter(
            "pio_fleet_scrapes_total",
            "Replica scrape attempts by outcome (ok|error)")
        self._scrape_hist = reg.histogram(
            "pio_fleet_scrape_seconds",
            "Wall time of one replica scrape (fetch + merge)",
            bounds=[0.001 * (2.0 ** i) for i in range(14)])
        self._cycles_total = reg.counter(
            "pio_fleet_scrape_cycles_total",
            "Completed scrape cycles over the whole fleet")
        self._resets = reg.counter(
            "pio_fleet_counter_resets_total",
            "Counter/histogram regressions absorbed by reset "
            "compensation (a replica restarted; merged series stayed "
            "monotone)")
        self._merge_errors = reg.counter(
            "pio_fleet_merge_errors_total",
            "Families that could not be merged (kind or bucket-layout "
            "conflict across replicas)")
        self._up_gauge = reg.gauge(
            "pio_fleet_replica_up",
            "1 while the replica's last successful scrape is fresher "
            "than the staleness bound")
        self._age_fam = reg.gauge(
            "pio_fleet_last_scrape_age_seconds",
            "Seconds since the replica last answered a scrape "
            "(monotone-clock read at render time)")
        for st in self._states.values():
            self._register_replica_gauges(st)
        replicas_fam = reg.gauge(
            "pio_fleet_replicas",
            "Replicas by state (configured|up|draining); membership "
            "is dynamic under autoscaling, so every child is "
            "recomputed at render time")
        replicas_fam.labels(state="configured").set_fn(
            lambda: float(len(self._states)))
        replicas_fam.labels(state="up").set_fn(
            lambda: float(sum(
                1 for s in list(self._states.values())
                if s.up(time.monotonic(), self.config.stale_after))))
        replicas_fam.labels(state="draining").set_fn(
            lambda: float(sum(
                1 for s in list(self._states.values())
                if s.draining)))
        self._qps_gauge = reg.gauge(
            "pio_fleet_qps",
            "Fleet-wide /queries.json request rate estimated from "
            "merged counter deltas between scrape cycles")
        self._headroom_gauge = reg.gauge(
            "pio_fleet_capacity_headroom",
            "1 - qps / (knee_qps x replicas up) against the committed "
            "CAPACITY.json knee; negative = over capacity, -1 when no "
            "capacity model is loaded")
        self._headroom_gauge.set(-1.0)
        # fleet-wide hot keys: REBUILT from the per-replica cumulative
        # sketches every cycle (accumulating them each cycle would
        # double-count), swapped atomically for the collector
        self.hot = SpaceSaving(capacity=config.hot_keys_k)
        mount_hot_key_metrics(reg, _HotProxy(self), top_n=10)
        register_process_metrics(reg)

        self.slo = None
        if config.slo_interval_sec > 0:
            from ..slo import SLOEngine, default_specs, load_specs

            if config.slo_specs:
                specs, _ = load_specs(config.slo_specs)
            else:
                specs = default_specs()
            self.slo = SLOEngine(reg, specs)
            self.slo.register_metrics(reg)

    @staticmethod
    def _load_knee(path: Optional[str]) -> Optional[float]:
        """Best knee qps in the committed capacity model (the
        single-replica ceiling the headroom gauge scales by fleet
        size); None without a model."""
        if not path:
            return None
        with open(path, encoding="utf-8") as f:
            capacity = json.load(f)
        knees = [c.get("knee_qps")
                 for c in (capacity.get("configs") or {}).values()
                 if isinstance(c, dict) and c.get("knee_qps")]
        return max(knees) if knees else None

    # -- membership ---------------------------------------------------------
    def _register_replica_gauges(self, st: _ReplicaState) -> None:
        self._up_gauge.labels(replica=st.name).set(0.0)
        self._age_fam.labels(replica=st.name).set_fn(
            (lambda s: lambda: (time.monotonic() - s.last_ok)
             if s.last_ok is not None else -1.0)(st))

    def add_replica(self, replica: str) -> str:
        """Join a replica to the scrape set (idempotent); the replica
        lifecycle manager calls this once a spawn reports warm. A
        rejoining name reclaims its tombstoned merge anchors so the
        merged counters don't double-count its pre-restart lifetime."""
        name, base = _normalize(replica)
        with self._cycle_lock:
            if name in self._states:
                return name
            st = _ReplicaState(name, base)
            st.counters, st.hists = self._anchor_tombstones.pop(
                name, ({}, {}))
            self._states[name] = st
            self._register_replica_gauges(st)
        return name

    def remove_replica(self, replica: str) -> bool:
        """Remove a replica from the scrape set (scale-in terminate or
        corpse removal). Its gauge children leave the exposition; its
        merged counter/histogram contributions stay — monotone
        history — and its anchors are tombstoned for a possible
        rejoin."""
        name = _normalize(replica)[0]
        with self._cycle_lock:
            return self._remove_locked(name)

    def _remove_locked(self, name: str) -> bool:
        st = self._states.pop(name, None)
        if st is None:
            return False
        self._anchor_tombstones[name] = (st.counters, st.hists)
        for fam in self.registry.families():
            if fam.kind == "gauge":
                fam.remove_matching(replica=name)
        return True

    # -- control-plane signals ----------------------------------------------
    def capacity_signals(self) -> Dict[str, Any]:
        """The merged signals one autoscaler tick consumes. Headroom
        is ``None`` (not the -1 gauge sentinel) when no capacity model
        is loaded, so the policy can tell "plenty of room" from "no
        model to reason with"."""
        headroom = self._headroom_gauge.labels().value
        return {
            "qps": self._qps_gauge.labels().value,
            "kneeQps": self._knee_qps,
            "headroom": headroom if self._knee_qps else None,
        }

    def replica_health(self, replica: str) -> str:
        """``up`` | ``down`` | ``unknown`` | ``absent`` for the heal
        pass. A member that has never answered a scrape is
        ``unknown`` — a fresh join mid-warmup, not a corpse — so the
        autoscaler won't kill what it just spawned."""
        name = _normalize(replica)[0]
        st = self._states.get(name)
        if st is None:
            return "absent"
        if st.last_ok is None:
            return "unknown"
        return ("up"
                if st.up(time.monotonic(), self.config.stale_after)
                else "down")

    def attach_autoscaler(self, autoscaler) -> None:
        """Surface an autoscaler's decision log on ``/fleet.json`` and
        accept ``POST /scale`` requests for it."""
        self.autoscaler = autoscaler

    def attach_router(self, router) -> None:
        """Surface a query router's ring/backends on the fleet's
        ``GET /route.json``."""
        self.router = router

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "FleetAggregator":
        if self.slo is not None:
            self.slo.start(self.config.slo_interval_sec)
        self._thread = threading.Thread(
            target=self._loop, name="fleet-scraper", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.slo is not None:
            self.slo.stop()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrape_cycle()
            except Exception:  # noqa: BLE001 — the loop must survive
                pass           # any single bad cycle
            self._stop.wait(self.config.scrape_interval_sec)

    # -- scraping -----------------------------------------------------------
    def scrape_cycle(self) -> Dict[str, Any]:
        """One full pass over the fleet: scrape + merge every replica,
        then recompute the cross-replica derivations (gauge rollups,
        hot-key union, qps/headroom). Serialized; also the handler of
        ``POST /scrape`` so tests/smokes get quiescent exact state."""
        with self._cycle_lock:
            outcomes: Dict[str, Any] = {}
            departed: List[str] = []
            for st in list(self._states.values()):
                outcomes[st.name] = self._scrape_replica(st)
                if outcomes[st.name] == "departed":
                    departed.append(st.name)
            # a draining replica that stopped answering finished its
            # drain and exited: expected departure, so it leaves the
            # membership instead of flapping pio_fleet_replica_up
            for name in departed:
                self._remove_locked(name)
            self._rollup_gauges()
            self._merge_hot_keys()
            self._update_capacity()
            self._cycles += 1
            self._cycles_total.inc()
            return outcomes

    def _scrape_replica(self, st: _ReplicaState) -> str:
        t0 = time.monotonic()
        try:
            code, families = self.fetch(st.base + "/metrics.json",
                                        self.config.timeout_sec)
            if code != 200 or not isinstance(families, dict):
                raise RuntimeError(
                    f"/metrics.json answered {code}")
            self._merge_families(st, families)
            # status is best-effort enrichment: a replica whose
            # metrics merged but whose status route hiccuped is
            # still UP
            try:
                s_code, status = self.fetch(st.base + "/status.json",
                                            self.config.timeout_sec)
                if s_code == 200 and isinstance(status, dict):
                    st.status = status
            except Exception:  # noqa: BLE001
                pass
            st.last_ok = time.monotonic()
            st.last_err = None
            outcome = "ok"
        except Exception as e:  # noqa: BLE001 — a dead replica is a
            if st.draining:       # data point, not a crash
                # drain completed between scrapes — the silence is the
                # expected exit, not a failure: no error outcome, no
                # up-gauge flap, no counter-reset noise when a
                # successor reuses the port (anchors are tombstoned)
                st.scrape_sec = time.monotonic() - t0
                return "departed"
            st.last_err = str(e)
            outcome = "error"
        st.scrape_sec = time.monotonic() - t0
        self._scrape_hist.labels(replica=st.name).observe(st.scrape_sec)
        self._scrapes.labels(replica=st.name, outcome=outcome).inc()
        self._up_gauge.labels(replica=st.name).set(
            1.0 if st.up(time.monotonic(), self.config.stale_after)
            else 0.0)
        return outcome

    def _merge_families(self, st: _ReplicaState,
                        families: Dict[str, Any]) -> None:
        for name, fam in sorted(families.items()):
            if name in _MERGE_SKIP or not isinstance(fam, dict):
                continue
            kind = fam.get("kind")
            help_ = str(fam.get("help") or "")
            try:
                if kind == "counter":
                    self._merge_counter(st, name, help_, fam)
                elif kind == "histogram":
                    self._merge_histogram(st, name, help_, fam)
                elif kind == "gauge":
                    self._merge_gauge(st, name, help_, fam)
            except ValueError:
                # kind conflict across replicas or a bucket-layout
                # mismatch: count it, keep scraping — one bad family
                # must not sever the whole replica
                self._merge_errors.labels(replica=st.name,
                                          family=name).inc()

    def _merge_counter(self, st: _ReplicaState, name: str,
                       help_: str, fam: Dict[str, Any]) -> None:
        fleet_fam = self.registry.counter(name, help_)
        for child in fam.get("children") or []:
            labels = dict(child.get("labels") or {})
            raw = float(child.get("value") or 0.0)
            key = (name, tuple(sorted(labels.items())))
            last = st.counters.get(key)
            delta = raw if last is None else raw - last
            if delta < 0:
                # replica restarted: its counter began again from 0,
                # so the ENTIRE current value is new observations
                self._resets.labels(replica=st.name).inc()
                delta = raw
            st.counters[key] = raw
            if delta > 0:
                fleet_fam.labels(**labels).inc(delta)

    def _merge_histogram(self, st: _ReplicaState, name: str,
                         help_: str, fam: Dict[str, Any]) -> None:
        for child in fam.get("children") or []:
            labels = dict(child.get("labels") or {})
            buckets = child.get("buckets") or []
            if len(buckets) < 2:
                continue
            rebuilt = StreamingHistogram.from_buckets(
                buckets,
                sum=child.get("sum"),
                minimum=child.get("min"),
                maximum=child.get("max"))
            counts = list(rebuilt._counts)
            total_sum = float(child.get("sum") or 0.0)
            key = (name, tuple(sorted(labels.items())))
            last = st.hists.get(key)
            if last is not None and len(last[0]) == len(counts):
                deltas = [n - p for n, p in zip(counts, last[0])]
                dsum = total_sum - last[1]
                if any(d < 0 for d in deltas) or dsum < -1e-9:
                    # reset: the current histogram is all-new
                    self._resets.labels(replica=st.name).inc()
                    deltas, dsum = counts, total_sum
            else:
                deltas, dsum = counts, total_sum
            st.hists[key] = (counts, total_sum)
            n = sum(deltas)
            if n == 0:
                continue
            fleet_fam = self.registry.histogram(
                name, help_, bounds=rebuilt.bounds)
            fleet_child = fleet_fam.labels(**labels)
            # the delta vector is itself a valid histogram of the
            # observations that landed since the last scrape; the
            # replica's lifetime min/max bound them (bucket-resolution
            # truth — same resolution every quantile here has)
            cum: List[Tuple[float, int]] = []
            acc = 0
            for le, d in zip(list(rebuilt.bounds) + [math.inf], deltas):
                acc += d
                cum.append((le, acc))
            fleet_child.merge(StreamingHistogram.from_buckets(
                cum, sum=max(dsum, 0.0),
                minimum=child.get("min"), maximum=child.get("max")))

    def _merge_gauge(self, st: _ReplicaState, name: str,
                     help_: str, fam: Dict[str, Any]) -> None:
        fleet_fam = self.registry.gauge(name, help_)
        for child in fam.get("children") or []:
            labels = dict(child.get("labels") or {})
            value = float(child.get("value") or 0.0)
            st.gauges[(name, tuple(sorted(labels.items())))] = value
            fleet_fam.labels(replica=st.name, **labels).set(value)

    def _rollup_gauges(self) -> None:
        """``agg="min"|"max"|"sum"`` children recomputed over the
        replicas that are currently SERVING — a down replica's last
        reading must not pin a rollup forever, and a draining one is
        winding down outside the fleet's capacity (its
        ``replica=``-labeled child DOES keep its last value; check
        pio_fleet_replica_up / the lifecycle field)."""
        now = time.monotonic()
        stale = self.config.stale_after
        pools: Dict[Tuple[str, Tuple], List[float]] = {}
        for st in self._states.values():
            if not st.serving(now, stale):
                continue
            for key, v in st.gauges.items():
                pools.setdefault(key, []).append(v)
        for (name, items), vals in pools.items():
            fam = self.registry.get(name)
            if fam is None or not vals:
                continue
            labels = dict(items)
            fam.labels(agg="min", **labels).set(min(vals))
            fam.labels(agg="max", **labels).set(max(vals))
            fam.labels(agg="sum", **labels).set(sum(vals))

    def _merge_hot_keys(self) -> None:
        now = time.monotonic()
        fresh = SpaceSaving(capacity=self.config.hot_keys_k)
        for st in self._states.values():
            if not st.serving(now, self.config.stale_after):
                continue
            block = st.status.get("hotKeys") or {}
            fresh.merge_items(block.get("top") or [],
                              total=float(block.get("total") or 0.0))
        self.hot = fresh

    def _update_capacity(self) -> None:
        fam = self.registry.get("pio_http_requests_total")
        total = 0.0
        if fam is not None:
            for items, child in fam.children():
                if dict(items).get("route") == "/queries.json":
                    total += float(child.value)
        now = time.monotonic()
        qps = 0.0
        if self._last_queries is not None:
            last_t, last_total = self._last_queries
            dt = now - last_t
            if dt > 0:
                qps = max(0.0, (total - last_total) / dt)
        self._last_queries = (now, total)
        self._qps_gauge.set(qps)
        # the denominator is SERVING replicas: a draining replica's
        # capacity is leaving, and counting it would overstate
        # headroom exactly when the autoscaler most needs it honest
        n_serving = sum(1 for s in self._states.values()
                        if s.serving(now, self.config.stale_after))
        if self._knee_qps and n_serving:
            self._headroom_gauge.set(
                1.0 - qps / (self._knee_qps * n_serving))
        else:
            self._headroom_gauge.set(-1.0)

    # -- read side ----------------------------------------------------------
    def replica_summaries(self) -> List[Dict[str, Any]]:
        now = time.monotonic()
        stale = self.config.stale_after
        out = []
        for st in list(self._states.values()):
            status = st.status or {}
            degraded = status.get("degraded") or {}
            slo = status.get("slo") or {}
            out.append({
                "replica": st.name,
                "url": st.base,
                "up": st.up(now, stale),
                "lifecycle": status.get("lifecycle"),
                "lastScrapeAgeSec": (
                    round(now - st.last_ok, 3)
                    if st.last_ok is not None else None),
                "lastError": st.last_err,
                "scrapeSec": round(st.scrape_sec, 6),
                "servingWarm": status.get("servingWarm"),
                "requestCount": status.get("requestCount"),
                "degraded": degraded.get("active"),
                "nonfinite": degraded.get("nonfinite"),
                "sloBurning": slo.get("burning"),
                "hotKeys": (status.get("hotKeys") or {}).get("top"),
            })
        return out

    def fleet_status(self) -> Dict[str, Any]:
        now = time.monotonic()
        stale = self.config.stale_after
        states = list(self._states.values())
        n_up = sum(1 for s in states if s.up(now, stale))
        n_draining = sum(1 for s in states if s.draining)
        return {
            "server": "fleet",
            "replicasConfigured": len(states),
            "replicasUp": n_up,
            "replicasDraining": n_draining,
            "staleAfterSec": stale,
            "scrapeIntervalSec": self.config.scrape_interval_sec,
            # ptpu: allow[unguarded-shared-state] — display-only read
            # of a monotone int; taking _cycle_lock here would park
            # every status request behind an in-flight scrape cycle
            "cycles": self._cycles,
            "qps": self._qps_gauge.labels().value,
            "kneeQps": self._knee_qps,
            "capacityHeadroom": self._headroom_gauge.labels().value,
            "replicas": self.replica_summaries(),
            "slo": (self.slo.status() if self.slo is not None
                    else {"enabled": False}),
            "hotKeys": self.hot.snapshot(),
            "autoscale": (self.autoscaler.status()
                          if self.autoscaler is not None
                          else {"enabled": False}),
        }

    # -- trace fan-out ------------------------------------------------------
    def trace_lookup(self, trace_id: str) -> Dict[str, Any]:
        """Ask every replica's flight recorder for ``trace_id``;
        return the first hit annotated with the replica that held it.
        404s mean "not retained HERE" and fall through; only when no
        replica holds it does the fleet answer 404."""
        errors: Dict[str, str] = {}
        for st in list(self._states.values()):
            try:
                code, body = self.fetch(
                    st.base + "/trace.json?id=" + trace_id,
                    self.config.timeout_sec)
            except Exception as e:  # noqa: BLE001 — a dead replica
                errors[st.name] = str(e)  # can't veto the lookup
                continue
            if code == 200 and body is not None:
                return {"replica": st.name, "trace": body}
            errors[st.name] = f"status {code}"
        raise HTTPError(
            404, f"trace {trace_id!r} is not retained on any of "
                 f"{len(self._states)} replicas ({errors})")

    def trace_slowest(self, n: int) -> Dict[str, Any]:
        """The fleet's N slowest retained traces: every replica's
        ``?slowest=`` summaries merged and re-sorted by duration."""
        merged: List[Dict[str, Any]] = []
        for st in list(self._states.values()):
            try:
                code, body = self.fetch(
                    st.base + f"/trace.json?slowest={n}",
                    self.config.timeout_sec)
            except Exception:  # noqa: BLE001
                continue
            if code != 200 or not isinstance(body, dict):
                continue
            for t in body.get("traces") or []:
                t = dict(t)
                t["replica"] = st.name
                merged.append(t)
        merged.sort(key=lambda t: float(t.get("durationMs") or 0.0),
                    reverse=True)
        return {"traces": merged[:n]}

    def trace_status(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for st in list(self._states.values()):
            try:
                code, body = self.fetch(st.base + "/trace.json",
                                        self.config.timeout_sec)
                out[st.name] = body if code == 200 \
                    else {"error": f"status {code}"}
            except Exception as e:  # noqa: BLE001
                out[st.name] = {"error": str(e)}
        return out


class _HotProxy:
    """Indirection so the pio_hot_keys collector always reads the
    CURRENT merged sketch (the aggregator swaps a fresh one in every
    cycle; a collector bound to one instance would go stale)."""

    def __init__(self, agg: FleetAggregator) -> None:
        self._agg = agg

    def top(self, n: Optional[int] = None):
        return self._agg.hot.top(n)


def build_fleet_app(agg: FleetAggregator) -> HTTPApp:
    """The aggregator's HTTP surface, through the same
    :func:`mount_metrics` machinery every server in the repo uses:
    ``/metrics`` + ``/metrics.json`` + ``/status.json`` serve the
    MERGED registry (a fleet aggregator is itself scrapeable — fleets
    of fleets compose), plus the fleet-only routes."""
    app = HTTPApp(name="fleet")
    # runtime=False: pio_build_info / HBM / span collectors describe
    # ONE process — the aggregator's own would shadow nothing useful,
    # and the merged pio_span_seconds from replicas must stay the only
    # source of that family. tracer=False: the aggregator's requests
    # are not the traffic worth flight-recording.
    mount_metrics(app, agg.registry, server_name="fleet",
                  status=agg.fleet_status, runtime=False, tracer=False)
    _auth = make_key_auth(agg.config.accesskey)

    @app.route("GET", "/fleet.json")
    def fleet_json(req: Request) -> Response:
        return json_response(agg.fleet_status())

    @app.route("GET", "/slo.json")
    def slo_json(req: Request) -> Response:
        return json_response(
            agg.slo.status() if agg.slo is not None
            else {"enabled": False})

    @app.route("GET", "/hotkeys.json")
    def hotkeys_json(req: Request) -> Response:
        try:
            n = int(req.query.get("n", "16"))
        except ValueError:
            raise HTTPError(400, "n must be an integer")
        return json_response({
            "fleet": agg.hot.top(n),
            "replicas": {
                r["replica"]: r["hotKeys"]
                for r in agg.replica_summaries()},
        })

    @app.route("GET", "/trace.json")
    def trace_json(req: Request) -> Response:
        trace_id = req.query.get("id")
        if trace_id:
            return json_response(agg.trace_lookup(trace_id))
        if "slowest" in req.query:
            try:
                n = int(req.query["slowest"])
            except ValueError:
                raise HTTPError(400, "slowest must be an integer")
            return json_response(agg.trace_slowest(n))
        return json_response(agg.trace_status())

    @app.route("POST", "/scrape")
    def scrape(req: Request) -> Response:
        _auth(req)
        return json_response({"outcomes": agg.scrape_cycle(),
                              "cycles": agg._cycles})

    @app.route("POST", "/scale")
    def scale(req: Request) -> Response:
        _auth(req)
        if agg.autoscaler is None:
            raise HTTPError(
                404, "no autoscaler is attached to this fleet "
                     "(deploy with --autoscale)")
        to = req.query.get("to")
        reason = req.query.get("reason", "")
        if to is None and req.body:
            body = req.json()
            if isinstance(body, dict):
                to = body.get("to")
                reason = body.get("reason", reason)
        if to is None:
            raise HTTPError(400, "need ?to=N or a {\"to\": N} body")
        try:
            n = int(to)
        except (TypeError, ValueError):
            raise HTTPError(400, "to must be an integer")
        granted = agg.autoscaler.request_target(
            n, reason or "POST /scale")
        return json_response({"requested": n, "target": granted,
                              "autoscale": agg.autoscaler.status()})

    @app.route("GET", "/route.json")
    def route_json(req: Request) -> Response:
        if agg.router is None:
            raise HTTPError(
                404, "no query router is attached to this fleet "
                     "(deploy with --autoscale / router enabled)")
        out = agg.router.status()
        key = req.query.get("key")
        if key is not None:
            out["key"] = key
            out["affinity"] = agg.router.route_key(key)
            out["preference"] = agg.router.preference(
                key, agg.router.config.spill_fanout
                + agg.router.config.retries)
        return json_response(out)

    @app.route("GET", "/")
    def index(req: Request) -> Response:
        import html

        status = agg.fleet_status()
        rows = []
        for r in status["replicas"]:
            rows.append(
                "<tr><td>%s</td><td>%s</td><td>%s</td><td>%s</td>"
                "<td>%s</td></tr>" % (
                    html.escape(str(r["replica"])),
                    "up" if r["up"] else "DOWN",
                    html.escape(str(r["lastScrapeAgeSec"])),
                    html.escape(str(r["requestCount"])),
                    html.escape(str(r["sloBurning"] or []))))
        hot_rows = "".join(
            f"<li>{html.escape(str(k['key']))}: {k['count']:.0f} "
            f"(&plusmn;{k['error']:.0f})</li>"
            for k in status["hotKeys"]["top"][:10])
        body = f"""<html><head><title>predictionio_tpu fleet</title>
</head><body><h1>Fleet: {status['replicasUp']}/{
            status['replicasConfigured']} replicas up</h1>
<ul>
<li>scrape cycles: {status['cycles']} (every {
            status['scrapeIntervalSec']}s)</li>
<li>fleet qps: {status['qps']:.2f}</li>
<li>capacity headroom: {status['capacityHeadroom']:.3f} (knee {
            status['kneeQps']})</li>
<li>fleet SLO burning: {html.escape(str(
            (status['slo'] or {}).get('burning', [])))}</li>
</ul>
<table border='1'><tr><th>replica</th><th>state</th>
<th>scrape age (s)</th><th>requests</th><th>burning</th></tr>
{''.join(rows)}</table>
<h2>Hot keys (fleet-wide)</h2><ul>{hot_rows}</ul>
<p><a href='/fleet.json'>fleet.json</a> ·
<a href='/metrics'>merged metrics</a> ·
<a href='/slo.json'>slo.json</a> ·
<a href='/hotkeys.json'>hotkeys.json</a> ·
<a href='/trace.json?slowest=10'>slowest traces</a></p>
</body></html>"""
        return Response(body=body, content_type="text/html")

    @app.route("POST", "/stop")
    def stop(req: Request) -> Response:
        _auth(req)

        def _later() -> None:
            time.sleep(0.25)  # let the response flush first
            agg.stop()
            srv = app_server_ref[0]
            if srv is not None:
                srv.shutdown()

        threading.Thread(target=_later, daemon=True).start()
        return json_response({"stopping": True})

    app_server_ref: List[Optional[AppServer]] = [None]
    app.server_ref = app_server_ref  # type: ignore[attr-defined]
    return app


def create_fleet_server(config: FleetConfig, host: str = "0.0.0.0",
                        port: int = 8200, fetch=None,
                        ssl_context=None
                        ) -> Tuple[FleetAggregator, AppServer]:
    """Aggregator + its HTTP server, started (scrape loop + SLO
    engine running; caller picks ``serve_forever`` vs
    ``start_background``)."""
    agg = FleetAggregator(config, fetch=fetch)
    app = build_fleet_app(agg)
    server = AppServer(app, host=host, port=port,
                       ssl_context=ssl_context)
    app.server_ref[0] = server  # type: ignore[attr-defined]
    agg.start()
    return agg, server
