"""E-commerce recommendation template (ALS + popularity fallback +
realtime filters + weighted score adjustment).

Capability parity with the reference
``examples/scala-parallel-ecommercerecommendation/adjust-score/``:
implicit ALS over deduped view counts (``ECommAlgorithm.scala:90-166``,
``genMLlibRating`` :171-204), buy-count popularity fallback
(``trainDefault`` :206-240), and a three-path predict (:242-310):
known user → factor dot products; unknown user with recent history →
cosine similarity to recent items; otherwise → popularity. Serving-time
reads of the event store supply seen items, the ``unavailableItems``
constraint, and ``weightedItems`` score adjustment
(``genBlackList`` :329-396, ``weightedItems`` :399-425,
``getRecentItems`` :427-462), each with a soft timeout.

TPU shape: every ``.par`` map over product models becomes one
masked matvec/matmul over the ``[I, rank]`` factor matrix.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

import logging

import numpy as np

from ..controller import (
    Algorithm,
    Context,
    DataSource,
    Engine,
    EngineParams,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
)
from ..data.bimap import BiMap
from ..models.als import ALSParams, RatingsCOO, pack_ratings_cached, train_als
from ._common import candidate_mask, dedup_view_ratings, top_scores

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class Query:
    user: str
    num: int = 10
    categories: Optional[Tuple[str, ...]] = None
    white_list: Optional[Tuple[str, ...]] = None
    black_list: Optional[Tuple[str, ...]] = None

    def __init__(self, user, num=10, categories=None, white_list=None,
                 black_list=None):
        conv = lambda v: tuple(v) if v is not None else None
        object.__setattr__(self, "user", user)
        object.__setattr__(self, "num", num)
        object.__setattr__(self, "categories", conv(categories))
        object.__setattr__(self, "white_list", conv(white_list))
        object.__setattr__(self, "black_list", conv(black_list))


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...] = ()

    def to_json(self) -> dict:
        return {"itemScores": [{"item": s.item, "score": s.score}
                               for s in self.item_scores]}


@dataclass(frozen=True)
class Item:
    categories: Optional[Tuple[str, ...]] = None


@dataclass(frozen=True)
class UserItemEvent:
    user: str
    item: str
    t: float


@dataclass
class TrainingData(SanityCheck):
    users: Dict[str, dict]
    items: Dict[str, Item]
    view_events: List[UserItemEvent]
    buy_events: List[UserItemEvent]

    def sanity_check(self):
        if not self.users or not self.items:
            raise ValueError("users/items cannot be empty")


@dataclass(frozen=True)
class DataSourceParams:
    app_name: str = ""


class ECommerceDataSource(DataSource):
    def __init__(self, params: DataSourceParams = DataSourceParams()):
        self.params = params

    def read_training(self, ctx: Context) -> TrainingData:
        app = self.params.app_name or ctx.app_name
        users = {eid: {} for eid in
                 ctx.event_store.aggregate_properties(app, "user")}
        items = {}
        for eid, pm in ctx.event_store.aggregate_properties(
                app, "item").items():
            cats = pm.get("categories")
            items[eid] = Item(categories=tuple(cats) if cats else None)
        views, buys = [], []
        for e in ctx.event_store.find(
                app, entity_type="user", event_names=["view", "buy"],
                target_entity_type="item"):
            ev = UserItemEvent(e.entity_id, e.target_entity_id,
                               e.event_time.timestamp())
            (views if e.event == "view" else buys).append(ev)
        return TrainingData(users, items, views, buys)


@dataclass(frozen=True)
class ECommAlgorithmParams:
    """``ECommAlgorithmParams`` (``ECommAlgorithm.scala:38-47``)."""
    app_name: str = ""
    unseen_only: bool = False
    seen_events: Tuple[str, ...] = ("buy", "view")
    similar_events: Tuple[str, ...] = ("view",)
    rank: int = 10
    num_iterations: int = 20
    lambda_: float = 0.01
    seed: Optional[int] = None
    #: serving-time event-store read deadline (reference: 200ms Duration)
    timeout_ms: int = 200


@dataclass
class ECommModel:
    #: app the model was trained from — fallback for serving-time reads
    #: when ``ECommAlgorithmParams.app_name`` is unset
    app_name: str
    rank: int
    user_factors: np.ndarray   # [U, rank]
    has_user: np.ndarray       # [U] bool — user appeared in training
    item_factors: np.ndarray   # [I, rank]
    has_item: np.ndarray       # [I] bool — item has a trained vector
    popular_count: np.ndarray  # [I] buy counts
    user_ids: BiMap
    item_ids: BiMap
    items: Dict[int, Item]


class ECommAlgorithm(Algorithm):
    query_class = Query

    def __init__(self, params: ECommAlgorithmParams = ECommAlgorithmParams()):
        self.params = params

    # -- training ------------------------------------------------------------
    def gen_ratings(self, td: TrainingData, user_ids: BiMap,
                    item_ids: BiMap) -> RatingsCOO:
        """Deduped view counts (``genMLlibRating`` :171-204)."""
        return dedup_view_ratings(td.view_events, user_ids, item_ids)

    def train_default(self, td: TrainingData, user_ids: BiMap,
                      item_ids: BiMap) -> np.ndarray:
        """Buy-count popularity (``trainDefault`` :206-240)."""
        counts = np.zeros(len(item_ids), dtype=np.int64)
        for b in td.buy_events:
            if b.user in user_ids and b.item in item_ids:
                counts[item_ids[b.item]] += 1
        return counts

    def train(self, ctx: Context, td: TrainingData) -> ECommModel:
        if not td.view_events:
            raise ValueError("viewEvents cannot be empty")
        self._serving_store = ctx.event_store
        user_ids = BiMap.string_int(td.users.keys())
        item_ids = BiMap.string_int(td.items.keys())
        ratings = self.gen_ratings(td, user_ids, item_ids)
        p = self.params
        als = ALSParams(rank=p.rank, num_iterations=p.num_iterations,
                        reg=p.lambda_, implicit_prefs=True, alpha=1.0,
                        seed=p.seed if p.seed is not None else 0)
        packed = pack_ratings_cached(ratings, als, mesh=ctx.mesh)
        U, V = train_als(ratings, als, mesh=ctx.mesh, packed=packed)
        U = np.asarray(U)[:len(user_ids)]
        V = np.asarray(V)[:len(item_ids)]
        has_user = np.zeros(len(user_ids), dtype=bool)
        has_user[np.unique(ratings.users)] = True
        has_item = np.zeros(len(item_ids), dtype=bool)
        has_item[np.unique(ratings.items)] = True
        return ECommModel(
            app_name=p.app_name or ctx.app_name,
            rank=p.rank, user_factors=U, has_user=has_user,
            item_factors=V, has_item=has_item,
            popular_count=self.train_default(td, user_ids, item_ids),
            user_ids=user_ids, item_ids=item_ids,
            items={item_ids[k]: v for k, v in td.items.items()})

    # -- serving-time event-store lookups -------------------------------------
    def bind_serving(self, ctx: Context) -> None:
        # capture the serving Context's storage so filter reads
        # (seen/unavailable/weighted/recent) hit the same backend the model
        # was deployed against, not the process-global default
        self._serving_store = ctx.event_store

    def bind_feature_cache(self, cache) -> None:
        """Engine-server hook (ISSUE 4): serving-time filter reads go
        through this :class:`~..cache.ShardedTTLCache` tier — a hot
        user's seen/recent sets and the app-wide constraint reads stop
        hitting storage once per query. Entries are tagged with the
        entity they derive from, so the invalidation bus clears them
        the moment a contradicting event is ingested."""
        self._feature_cache = cache

    def _ctx_store(self):
        store = getattr(self, "_serving_store", None)
        if store is not None:
            return store
        from ..data.store import event_store
        return event_store

    def _cached_read(self, key: tuple, tags: Tuple[str, ...], fn):
        cache = getattr(self, "_feature_cache", None)
        if cache is None:
            return fn()
        found, value = cache.lookup(key)
        if found:
            return value
        value = fn()
        cache.put(key, value, tags=tags)
        return value

    def gen_black_list(self, query: Query, app_name: str) -> Set[str]:
        """query.blackList + seen items + unavailableItems constraint
        (``genBlackList`` :329-396). Event-store failures degrade to empty
        sets — serving never hard-fails on a filter read."""
        p = self.params
        seen: Set[str] = set()
        if p.unseen_only:
            def read_seen() -> Set[str]:
                out: Set[str] = set()
                try:
                    for e in self._ctx_store().find_by_entity(
                            app_name, "user", query.user,
                            event_names=list(p.seen_events),
                            target_entity_type="item",
                            timeout_ms=p.timeout_ms):
                        if e.target_entity_id:
                            out.add(e.target_entity_id)
                except Exception as err:
                    log.error("error reading seen events: %s", err)
                return out

            seen = self._cached_read(
                ("ecomm-seen", app_name, query.user, p.seen_events),
                (f"user:{query.user}",), read_seen)

        def read_unavailable() -> Set[str]:
            try:
                evs = self._ctx_store().find_by_entity(
                    app_name, "constraint", "unavailableItems",
                    event_names=["$set"], limit=1, latest=True,
                    timeout_ms=p.timeout_ms)
                if evs:
                    return set(evs[0].properties.get("items") or ())
            except Exception as err:
                log.error("error reading unavailableItems: %s", err)
            return set()

        unavailable = self._cached_read(
            ("ecomm-unavailable", app_name),
            ("constraint:unavailableItems",), read_unavailable)
        return set(query.black_list or ()) | seen | unavailable

    def weighted_items(self, app_name: str) -> List[Tuple[Set[str], float]]:
        """Latest ``weightedItems`` constraint → weight groups
        (``weightedItems`` :399-425)."""
        p = self.params

        def read_weighted() -> List[Tuple[Set[str], float]]:
            try:
                evs = self._ctx_store().find_by_entity(
                    app_name, "constraint", "weightedItems",
                    event_names=["$set"], limit=1, latest=True,
                    timeout_ms=p.timeout_ms)
                if evs:
                    return [(set(g["items"]), float(g["weight"]))
                            for g in (evs[0].properties.get("weights")
                                      or ())]
            except Exception as err:
                log.error("error reading weightedItems: %s", err)
            return []

        return self._cached_read(("ecomm-weighted", app_name),
                                 ("constraint:weightedItems",),
                                 read_weighted)

    def get_recent_items(self, query: Query, app_name: str) -> Set[str]:
        """Latest 10 similar-events targets (``getRecentItems`` :427-462)."""
        p = self.params

        def read_recent() -> Set[str]:
            try:
                return {e.target_entity_id for e in self._ctx_store()
                        .find_by_entity(
                            app_name, "user", query.user,
                            event_names=list(p.similar_events),
                            target_entity_type="item", limit=10,
                            latest=True, timeout_ms=p.timeout_ms)
                        if e.target_entity_id}
            except Exception as err:
                log.error("error reading recent events: %s", err)
                return set()

        return self._cached_read(
            ("ecomm-recent", app_name, query.user, p.similar_events),
            (f"user:{query.user}",), read_recent)

    # -- predict ---------------------------------------------------------------
    def _weights_vector(self, model: ECommModel,
                        app_name: str) -> np.ndarray:
        """The per-item weight vector, computed ONCE per (model,
        app_name, weights-constraint) generation. The old code rebuilt
        an O(n_items) vector with a Python loop on EVERY predict; the
        weight groups change only when a new ``weightedItems`` $set
        lands, so the vector is memoized against the groups' content
        (and a weakref to the model — new model means new item index
        space) and rebuilt only when either changes."""
        import weakref

        groups = self.weighted_items(app_name)
        sig = tuple(sorted((weight, tuple(sorted(items)))
                           for items, weight in groups))
        memo = getattr(self, "_weights_memo", None)
        if (memo is not None and memo[0]() is model
                and memo[1] == app_name and memo[2] == sig):
            return memo[3]
        w = np.ones(len(model.item_ids), dtype=np.float64)
        for items, weight in groups:
            idx = [model.item_ids[it] for it in items
                   if it in model.item_ids]
            if idx:
                w[idx] = weight
        self._weights_memo = (weakref.ref(model), app_name, sig, w)
        return w

    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        app_name = self.params.app_name or model.app_name
        black = self.gen_black_list(query, app_name)
        weights = self._weights_vector(model, app_name)
        mask = candidate_mask(
            model.items, len(model.item_ids), model.item_ids,
            white_list=query.white_list, black_list=black,
            categories=query.categories)

        uidx = model.user_ids.get(query.user)
        if uidx is not None and model.has_user[uidx]:
            # known user: dot(userFeature, itemFeature) × weight (:469-504)
            scores = (model.item_factors @ model.user_factors[uidx]) * weights
            scores[~model.has_item] = 0.0
            top = top_scores(scores, mask, query.num, positive_only=True)
        else:
            recent = {model.item_ids[i]
                      for i in self.get_recent_items(query, app_name)
                      if i in model.item_ids}
            recent_f = [model.item_factors[i] for i in recent
                        if model.has_item[i]]
            if recent_f:
                # cosine-similar to recent items (:539-576)
                R = np.stack(recent_f)
                Rn = R / np.maximum(
                    np.linalg.norm(R, axis=1, keepdims=True), 1e-12)
                V = model.item_factors
                Vn = V / np.maximum(
                    np.linalg.norm(V, axis=1, keepdims=True), 1e-12)
                scores = (Rn @ Vn.T).sum(axis=0) * weights
                scores[~model.has_item] = 0.0
                top = top_scores(scores, mask, query.num, positive_only=True)
            else:
                # popularity fallback (:506-537); no positive-score filter
                scores = model.popular_count.astype(np.float64) * weights
                top = top_scores(scores, mask, query.num, positive_only=False)

        inv = model.item_ids.inverse
        return PredictedResult(tuple(
            ItemScore(inv[i], s) for i, s in top))


def ecommerce_engine() -> Engine:
    return Engine(
        datasource_classes=ECommerceDataSource,
        preparator_classes=IdentityPreparator,
        algorithm_classes={"ecomm": ECommAlgorithm, "": ECommAlgorithm},
        serving_classes=FirstServing,
        datasource_params_class=DataSourceParams,
        algorithm_params_classes={"ecomm": ECommAlgorithmParams,
                                  "": ECommAlgorithmParams},
    )


def default_engine_params(app_name: str, **algo_kw) -> EngineParams:
    return EngineParams(
        datasource=("", DataSourceParams(app_name=app_name)),
        algorithms=[("ecomm", ECommAlgorithmParams(app_name=app_name,
                                                   **algo_kw))],
    )
