"""Recommendation engine template — the north-star workload.

Capability parity with the reference's bundled recommendation engine
(``tests/pio_tests/engines/recommendation-engine/src/main/scala/``):
DataSource reads ``rate``/``buy`` events (``DataSource.scala:47-52``,
k-fold readEval :83-105), the ALS algorithm trains factor models
(``ALSAlgorithm.scala:51-93``) and serves top-N via factor dot products
(:95-109), queries/results use the same JSON shapes the reference's
engine server speaks:

    POST /queries.json  {"user": "1", "num": 4}
    → {"itemScores": [{"item": "22", "score": 4.07}, ...]}
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..controller import (
    Algorithm,
    AverageMetric,
    Context,
    DataSource,
    Engine,
    EngineParams,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
)
from ..controller.metric import ndcg_at_k, precision_at_k
from ..models.als import (
    ALSModel,
    ALSParams,
    RatingsCOO,
    pack_ratings_cached,
    recommend_batch,
    recommend_products,
    train_als,
)
from ..models.data import kfold_split, ratings_from_columnar


# -- query/result schema (reference Query.scala / PredictedResult) ----------

@dataclass(frozen=True)
class Query:
    """``Query.scala``; ``black_list`` is the blacklist-items variant's
    added field (``examples/scala-parallel-recommendation/blacklist-items/
    src/main/scala/Engine.scala:26``)."""
    user: str
    num: int = 10
    black_list: Optional[Tuple[str, ...]] = None

    def __post_init__(self):
        if self.black_list is not None:
            object.__setattr__(self, "black_list", tuple(self.black_list))


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...] = ()

    def to_json(self) -> dict:
        return {"itemScores": [{"item": s.item, "score": s.score}
                               for s in self.item_scores]}


# -- training data -----------------------------------------------------------

@dataclass
class TrainingData(SanityCheck):
    #: a :class:`RatingsCOO`, or (multihost) a sharded ratings source
    #: (``read_rows``/``row_counts`` — duck-typed through the pack)
    ratings: RatingsCOO
    user_ids: object  # BiMap
    item_ids: object  # BiMap

    def sanity_check(self):
        r = self.ratings
        nnz = (int(np.asarray(r.row_counts("user")).sum())
               if hasattr(r, "row_counts") else r.users.size)
        if nnz == 0:
            raise ValueError("TrainingData has no ratings; check that "
                             "rate/buy events exist for the app")


@dataclass(frozen=True)
class DataSourceParams:
    app_name: str = ""
    channel_name: Optional[str] = None
    eval_k: int = 0              # folds for read_eval (0 = no eval data)
    eval_query_num: int = 10     # N per eval query
    eval_rating_threshold: float = 2.0  # "relevant" cutoff for actuals
    seed: int = 3
    #: event name → fixed rating (None ⇒ read the ``rating`` property).
    #: Default replays the quickstart (rate + buy=4.0); the
    #: reading-custom-events / train-with-view-event variants configure
    #: e.g. {"like": 5.0, "dislike": 1.0} or {"view": 1.0} here instead
    #: of editing the DataSource (``examples/scala-parallel-recommendation/
    #: {reading-custom-events,train-with-view-event}/…/DataSource.scala:50``).
    event_weights: Optional[Dict[str, Optional[float]]] = None


@dataclass(frozen=True)
class EvalInfo:
    fold: int
    rating_threshold: float


@dataclass(frozen=True)
class ActualResult:
    """Ground truth for one eval query: the user's held-out rated items."""
    ratings: Tuple[Tuple[str, float], ...]  # (item, rating)


class RecommendationDataSource(DataSource):
    def __init__(self, params: DataSourceParams = DataSourceParams()):
        self.params = params

    def _read_ratings(self, ctx: Context):
        import jax

        weights = self.params.event_weights
        multihost = jax.process_count() > 1
        batch = ctx.event_store.find_columnar(
            self.params.app_name or ctx.app_name,
            channel_name=self.params.channel_name,
            entity_type="user", target_entity_type="item",
            event_names=(list(weights) if weights is not None
                         else ["rate", "buy"]),
            # a bulk COO build needs neither time order nor raw JSON
            ordered=False, with_props=False,
            # multihost: the storage layer hands this process ONLY its
            # shard (shard pushdown — a remote backend ships 1/N of the
            # bytes); the sharded source below re-assembles per-factor-
            # row triples over the collective fabric
            host_sharded=multihost)
        if multihost:
            from ..models.data import ShardedColumnarRatingsSource
            src = ShardedColumnarRatingsSource(
                batch, event_weights=weights)
            return src, src.user_ids, src.item_ids
        return ratings_from_columnar(batch, event_weights=weights)

    def read_training(self, ctx: Context) -> TrainingData:
        ratings, user_ids, item_ids = self._read_ratings(ctx)
        return TrainingData(ratings, user_ids, item_ids)

    def read_eval(self, ctx: Context):
        """K-fold split over rating entries (reference ``DataSource.scala:
        83-105``): train on k-1 folds, hold out one; queries ask top-N for
        each user present in the held-out fold, actuals are their held-out
        items."""
        p = self.params
        if p.eval_k <= 1:
            raise ValueError("eval_k must be >= 2 for read_eval")
        ratings, user_ids, item_ids = self._read_ratings(ctx)
        if hasattr(ratings, "to_coo"):
            # k-fold splitting slices entry arrays; materialize the
            # global COO (collective under multihost — eval is not the
            # memory-bound path training is)
            ratings = ratings.to_coo()
        # dense inverse-lookup arrays: at ML-20M scale a fold holds ~10M
        # test entries, and per-entry dict lookups + numpy-scalar
        # unboxing in a Python loop cost minutes on one core — the
        # grouping below is numpy lexsort + slicing instead
        inv_u_arr = np.empty(ratings.n_users, dtype=object)
        for s, j in user_ids.items():
            inv_u_arr[j] = s
        inv_i_arr = np.empty(ratings.n_items, dtype=object)
        for s, j in item_ids.items():
            inv_i_arr[j] = s
        folds = []
        for f, (train_mask, test_mask) in enumerate(
                kfold_split(len(ratings.users), p.eval_k, p.seed)):
            td = TrainingData(
                RatingsCOO(ratings.users[train_mask],
                           ratings.items[train_mask],
                           ratings.ratings[train_mask],
                           ratings.n_users, ratings.n_items),
                user_ids, item_ids)
            te_u = ratings.users[test_mask]
            order = np.lexsort((np.arange(len(te_u)), te_u))
            u_s = te_u[order]
            i_names = inv_i_arr[ratings.items[test_mask][order]]
            r_s = ratings.ratings[test_mask][order].astype(float)
            starts = np.flatnonzero(
                np.r_[True, u_s[1:] != u_s[:-1]]) if len(u_s) else \
                np.empty(0, np.int64)
            bounds = np.r_[starts, len(u_s)]
            qa = []
            for b in range(len(starts)):
                lo, hi = bounds[b], bounds[b + 1]
                qa.append((
                    Query(user=inv_u_arr[u_s[lo]],
                          num=p.eval_query_num),
                    ActualResult(tuple(zip(i_names[lo:hi].tolist(),
                                           r_s[lo:hi].tolist())))))
            folds.append((td, EvalInfo(fold=f,
                                       rating_threshold=p.eval_rating_threshold),
                          qa))
        return folds


# -- algorithm ---------------------------------------------------------------

class ALSAlgorithm(Algorithm):
    """Explicit-feedback ALS (``ALSAlgorithm.scala:39-150``); set
    ``implicit_prefs=True`` for the trainImplicit variants."""

    query_class = Query

    def __init__(self, params: ALSParams = ALSParams()):
        self.params = params

    def train(self, ctx: Context, td: TrainingData) -> ALSModel:
        mesh = ctx.mesh
        packed = pack_ratings_cached(td.ratings, self.params, mesh=mesh)
        U, V = train_als(td.ratings, self.params, mesh=mesh, packed=packed)
        return ALSModel(user_factors=U, item_factors=V,
                        n_users=td.ratings.n_users,
                        n_items=td.ratings.n_items,
                        user_ids=td.user_ids, item_ids=td.item_ids,
                        params=self.params)

    def predict(self, model: ALSModel, query: Query) -> PredictedResult:
        return self._predict_impl(model, query, pinned=None)

    def _predict_impl(self, model: ALSModel, query: Query,
                      pinned) -> PredictedResult:
        uidx = model.user_ids.get(query.user) if model.user_ids else None
        if uidx is None:
            return PredictedResult()  # unknown user (reference returns empty)
        black = {model.item_ids[i] for i in (query.black_list or ())
                 if i in model.item_ids}
        # over-fetch by the blacklist size, then filter (the variant's
        # recommendProductsWithFilter, blacklist-items ALSAlgorithm.scala:
        # 102-104)
        if pinned is not None:
            from ..models.als import recommend_pinned

            table, slot = pinned
            ids, scores = recommend_pinned(model, table, slot,
                                           query.num + len(black))
        else:
            ids, scores = recommend_products(model, int(uidx),
                                             query.num + len(black))
        inv = model.item_ids.inverse
        out = [(int(i), float(s)) for i, s in zip(ids, scores)
               if int(i) not in black][: query.num]
        return PredictedResult(tuple(
            ItemScore(item=inv[i], score=s) for i, s in out))

    # -- hot-entity tier hooks (ISSUE 4) ------------------------------------
    def pin_hot_entities(self, model: ALSModel,
                         entity_keys: Sequence[str],
                         devices: Optional[Sequence] = None):
        """Pin the hottest users' factor rows as ONE device-resident
        table (:func:`~..models.als.pin_user_rows`); returns
        ``({user: (table, slot)}, nbytes)``. Host-served models return
        empty — there is no transfer to skip. The pinned table is
        padded to a pow2 capacity and its k-ladder warmed here (on the
        refresh thread), so the first hot-path query after a refresh
        never pays a compile.

        With ``devices`` (replicated-mode lanes, ISSUE 6) the pinned
        table is committed to EVERY lane device
        (:func:`~..models.als.pin_user_rows_lanes`) and the handle
        carries the per-device tuple — hot serves stay local to a lane.
        Sharded models pin a mesh-replicated table instead (the rows
        are fetched through the collective gather)."""
        from ..models.als import (
            pin_user_rows,
            pin_user_rows_lanes,
            recommend_pinned,
        )

        known = [(e, int(model.user_ids[e])) for e in entity_keys
                 if model.user_ids and e in model.user_ids]
        if not known:
            return {}, 0
        cap = 1
        while cap < len(known):
            cap *= 2
        if devices and getattr(model, "mesh", None) is None:
            table, nbytes = pin_user_rows_lanes(
                model, [u for _, u in known], cap, devices)
        else:
            table, nbytes = pin_user_rows(model, [u for _, u in known],
                                          cap)
        if table is None:
            return {}, 0
        ks, k = [], 8
        while k <= min(128, model.n_items):
            ks.append(k)
            k *= 2
        for k in ks or [min(8, model.n_items)]:
            recommend_pinned(model, table, 0, k)
        return {e: (table, slot)
                for slot, (e, _) in enumerate(known)}, nbytes

    def predict_pinned(self, model: ALSModel, query: Query,
                       handle) -> PredictedResult:
        """Serve one query off a pinned hot-user row (the device-
        resident hot tier's fast path)."""
        return self._predict_impl(model, query, pinned=handle)

    def prepare_serving_model(self, model: ALSModel,
                              max_batch: int = 1) -> ALSModel:
        from ..models.als import ensure_device_resident

        return ensure_device_resident(model, max_batch)

    def quantize_serving_model(self, model: ALSModel,
                               quant: str) -> ALSModel:
        """Row-quantize the serving factor tables (ISSUE 13,
        ``ServerConfig.serving_quant``): int8/bf16 storage with
        per-row scales and f32 accumulation, behind the deploy-time
        NDCG@10 parity probe — a model whose rank/scale cannot take
        the quantization keeps its f32 tables (auto-off)."""
        from ..models.als import quantize_serving_model

        return quantize_serving_model(model, quant)

    # -- mesh-wide serving placement hooks (ISSUE 6) ------------------------
    def replicate_serving_model(self, model: ALSModel,
                                device) -> ALSModel:
        """One full factor-table copy committed to ``device`` — a
        replicated-mode lane's model (per-device compiled executables,
        no cross-device sync on the serve path)."""
        from ..models.als import replicate_model

        return replicate_model(model, device)

    def shard_serving_model(self, model: ALSModel, mesh) -> ALSModel:
        """Row-shard both factor tables over the serving mesh
        (``NamedSharding``, ALX layout) — the >1-HBM model placement;
        serving routes through the mesh ranking program."""
        from ..models.als import shard_model

        return shard_model(model, mesh)

    def warm_serving(self, model: ALSModel, max_batch: int = 1) -> None:
        """Pre-compile the serving device kernels for the single-query
        path and every pow2 batch size the micro-batcher can produce
        (each novel shape is a fresh XLA compile — 6-20s through a
        device tunnel; cf. ``ServerConfig.warm_start``)."""
        if model.user_ids is None or len(model.user_ids) == 0:
            return
        from ..models.als import recommend_batch, recommend_products

        # k ladder: batch_predict fetches k = num + blacklist-length,
        # and each pow2 k bucket is its own compiled shape
        ks = []
        k = 8
        while k <= min(128, model.n_items):
            ks.append(k)
            k *= 2
        ks = ks or [min(8, model.n_items)]
        for k in ks:
            recommend_products(model, 0, k)
        b = 1
        top = max(max_batch, 1)
        while True:
            for k in ks:
                recommend_batch(model, np.zeros(b, dtype=np.int64), k)
            if b >= top:  # b is the pow2 ceiling of max_batch: every
                break     # runtime batch pads to a warmed shape
            b *= 2

    def batch_predict_async(self, model: ALSModel,
                            queries: Sequence[Query]):
        """Dispatch half of :meth:`batch_predict` (ISSUE 9): enqueues
        the batched device top-k and returns a no-arg resolver that
        blocks on the device arrays and builds the per-query results.
        The staged serving pipeline's dispatch stage calls this and
        hands the resolver to the readback stage, so the NEXT batch
        launches while this one's results are still on device
        (docs/serving-pipeline.md)."""
        from ..models.als import recommend_batch_async

        known = [(qi, int(model.user_ids[q.user])) for qi, q in
                 enumerate(queries) if model.user_ids
                 and q.user in model.user_ids]
        out: List[PredictedResult] = [PredictedResult()] * len(queries)
        if not known:
            return lambda: out
        max_black = max((len(q.black_list or ()) for q in queries),
                        default=0)
        num = max(q.num for q in queries) + max_black
        idx = np.array([u for _, u in known], dtype=np.int64)
        handle = recommend_batch_async(model, idx, num)

        def resolve() -> List[PredictedResult]:
            ids, scores = handle()
            inv = model.item_ids.inverse
            for row, (qi, _) in enumerate(known):
                q = queries[qi]
                black = {model.item_ids[i] for i in (q.black_list or ())
                         if i in model.item_ids}
                picked = [(int(i), float(s))
                          for i, s in zip(ids[row], scores[row])
                          if int(i) not in black][: q.num]
                out[qi] = PredictedResult(tuple(
                    ItemScore(item=inv[i], score=s) for i, s in picked))
            return out

        return resolve

    def batch_predict(self, model: ALSModel, queries: Sequence[Query]
                      ) -> List[PredictedResult]:
        """One batched device dispatch for all known users
        (the reference's cartesian batchPredict, ``ALSAlgorithm.scala:
        113-150``, without the shuffle). Dispatch + immediate readback
        of :meth:`batch_predict_async` — the two must never diverge."""
        return self.batch_predict_async(model, queries)()


class RecommendationServing(FirstServing):
    pass


@dataclass(frozen=True)
class FileBlacklistServingParams:
    """``ServingParams(filepath)`` of the customize-serving variant."""
    filepath: str = ""


class FileBlacklistServing(RecommendationServing):
    """Drop items listed (one per line) in a file re-read per request —
    the customize-serving variant (``examples/scala-parallel-
    recommendation/customize-serving/src/main/scala/Serving.scala:28-44``)."""

    def __init__(self, params: FileBlacklistServingParams
                 = FileBlacklistServingParams()):
        self.params = params

    def serve(self, query: Query,
              predictions) -> PredictedResult:
        disabled = set()
        if self.params.filepath:
            with open(self.params.filepath, "r", encoding="utf-8") as f:
                disabled = {line.strip() for line in f if line.strip()}
        first = predictions[0]
        return PredictedResult(tuple(
            s for s in first.item_scores if s.item not in disabled))


@dataclass(frozen=True)
class ExcludeItemsPreparatorParams:
    """The customize-data-prep variant's exclusion list: items read from
    a file (one per line) or given inline are dropped before training
    (``examples/scala-parallel-recommendation/customize-data-prep/src/
    main/scala/Preparator.scala``)."""
    filepath: str = ""
    items: Tuple[str, ...] = ()


class ExcludeItemsPreparator(IdentityPreparator):
    def __init__(self, params: ExcludeItemsPreparatorParams
                 = ExcludeItemsPreparatorParams()):
        self.params = params

    def prepare(self, ctx: Context, td: TrainingData) -> TrainingData:
        excluded = set(self.params.items)
        if self.params.filepath:
            with open(self.params.filepath, "r", encoding="utf-8") as f:
                excluded |= {line.strip() for line in f if line.strip()}
        bad_idx = {td.item_ids[i] for i in excluded if i in td.item_ids}
        if not bad_idx:
            return td
        # excluded items leave the model ENTIRELY (re-indexed out), so
        # they can never be recommended — matching the reference, where a
        # filtered item simply has no MLlib factor entry
        from ..data.bimap import BiMap

        new_item_ids = BiMap.string_int(
            k for k in td.item_ids.keys() if k not in excluded)
        remap = np.full(td.ratings.n_items, -1, dtype=np.int64)
        for old_key, new_i in new_item_ids.items():
            remap[td.item_ids[old_key]] = new_i
        keep = ~np.isin(td.ratings.items, list(bad_idx))
        return TrainingData(
            RatingsCOO(td.ratings.users[keep],
                       remap[td.ratings.items[keep]].astype(
                           td.ratings.items.dtype),
                       td.ratings.ratings[keep], td.ratings.n_users,
                       len(new_item_ids)),
            td.user_ids, new_item_ids)


def recommendation_engine() -> Engine:
    """Engine factory (the template's ``EngineFactory`` object)."""
    return Engine(
        datasource_classes=RecommendationDataSource,
        preparator_classes={"": IdentityPreparator,
                            "exclude": ExcludeItemsPreparator},
        algorithm_classes={"als": ALSAlgorithm, "": ALSAlgorithm},
        serving_classes={"": RecommendationServing,
                         "fileblacklist": FileBlacklistServing},
        datasource_params_class=DataSourceParams,
        preparator_params_class={"exclude": ExcludeItemsPreparatorParams},
        algorithm_params_classes={"als": ALSParams, "": ALSParams},
        serving_params_class={"fileblacklist": FileBlacklistServingParams},
    )


# -- evaluation metrics (reference Evaluation.scala:32-89) -------------------

class PrecisionAtK(AverageMetric):
    """Precision@K with a relevance threshold (``Evaluation.scala:32-51``)."""

    def __init__(self, k: int = 10, rating_threshold: float = 2.0):
        self.k = k
        self.rating_threshold = rating_threshold

    @property
    def header(self) -> str:
        return f"Precision@{self.k} (threshold={self.rating_threshold})"

    def calculate_point(self, ei, q: Query, p: PredictedResult,
                        a: ActualResult):
        relevant = {item for item, r in a.ratings
                    if r >= self.rating_threshold}
        return precision_at_k([s.item for s in p.item_scores], relevant,
                              self.k)


class NDCGAtK(AverageMetric):
    """Binary NDCG@K — the BASELINE.md quality target."""

    def __init__(self, k: int = 10, rating_threshold: float = 2.0):
        self.k = k
        self.rating_threshold = rating_threshold

    @property
    def header(self) -> str:
        return f"NDCG@{self.k} (threshold={self.rating_threshold})"

    def calculate_point(self, ei, q: Query, p: PredictedResult,
                        a: ActualResult):
        relevant = {item for item, r in a.ratings
                    if r >= self.rating_threshold}
        return ndcg_at_k([s.item for s in p.item_scores], relevant, self.k)


class PositiveCount(AverageMetric):
    """Average number of relevant actuals per query
    (``Evaluation.scala:53-61``) — a sanity diagnostic, not a target."""

    def __init__(self, rating_threshold: float = 2.0):
        self.rating_threshold = rating_threshold

    @property
    def header(self) -> str:
        return f"PositiveCount (threshold={self.rating_threshold})"

    def calculate_point(self, ei, q, p, a: ActualResult):
        return float(sum(1 for _, r in a.ratings
                         if r >= self.rating_threshold))


def query_from_json(obj: dict) -> Query:
    return Query(user=str(obj["user"]), num=int(obj.get("num", 10)))


def default_engine_params(app_name: str, **als_kw) -> EngineParams:
    return EngineParams(
        datasource=("", DataSourceParams(app_name=app_name)),
        preparator=("", None),
        algorithms=(("als", ALSParams(**als_kw)),),
        serving=("", None))
