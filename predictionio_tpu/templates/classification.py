"""Classification engine template (two algorithms, P2L pattern).

Capability parity with the reference
``examples/scala-parallel-classification/add-algorithm/``: the DataSource
aggregates ``user`` entity properties requiring ``plan`` (the label) and
``attr0/attr1/attr2`` (features) (``DataSource.scala:45-71``); algorithms
are MLlib-style multinomial naive Bayes with ``lambda`` smoothing
(``NaiveBayesAlgorithm.scala:30-58``) and a random forest
(``RandomForestAlgorithm.scala:35-70``); queries carry the three
attributes and predictions return the label
(``Engine.scala`` Query/PredictedResult).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..controller import (
    Algorithm,
    AverageMetric,
    Context,
    DataSource,
    Engine,
    EngineParams,
    FirstServing,
    IdentityPreparator,
    SanityCheck,
)
from ..e2.cross_validation import split_data
from ..models.classify import (
    NaiveBayesModel,
    RandomForestModel,
    RandomForestParams,
    train_naive_bayes_multinomial,
    train_random_forest,
)


@dataclass(frozen=True)
class Query:
    attr0: float
    attr1: float
    attr2: float


@dataclass(frozen=True)
class PredictedResult:
    label: float

    def to_json(self) -> dict:
        return {"label": self.label}


@dataclass(frozen=True)
class ActualResult:
    label: float


@dataclass
class TrainingData(SanityCheck):
    features: np.ndarray  # [N, 3]
    labels: np.ndarray    # [N]

    def sanity_check(self):
        if len(self.features) == 0:
            raise ValueError("TrainingData is empty; are user entities "
                             "missing plan/attr0/attr1/attr2 properties?")


@dataclass(frozen=True)
class DataSourceParams:
    app_name: str = ""
    eval_k: Optional[int] = None


_REQUIRED = ("plan", "attr0", "attr1", "attr2")


class ClassificationDataSource(DataSource):
    def __init__(self, params: DataSourceParams = DataSourceParams()):
        self.params = params

    def _read_points(self, ctx: Context) -> Tuple[np.ndarray, np.ndarray]:
        props = ctx.event_store.aggregate_properties(
            self.params.app_name or ctx.app_name, entity_type="user",
            required=list(_REQUIRED))
        feats, labels = [], []
        for entity_id, pm in sorted(props.items()):
            labels.append(float(pm.get("plan")))
            feats.append([float(pm.get("attr0")), float(pm.get("attr1")),
                          float(pm.get("attr2"))])
        return (np.asarray(feats, dtype=np.float64).reshape(-1, 3),
                np.asarray(labels, dtype=np.float64))

    def read_training(self, ctx: Context) -> TrainingData:
        X, y = self._read_points(ctx)
        return TrainingData(X, y)

    def read_eval(self, ctx: Context):
        """k-fold split, fold i tests points with index % k == i
        (``DataSource.scala:112-123`` via CrossValidation semantics)."""
        if not self.params.eval_k:
            raise ValueError("DataSourceParams.eval_k must be set for eval")
        X, y = self._read_points(ctx)
        points = list(zip(X, y))
        return split_data(
            self.params.eval_k, points, evaluator_info=None,
            training_data_creator=lambda pts: TrainingData(
                np.asarray([p[0] for p in pts]).reshape(-1, 3),
                np.asarray([p[1] for p in pts])),
            query_creator=lambda p: Query(*map(float, p[0])),
            actual_creator=lambda p: ActualResult(float(p[1])))


@dataclass(frozen=True)
class NaiveBayesParams:
    lambda_: float = 1.0


class NaiveBayesAlgorithm(Algorithm):
    """``NaiveBayesAlgorithm.scala:30-58``."""

    query_class = Query

    def __init__(self, params: NaiveBayesParams = NaiveBayesParams()):
        self.params = params

    def train(self, ctx: Context, data: TrainingData) -> NaiveBayesModel:
        if len(data.features) == 0:
            raise ValueError("labeledPoints cannot be empty")
        return train_naive_bayes_multinomial(data.features, data.labels,
                                             lam=self.params.lambda_)

    def predict(self, model: NaiveBayesModel, query: Query
                ) -> PredictedResult:
        return PredictedResult(model.predict(
            [query.attr0, query.attr1, query.attr2]))

    def batch_predict(self, model: NaiveBayesModel,
                      queries: Sequence[Query]) -> List[PredictedResult]:
        X = np.asarray([[q.attr0, q.attr1, q.attr2] for q in queries])
        return [PredictedResult(float(l))
                for l in model.predict_batch(X)]


class RandomForestAlgorithm(Algorithm):
    """``RandomForestAlgorithm.scala:35-70``."""

    query_class = Query

    def __init__(self, params: RandomForestParams = RandomForestParams()):
        self.params = params

    def train(self, ctx: Context, data: TrainingData) -> RandomForestModel:
        if len(data.features) == 0:
            raise ValueError("labeledPoints cannot be empty")
        return train_random_forest(data.features, data.labels, self.params)

    def predict(self, model: RandomForestModel, query: Query
                ) -> PredictedResult:
        return PredictedResult(model.predict(
            [query.attr0, query.attr1, query.attr2]))

    def batch_predict(self, model: RandomForestModel,
                      queries: Sequence[Query]) -> List[PredictedResult]:
        X = np.asarray([[q.attr0, q.attr1, q.attr2] for q in queries])
        return [PredictedResult(float(l))
                for l in model.predict_batch(X)]


class Accuracy(AverageMetric):
    """Fraction of exact label matches (the template's eval metric)."""

    header = "Accuracy"

    def calculate_point(self, ei, q: Query, p: PredictedResult,
                        a: ActualResult) -> float:
        return 1.0 if p.label == a.label else 0.0


def classification_engine() -> Engine:
    """``Engine.scala`` factory: naive Bayes + random forest slots."""
    return Engine(
        datasource_classes=ClassificationDataSource,
        preparator_classes=IdentityPreparator,
        algorithm_classes={"naive": NaiveBayesAlgorithm,
                           "randomforest": RandomForestAlgorithm,
                           "": NaiveBayesAlgorithm},
        serving_classes=FirstServing,
        datasource_params_class=DataSourceParams,
        algorithm_params_classes={"naive": NaiveBayesParams,
                                  "randomforest": RandomForestParams,
                                  "": NaiveBayesParams},
    )


def default_engine_params(app_name: str, algo: str = "naive",
                          **algo_kw) -> EngineParams:
    params_cls = {"naive": NaiveBayesParams,
                  "randomforest": RandomForestParams}[algo]
    return EngineParams(
        datasource=("", DataSourceParams(app_name=app_name)),
        algorithms=[(algo, params_cls(**algo_kw))],
    )
