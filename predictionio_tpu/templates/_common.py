"""Shared helpers for the item-recommendation templates.

The reference duplicates these patterns across templates (each template is
a standalone sbt project); here similarproduct and ecommerce share one
implementation of: deduped view-count ratings (``ECommAlgorithm.
genMLlibRating`` :171-204 / similarproduct ``ALSAlgorithm.train``),
the candidate-item filter (``isCandidateItem``), and top-N selection.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..data.bimap import BiMap
from ..models.als import RatingsCOO


def dedup_view_ratings(events: Iterable, user_ids: BiMap,
                       item_ids: BiMap) -> RatingsCOO:
    """COO of per-(user, item) event counts; events need .user/.item."""
    counts: Dict[Tuple[int, int], float] = {}
    for v in events:
        u, i = user_ids.get(v.user), item_ids.get(v.item)
        if u is None or i is None:
            continue
        counts[(u, i)] = counts.get((u, i), 0.0) + 1.0
    if not counts:
        raise ValueError("no valid events to train on")
    keys = np.array(list(counts.keys()), dtype=np.int32)
    vals = np.array(list(counts.values()), dtype=np.float32)
    return RatingsCOO(users=keys[:, 0], items=keys[:, 1], ratings=vals,
                      n_users=len(user_ids), n_items=len(item_ids))


def candidate_mask(items: Dict[int, object], n_items: int, item_ids: BiMap,
                   white_list: Optional[Sequence[str]] = None,
                   black_list: Iterable[str] = (),
                   exclude_idx: Iterable[int] = (),
                   categories: Optional[Sequence[str]] = None,
                   category_black_list: Optional[Sequence[str]] = None,
                   ) -> np.ndarray:
    """Boolean [I] filter; ``items`` values expose ``.categories``.

    Semantics of the reference's ``isCandidateItem``: whitelist keeps only
    listed items; blacklist and the query's own items are dropped; with a
    ``categories`` filter, items lacking any overlapping category
    (including items with no categories at all) are dropped."""
    mask = np.ones(n_items, dtype=bool)
    if white_list is not None:
        white = np.zeros(n_items, dtype=bool)
        for it in white_list:
            idx = item_ids.get(it)
            if idx is not None:
                white[idx] = True
        mask &= white
    for it in black_list:
        idx = item_ids.get(it)
        if idx is not None:
            mask[idx] = False
    for idx in exclude_idx:
        if 0 <= idx < n_items:
            mask[idx] = False
    if categories is not None:
        cats = set(categories)
        for i in np.flatnonzero(mask):
            item_cats = getattr(items.get(int(i)), "categories", None)
            mask[i] = bool(item_cats) and bool(set(item_cats) & cats)
    if category_black_list is not None:
        bad = set(category_black_list)
        for i in np.flatnonzero(mask):
            item_cats = getattr(items.get(int(i)), "categories", None) or ()
            if set(item_cats) & bad:
                mask[i] = False
    return mask


def top_scores(scores: np.ndarray, mask: np.ndarray, num: int,
               positive_only: bool = True) -> List[Tuple[int, float]]:
    """Top-``num`` (index, score) over the masked scores, descending;
    O(I) partition + O(num log num) sort."""
    s = np.where(mask, scores, -np.inf)
    if positive_only:
        s = np.where(s > 0, s, -np.inf)
    k = min(num, len(s))
    if k <= 0:
        return []
    idx = np.argpartition(-s, k - 1)[:k] if k < len(s) else np.argsort(-s)
    idx = idx[np.argsort(-s[idx], kind="stable")]
    return [(int(i), float(s[i])) for i in idx if np.isfinite(s[i])]
