"""Sequential-recommendation template: next-item prediction from
chronological item histories via causal self-attention
(``models/seqrec.py``) — a model family BEYOND the reference's inventory
(it has no sequence models), expressed in the same DASE shape as every
shipped template so the whole lifecycle (train/deploy/eval/
batchpredict) applies unchanged.

Query: ``{"user": "u1", "num": 10}`` (recent history read from the
event store at serving time — the e-commerce template's realtime-lookup
pattern) or ``{"items": ["i3", "i9"], "num": 10}`` for an explicit
session history. Known items in the history are excluded from results.

Sharding baseline (ISSUE 14): this template holds NO PartitionSpecs of
its own — it hands ``ctx.mesh`` to ``models/seqrec.py``, whose batch
sharding derives from the mesh via ``rows_spec`` (the hard-coded
``P(("data","model"))`` it used to carry broke on any other mesh).
The compiled collective structure of the training step is pinned by
the ``seqrec_train_step`` entry of ``ptpu audit-hlo``; the sequential
mesh/fused-kernel ROADMAP work starts from that clean slate.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..controller import (
    Context,
    DataSource,
    IdentityPreparator,
    FirstServing,
    Algorithm,
    Engine,
    SanityCheck,
)
from ..controller.metric import AverageMetric, ndcg_at_k
from ..data.bimap import BiMap
from ..models.data import ratings_from_columnar
from ..models.seqrec import (
    SeqRecModel,
    SeqRecParams,
    recommend_next_batch,
    sequences_from_ratings,
    train_seqrec,
)


@dataclass(frozen=True)
class Query:
    user: Optional[str] = None
    items: Optional[Tuple[str, ...]] = None
    num: int = 10
    #: exclude history items from results (serving default). Eval turns
    #: it off: leave-one-out targets may legitimately REPEAT an item
    #: from the prefix, and an unconditional filter would score every
    #: repeat-consumption user 0 regardless of model quality.
    exclude_known: bool = True

    def __post_init__(self):
        if self.items is not None:
            object.__setattr__(self, "items", tuple(self.items))


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...] = ()

    def to_json(self) -> dict:
        return {"itemScores": [{"item": s.item, "score": s.score}
                               for s in self.item_scores]}


@dataclass
class TrainingData(SanityCheck):
    sequences: np.ndarray      # [n_users, max_len] int32, -1 padded
    item_ids: BiMap
    n_items: int
    events: Tuple[str, ...] = ()
    app_name: str = ""

    def sanity_check(self):
        if (self.sequences >= 0).sum() == 0:
            raise ValueError("no interaction events found")


@dataclass(frozen=True)
class DataSourceParams:
    app_name: str = ""
    #: events forming the sequence, in preference order
    events: Tuple[str, ...] = ("view", "rate", "buy")
    max_len: int = 50
    #: top-N requested by eval queries
    eval_query_num: int = 10


@dataclass(frozen=True)
class EvalInfo:
    n_users: int = 0


@dataclass(frozen=True)
class ActualResult:
    #: the held-out NEXT item (leave-one-out)
    item: str = ""


class SequentialDataSource(DataSource):
    """Chronological per-user item sequences from the columnar bulk
    read (no per-event Python objects on the training path)."""

    def __init__(self, params: DataSourceParams = DataSourceParams()):
        self.params = params

    def read_training(self, ctx: Context) -> TrainingData:
        app = self.params.app_name or ctx.app_name
        batch = ctx.event_store.find_columnar(
            app, entity_type="user", target_entity_type="item",
            event_names=list(self.params.events), ordered=False,
            with_props=False)
        coo, user_ids, item_ids = ratings_from_columnar(
            batch, event_weights={e: 1.0 for e in self.params.events})
        sel_times = self._times_for(batch, coo)
        seqs = sequences_from_ratings(coo.users, coo.items, sel_times,
                                      coo.n_users, self.params.max_len)
        return TrainingData(sequences=seqs, item_ids=item_ids,
                            n_items=coo.n_items,
                            events=tuple(self.params.events),
                            app_name=app)

    def read_eval(self, ctx: Context):
        """Leave-one-out: per user with ≥3 interactions, hold out the
        LAST item; the query carries the prefix explicitly (eval is
        storage-independent), the actual is the held-out next item —
        the standard sequential-recommendation protocol."""
        td = self.read_training(ctx)
        inv = td.item_ids.inverse
        train = td.sequences.copy()
        qa = []
        for row in range(len(train)):
            real = train[row][train[row] >= 0]
            if len(real) < 3:
                continue
            target = int(real[-1])
            prefix = [int(x) for x in real[:-1]]
            # drop the held-out item from the training window
            train[row, :] = -1
            train[row, -len(prefix):] = prefix
            qa.append((Query(items=tuple(inv[i] for i in prefix),
                             num=self.params.eval_query_num,
                             exclude_known=False),
                       ActualResult(item=inv[target])))
        td_train = TrainingData(sequences=train, item_ids=td.item_ids,
                                n_items=td.n_items, events=td.events,
                                app_name=td.app_name)
        return [(td_train, EvalInfo(n_users=len(qa)), qa)]

    @staticmethod
    def _times_for(batch, coo) -> np.ndarray:
        """Event times aligned to the COO entries: the batch holds only
        the requested event names (filter pushdown) with fixed weights,
        so ratings_from_columnar's selection is exactly target>=0."""
        times = np.asarray(batch.event_time)[
            np.asarray(batch.target_id) >= 0]
        assert len(times) == len(coo.users), (len(times), len(coo.users))
        return times


class HitRateAtK(AverageMetric):
    """Fraction of users whose held-out next item appears in the top-k
    (the standard leave-one-out sequential-rec metric)."""

    def __init__(self, k: int = 10):
        self.k = k

    @property
    def header(self) -> str:
        return f"HitRate@{self.k}"

    def calculate_point(self, ei, q: Query, p: PredictedResult,
                        a: ActualResult):
        top = [s.item for s in p.item_scores[: self.k]]
        return 1.0 if a.item in top else 0.0


class SeqNDCGAtK(AverageMetric):
    """Binary NDCG@k of the single held-out next item."""

    def __init__(self, k: int = 10):
        self.k = k

    @property
    def header(self) -> str:
        return f"SeqNDCG@{self.k}"

    def calculate_point(self, ei, q: Query, p: PredictedResult,
                        a: ActualResult):
        return ndcg_at_k([s.item for s in p.item_scores], {a.item},
                         self.k) or 0.0


class SeqRecAlgorithm(Algorithm):
    """DASE wrapper over :func:`train_seqrec`."""

    query_class = Query

    def __init__(self, params: SeqRecParams = SeqRecParams()):
        self.params = params

    def train(self, ctx: Context, td: TrainingData) -> SeqRecModel:
        model, losses = train_seqrec(td.sequences, td.n_items,
                                     self.params, mesh=ctx.mesh,
                                     item_ids=td.item_ids,
                                     events=td.events,
                                     app_name=td.app_name)
        return model

    # serving-time history lookup (the e-commerce realtime pattern)
    def bind_serving(self, ctx: Context) -> None:
        self._serving_store = ctx.event_store
        self._app_name = ctx.app_name

    def _history_for(self, model: SeqRecModel, query: Query) -> list:
        """Resolve one query's item-index history (explicit session
        items, or a serving-time event-store read for user queries)."""
        ids: BiMap = model.item_ids
        history: list = []
        if query.items:
            history = [ids[i] for i in query.items if i in ids]
        elif query.user:
            store = getattr(self, "_serving_store", None)
            if store is None:
                from ..data.store import event_store as store  # noqa: F811
            try:
                evs = store.find_by_entity(
                    model.app_name
                    or getattr(self, "_app_name", "") or "", "user",
                    query.user, target_entity_type="item",
                    event_names=(list(model.events)
                                 if model.events else None),
                    limit=model.params.max_len, latest=True,
                    timeout_ms=200)
            except Exception:  # noqa: BLE001 — serving never hard-fails
                evs = []
            # latest-first → chronological
            history = [ids[e.target_entity_id] for e in reversed(evs)
                       if e.target_entity_id in ids]
        return history

    def _results(self, model: SeqRecModel, query: Query, history,
                 idx, scores) -> PredictedResult:
        known = set(history) if query.exclude_known else set()
        inv = model.item_ids.inverse
        out = [(int(i), float(s)) for i, s in zip(idx, scores)
               if int(i) not in known][: query.num]
        return PredictedResult(tuple(
            ItemScore(item=inv[i], score=s) for i, s in out))

    def warm_serving(self, model: SeqRecModel,
                     max_batch: int = 1) -> None:
        """Pre-compile the serving kernels for the pow2 batch ladder
        (cf. ``ServerConfig.warm_start``; each novel shape is a fresh
        XLA compile, 6-20s through a device tunnel)."""
        if model.n_items <= 0:
            return
        b = 1
        top = max(max_batch, 1)
        while True:
            recommend_next_batch(model, [[0]] * b, k=10)
            if b >= top:  # pow2 ceiling: the padded largest batch too
                break
            b *= 2

    def predict(self, model: SeqRecModel, query: Query) -> PredictedResult:
        # single-query = batch of one: exactly one over-fetch rule
        return self.batch_predict(model, [query])[0]

    def batch_predict(self, model: SeqRecModel,
                      queries: Sequence[Query]) -> List[PredictedResult]:
        """ONE device dispatch for the whole batch (the batch-predict
        job and the serving micro-batcher both call this). Serving-time
        store reads for user queries run CONCURRENTLY — serialized
        200ms-bounded lookups would cost the coalesced batch more than
        the dispatch it saves."""
        if len(queries) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                    max_workers=min(8, len(queries))) as pool:
                hists = list(pool.map(
                    lambda q: self._history_for(model, q), queries))
        else:
            hists = [self._history_for(model, q) for q in queries]
        live = [i for i, h in enumerate(hists) if h]
        out: List[PredictedResult] = [PredictedResult()] * len(queries)
        if not live:
            return out
        k = max(queries[i].num
                + (len(set(hists[i]))
                   if queries[i].exclude_known else 0)
                for i in live)
        ids, scores = recommend_next_batch(
            model, [hists[i] for i in live],
            k=min(k, model.n_items))
        for row, i in enumerate(live):
            out[i] = self._results(model, queries[i], hists[i],
                                   ids[row], scores[row])
        return out


class SequentialServing(FirstServing):
    pass


def sequential_engine() -> Engine:
    """Engine factory."""
    return Engine(
        datasource_classes=SequentialDataSource,
        preparator_classes=IdentityPreparator,
        algorithm_classes={"seqrec": SeqRecAlgorithm,
                           "": SeqRecAlgorithm},
        serving_classes=SequentialServing,
        datasource_params_class=DataSourceParams,
        algorithm_params_classes={"seqrec": SeqRecParams,
                                  "": SeqRecParams},
    )
