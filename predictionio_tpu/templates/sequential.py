"""Sequential-recommendation template: next-item prediction from
chronological item histories via causal self-attention
(``models/seqrec.py``) — a model family BEYOND the reference's inventory
(it has no sequence models), expressed in the same DASE shape as every
shipped template so the whole lifecycle (train/deploy/eval/
batchpredict) applies unchanged.

Query: ``{"user": "u1", "num": 10}`` (recent history read from the
event store at serving time — the e-commerce template's realtime-lookup
pattern) or ``{"items": ["i3", "i9"], "num": 10}`` for an explicit
session history. Known items in the history are excluded from results.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import numpy as np

from ..controller import (
    Context,
    DataSource,
    IdentityPreparator,
    FirstServing,
    Algorithm,
    Engine,
    SanityCheck,
)
from ..data.bimap import BiMap
from ..models.data import ratings_from_columnar
from ..models.seqrec import (
    SeqRecModel,
    SeqRecParams,
    recommend_next,
    sequences_from_ratings,
    train_seqrec,
)


@dataclass(frozen=True)
class Query:
    user: Optional[str] = None
    items: Optional[Tuple[str, ...]] = None
    num: int = 10

    def __post_init__(self):
        if self.items is not None:
            object.__setattr__(self, "items", tuple(self.items))


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...] = ()

    def to_json(self) -> dict:
        return {"itemScores": [{"item": s.item, "score": s.score}
                               for s in self.item_scores]}


@dataclass
class TrainingData(SanityCheck):
    sequences: np.ndarray      # [n_users, max_len] int32, -1 padded
    item_ids: BiMap
    n_items: int
    events: Tuple[str, ...] = ()
    app_name: str = ""

    def sanity_check(self):
        if (self.sequences >= 0).sum() == 0:
            raise ValueError("no interaction events found")


@dataclass(frozen=True)
class DataSourceParams:
    app_name: str = ""
    #: events forming the sequence, in preference order
    events: Tuple[str, ...] = ("view", "rate", "buy")
    max_len: int = 50


class SequentialDataSource(DataSource):
    """Chronological per-user item sequences from the columnar bulk
    read (no per-event Python objects on the training path)."""

    def __init__(self, params: DataSourceParams = DataSourceParams()):
        self.params = params

    def read_training(self, ctx: Context) -> TrainingData:
        app = self.params.app_name or ctx.app_name
        batch = ctx.event_store.find_columnar(
            app, entity_type="user", target_entity_type="item",
            event_names=list(self.params.events), ordered=False,
            with_props=False)
        coo, user_ids, item_ids = ratings_from_columnar(
            batch, event_weights={e: 1.0 for e in self.params.events})
        sel_times = self._times_for(batch, coo)
        seqs = sequences_from_ratings(coo.users, coo.items, sel_times,
                                      coo.n_users, self.params.max_len)
        return TrainingData(sequences=seqs, item_ids=item_ids,
                            n_items=coo.n_items,
                            events=tuple(self.params.events),
                            app_name=app)

    @staticmethod
    def _times_for(batch, coo) -> np.ndarray:
        """Event times aligned to the COO entries: the batch holds only
        the requested event names (filter pushdown) with fixed weights,
        so ratings_from_columnar's selection is exactly target>=0."""
        times = np.asarray(batch.event_time)[
            np.asarray(batch.target_id) >= 0]
        assert len(times) == len(coo.users), (len(times), len(coo.users))
        return times


class SeqRecAlgorithm(Algorithm):
    """DASE wrapper over :func:`train_seqrec`."""

    query_class = Query

    def __init__(self, params: SeqRecParams = SeqRecParams()):
        self.params = params

    def train(self, ctx: Context, td: TrainingData) -> SeqRecModel:
        model, losses = train_seqrec(td.sequences, td.n_items,
                                     self.params, mesh=ctx.mesh,
                                     item_ids=td.item_ids,
                                     events=td.events,
                                     app_name=td.app_name)
        return model

    # serving-time history lookup (the e-commerce realtime pattern)
    def bind_serving(self, ctx: Context) -> None:
        self._serving_store = ctx.event_store
        self._app_name = ctx.app_name

    def predict(self, model: SeqRecModel, query: Query) -> PredictedResult:
        ids: BiMap = model.item_ids
        history: list = []
        if query.items:
            history = [ids[i] for i in query.items if i in ids]
        elif query.user:
            store = getattr(self, "_serving_store", None)
            if store is None:
                from ..data.store import event_store as store  # noqa: F811
            try:
                evs = store.find_by_entity(
                    model.app_name
                    or getattr(self, "_app_name", "") or "", "user",
                    query.user, target_entity_type="item",
                    event_names=(list(model.events)
                                 if model.events else None),
                    limit=model.params.max_len, latest=True,
                    timeout_ms=200)
            except Exception:  # noqa: BLE001 — serving never hard-fails
                evs = []
            # latest-first → chronological
            history = [ids[e.target_entity_id] for e in reversed(evs)
                       if e.target_entity_id in ids]
        if not history:
            return PredictedResult()
        known = set(history)
        idx, scores = recommend_next(model, history,
                                     k=query.num + len(known))
        inv = ids.inverse
        out = [(int(i), float(s)) for i, s in zip(idx, scores)
               if int(i) not in known][: query.num]
        return PredictedResult(tuple(
            ItemScore(item=inv[i], score=s) for i, s in out))


class SequentialServing(FirstServing):
    pass


def sequential_engine() -> Engine:
    """Engine factory."""
    return Engine(
        datasource_classes=SequentialDataSource,
        preparator_classes=IdentityPreparator,
        algorithm_classes={"seqrec": SeqRecAlgorithm,
                           "": SeqRecAlgorithm},
        serving_classes=SequentialServing,
        datasource_params_class=DataSourceParams,
        algorithm_params_classes={"seqrec": SeqRecParams,
                                  "": SeqRecParams},
    )
