"""Similar-product engine template (multi-events, multi-algos).

Capability parity with the reference
``examples/scala-parallel-similarproduct/multi-events-multi-algos/``:
DataSource reads user/item entities plus ``view`` and ``like``/``dislike``
events (``DataSource.scala:43-140``); three algorithms —
implicit-ALS item-factor cosine (``ALSAlgorithm.scala:60-200``),
co-occurrence counting (``CooccurrenceAlgorithm.scala:45-160``), and the
like/dislike ±1 ALS variant (``LikeAlgorithm.scala:32-95``) — are combined
by a z-score-standardizing Serving (``Serving.scala:26-70``).

TPU shape: the per-item ``.par`` cosine loops become one
``[Q, rank] @ [rank, I]`` matmul over L2-normalized factors; candidate
filters are boolean masks applied before a device ``top_k``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..controller import (
    Algorithm,
    Context,
    DataSource,
    Engine,
    EngineParams,
    IdentityPreparator,
    SanityCheck,
    Serving,
)
from ..data.bimap import BiMap
from ..models.als import ALSParams, RatingsCOO, pack_ratings_cached, train_als
from ..models.cooccurrence import CooccurrenceModel, train_cooccurrence
from ._common import candidate_mask, dedup_view_ratings, top_scores


# -- query / result (Engine.scala:23-41) -------------------------------------

@dataclass(frozen=True)
class Query:
    items: Tuple[str, ...]
    num: int = 10
    categories: Optional[Tuple[str, ...]] = None
    category_black_list: Optional[Tuple[str, ...]] = None
    white_list: Optional[Tuple[str, ...]] = None
    black_list: Optional[Tuple[str, ...]] = None

    def __init__(self, items, num=10, categories=None,
                 category_black_list=None, white_list=None, black_list=None):
        conv = lambda v: tuple(v) if v is not None else None
        object.__setattr__(self, "items", tuple(items))
        object.__setattr__(self, "num", num)
        object.__setattr__(self, "categories", conv(categories))
        object.__setattr__(self, "category_black_list",
                           conv(category_black_list))
        object.__setattr__(self, "white_list", conv(white_list))
        object.__setattr__(self, "black_list", conv(black_list))


@dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclass(frozen=True)
class PredictedResult:
    item_scores: Tuple[ItemScore, ...] = ()

    def to_json(self) -> dict:
        return {"itemScores": [{"item": s.item, "score": s.score}
                               for s in self.item_scores]}


@dataclass(frozen=True)
class Item:
    categories: Optional[Tuple[str, ...]] = None


@dataclass(frozen=True)
class ViewEvent:
    user: str
    item: str
    t: float


@dataclass(frozen=True)
class LikeEvent:
    user: str
    item: str
    t: float
    like: bool


@dataclass
class TrainingData(SanityCheck):
    users: Dict[str, dict]
    items: Dict[str, Item]
    view_events: List[ViewEvent]
    like_events: List[LikeEvent]

    def sanity_check(self):
        if not self.users or not self.items:
            raise ValueError("users/items cannot be empty")


@dataclass(frozen=True)
class DataSourceParams:
    app_name: str = ""


class SimilarProductDataSource(DataSource):
    """``DataSource.scala:36-140``."""

    def __init__(self, params: DataSourceParams = DataSourceParams()):
        self.params = params

    def read_training(self, ctx: Context) -> TrainingData:
        app = self.params.app_name or ctx.app_name
        users = {eid: {} for eid in
                 ctx.event_store.aggregate_properties(app, "user")}
        items = {}
        for eid, pm in ctx.event_store.aggregate_properties(
                app, "item").items():
            cats = pm.get("categories")
            items[eid] = Item(categories=tuple(cats) if cats else None)
        views, likes = [], []
        for e in ctx.event_store.find(
                app, entity_type="user", event_names=["view"],
                target_entity_type="item"):
            views.append(ViewEvent(e.entity_id, e.target_entity_id,
                                   e.event_time.timestamp()))
        for e in ctx.event_store.find(
                app, entity_type="user", event_names=["like", "dislike"],
                target_entity_type="item"):
            likes.append(LikeEvent(e.entity_id, e.target_entity_id,
                                   e.event_time.timestamp(),
                                   like=(e.event == "like")))
        return TrainingData(users, items, views, likes)


# -- shared model: item factors + metadata -----------------------------------

@dataclass
class SPModel:
    item_factors: np.ndarray          # [I, rank]; rows may be all-zero
    has_factors: np.ndarray           # [I] bool
    item_ids: BiMap
    items: Dict[int, Item]


def _query_mask(model_items: Dict[int, Item], n_items: int,
                query_idx: Set[int], query: Query,
                item_ids: BiMap) -> np.ndarray:
    """Candidate filter (``CooccurrenceAlgorithm.isCandidateItem``
    :153-173 + the ALS variant's categoryBlackList); query items are
    always excluded."""
    return candidate_mask(
        model_items, n_items, item_ids,
        white_list=query.white_list, black_list=query.black_list or (),
        exclude_idx=query_idx, categories=query.categories,
        category_black_list=query.category_black_list)


class SPALSAlgorithm(Algorithm):
    """Implicit ALS on deduped view counts; predict = summed cosine
    between query items' factors and every item (``ALSAlgorithm.scala``)."""

    query_class = Query

    def __init__(self, params: ALSParams = ALSParams(
            rank=10, num_iterations=20, reg=0.01,
            implicit_prefs=True, alpha=1.0)):
        self.params = params

    def _check(self, td: TrainingData) -> None:
        if not td.view_events:
            raise ValueError("viewEvents cannot be empty")

    def _ratings(self, td: TrainingData, user_ids: BiMap,
                 item_ids: BiMap) -> RatingsCOO:
        return dedup_view_ratings(td.view_events, user_ids, item_ids)

    def train(self, ctx: Context, td: TrainingData) -> SPModel:
        self._check(td)
        user_ids = BiMap.string_int(td.users.keys())
        item_ids = BiMap.string_int(td.items.keys())
        ratings = self._ratings(td, user_ids, item_ids)
        packed = pack_ratings_cached(ratings, self.params, mesh=ctx.mesh)
        _, V = train_als(ratings, self.params, mesh=ctx.mesh, packed=packed)
        V = np.asarray(V)[:len(item_ids)]
        has = np.zeros(len(item_ids), dtype=bool)
        has[np.unique(ratings.items)] = True
        items = {item_ids[k]: v for k, v in td.items.items()}
        return SPModel(V, has, item_ids, items)

    def predict(self, model: SPModel, query: Query) -> PredictedResult:
        query_idx = {model.item_ids[i] for i in query.items
                     if i in model.item_ids}
        qf = [model.item_factors[i] for i in query_idx
              if model.has_factors[i]]
        if not qf:
            return PredictedResult()
        # summed cosine = (normalized query factors) @ (normalized factors)ᵀ
        Q = np.stack(qf)
        Qn = Q / np.maximum(np.linalg.norm(Q, axis=1, keepdims=True), 1e-12)
        V = model.item_factors
        Vn = V / np.maximum(np.linalg.norm(V, axis=1, keepdims=True), 1e-12)
        scores = Qn @ Vn.T
        scores = scores.sum(axis=0)
        scores[~model.has_factors] = 0.0
        mask = _query_mask(model.items, len(scores), query_idx, query,
                           model.item_ids)
        inv = model.item_ids.inverse
        return PredictedResult(tuple(
            ItemScore(inv[i], s)
            for i, s in top_scores(scores, mask, query.num)))


class SPLikeAlgorithm(SPALSAlgorithm):
    """±1 ratings from the LATEST like/dislike per (user, item)
    (``LikeAlgorithm.scala:59-95``); training flow shared with the ALS
    base, only the ratings construction differs."""

    def _check(self, td: TrainingData) -> None:
        if not td.like_events:
            raise ValueError("likeEvents cannot be empty")

    def _ratings(self, td: TrainingData, user_ids: BiMap,
                 item_ids: BiMap) -> RatingsCOO:
        latest: Dict[Tuple[int, int], Tuple[float, bool]] = {}
        for ev in td.like_events:
            u, i = user_ids.get(ev.user), item_ids.get(ev.item)
            if u is None or i is None:
                continue
            cur = latest.get((u, i))
            if cur is None or ev.t > cur[0]:
                latest[(u, i)] = (ev.t, ev.like)
        if not latest:
            raise ValueError("likeEvents cannot be empty")
        keys = np.array(list(latest.keys()), dtype=np.int32)
        vals = np.array([1.0 if like else -1.0
                         for _, like in latest.values()], dtype=np.float32)
        return RatingsCOO(users=keys[:, 0], items=keys[:, 1], ratings=vals,
                          n_users=len(user_ids), n_items=len(item_ids))


@dataclass(frozen=True)
class CooccurrenceParams:
    n: int = 20


class SPCooccurrenceAlgorithm(Algorithm):
    """``CooccurrenceAlgorithm.scala:45-160``."""

    query_class = Query

    def __init__(self, params: CooccurrenceParams = CooccurrenceParams()):
        self.params = params

    def train(self, ctx: Context, td: TrainingData
              ) -> Tuple[CooccurrenceModel, BiMap, Dict[int, Item]]:
        item_ids = BiMap.string_int(td.items.keys())
        user_ids = BiMap.string_int(td.users.keys())
        pairs = [(user_ids[v.user], item_ids[v.item]) for v in td.view_events
                 if v.user in user_ids and v.item in item_ids]
        if not pairs:
            raise ValueError("no valid view events")
        arr = np.array(pairs, dtype=np.int64)
        model = train_cooccurrence(arr[:, 0], arr[:, 1],
                                   len(user_ids), len(item_ids),
                                   self.params.n)
        items = {item_ids[k]: v for k, v in td.items.items()}
        return (model, item_ids, items)

    def predict(self, model, query: Query) -> PredictedResult:
        cooc, item_ids, items = model
        query_idx = {item_ids[i] for i in query.items if i in item_ids}
        scored = cooc.score_items(sorted(query_idx))
        scores = np.zeros(cooc.n_items)
        for i, c in scored.items():
            scores[i] = c
        mask = _query_mask(items, cooc.n_items, query_idx, query, item_ids)
        inv = item_ids.inverse
        return PredictedResult(tuple(
            ItemScore(inv[i], s)
            for i, s in top_scores(scores, mask, query.num)))


class SimilarProductServing(Serving):
    """z-score standardize each algorithm's scores (skipped when num==1),
    then sum per item (``Serving.scala:26-70``)."""

    def serve(self, query: Query,
              predictions: Sequence[PredictedResult]) -> PredictedResult:
        if query.num == 1:
            standardized = [p.item_scores for p in predictions]
        else:
            standardized = []
            for p in predictions:
                vals = np.array([s.score for s in p.item_scores])
                if vals.size and vals.std() > 0:
                    mean, std = vals.mean(), vals.std(ddof=1)
                else:
                    mean, std = 0.0, 0.0
                standardized.append(tuple(
                    ItemScore(s.item,
                              0.0 if std == 0
                              else (s.score - mean) / std)
                    for s in p.item_scores))
        combined: Dict[str, float] = {}
        for group in standardized:
            for s in group:
                combined[s.item] = combined.get(s.item, 0.0) + s.score
        top = sorted(combined.items(), key=lambda kv: -kv[1])[:query.num]
        return PredictedResult(tuple(ItemScore(i, v) for i, v in top))


def similarproduct_engine() -> Engine:
    """``SimilarProductEngine`` factory (``Engine.scala:43-54``)."""
    return Engine(
        datasource_classes=SimilarProductDataSource,
        preparator_classes=IdentityPreparator,
        algorithm_classes={"als": SPALSAlgorithm,
                           "cooccurrence": SPCooccurrenceAlgorithm,
                           "likealgo": SPLikeAlgorithm,
                           "": SPALSAlgorithm},
        serving_classes=SimilarProductServing,
        datasource_params_class=DataSourceParams,
        algorithm_params_classes={"als": ALSParams,
                                  "cooccurrence": CooccurrenceParams,
                                  "likealgo": ALSParams,
                                  "": ALSParams},
    )
