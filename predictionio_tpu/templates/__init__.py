"""Engine templates — the workloads the framework ships with, mirroring
the reference's example engines (SURVEY §2.2)."""

from . import recommendation

__all__ = ["recommendation"]
