"""Replica lifecycle manager (ISSUE 18): spawn → warm → ready →
drain → terminate, as an explicit state machine.

The autoscaler decides *how many* replicas; this module owns *how one
replica joins or leaves* without dropping a query:

- **spawn/warm gating** — a freshly spawned replica serves nothing
  until its own ``/status.json`` reports ``servingWarm`` (the
  ``pio_serving_warm`` gauge): the warm-start compile ladder must
  finish before the ring sends it traffic, or its first queries eat
  multi-second jit compiles and light the latency SLO the scale-out
  was meant to protect. Only on READY does the replica enter the
  router's ring and the aggregator's scrape set.
- **drain** — leaving is the mirror image: the replica first drops
  out of the ring (no NEW assignments), is told to advertise
  ``lifecycle: draining`` in its ``/status.json`` (so the fleet
  aggregator excludes it from rollups and the headroom denominator
  without an availability flap — the satellite fix of ISSUE 18), and
  only once the router counts zero in-flight requests on it — or the
  drain deadline expires — is it actually stopped and removed.
- **dead** — the chaos path: a replica that failed its health signal
  is removed immediately (best-effort stop), and the autoscaler's
  next evaluation replaces it.

Spawning and probing are injectable callables, so unit tests drive
the state machine with fakes while ``ptpu deploy --fleet-of`` and the
autoscale smoke plug in real engine servers.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..concurrency import new_lock

__all__ = ["ReplicaLifecycle", "STATES"]

#: the full state vocabulary, in lifecycle order
STATES = ("spawning", "warming", "ready", "draining", "terminated",
          "dead")


def _default_probe(base: str, timeout: float) -> Dict[str, Any]:
    import urllib.request

    with urllib.request.urlopen(base + "/status.json",
                                timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _default_notify_drain(base: str, timeout: float,
                          accesskey: Optional[str] = None) -> None:
    import urllib.parse
    import urllib.request

    url = base + "/drain"
    if accesskey:
        url += "?accessKey=" + urllib.parse.quote(accesskey)
    req = urllib.request.Request(url, data=b"")
    with urllib.request.urlopen(req, timeout=timeout):
        pass


class _Managed:
    __slots__ = ("name", "base", "stop_fn", "state", "since",
                 "reason")

    def __init__(self, name: str, base: str,
                 stop_fn: Optional[Callable[[], None]],
                 state: str, now: float) -> None:
        self.name = name
        self.base = base
        self.stop_fn = stop_fn
        self.state = state
        self.since = now
        self.reason = ""


class ReplicaLifecycle:
    """Owns the managed-replica table and the per-replica worker
    threads that walk the state machine.

    ``spawn() -> (replica_spec, stop_fn)`` boots one replica and
    returns its address (``host:port`` or URL) plus the callable that
    stops it. ``probe(base, timeout) -> status-dict`` and
    ``notify_drain(base, timeout)`` default to real HTTP.
    """

    def __init__(self, spawn: Callable[[], Tuple[str, Callable[[], None]]],
                 router=None, aggregator=None, registry=None,
                 probe: Callable[[str, float], Dict[str, Any]] = None,
                 notify_drain: Callable[[str, float], None] = None,
                 warm_timeout_sec: float = 300.0,
                 drain_deadline_sec: float = 30.0,
                 poll_interval_sec: float = 0.25,
                 probe_timeout_sec: float = 10.0,
                 on_transition: Optional[Callable[..., None]] = None,
                 accesskey: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._spawn = spawn
        self.router = router
        self.aggregator = aggregator
        self._probe = probe or _default_probe
        self._notify_drain = notify_drain or (
            lambda base, timeout: _default_notify_drain(
                base, timeout, accesskey))
        self.warm_timeout_sec = warm_timeout_sec
        self.drain_deadline_sec = drain_deadline_sec
        self.poll_interval_sec = poll_interval_sec
        self.probe_timeout_sec = probe_timeout_sec
        self.on_transition = on_transition
        self._clock = clock
        self._lock = new_lock("ReplicaLifecycle._lock")
        self._replicas: Dict[str, _Managed] = {}
        self._threads: List[threading.Thread] = []
        self._closed = threading.Event()
        self._transitions = None
        if registry is not None:
            self._transitions = registry.counter(
                "pio_autoscale_transitions_total",
                "Replica lifecycle transitions by destination state")
            fam = registry.gauge(
                "pio_autoscale_replicas",
                "Managed replicas by lifecycle state "
                "(spawning|warming|ready|draining)")
            for state in ("spawning", "warming", "ready", "draining"):
                fam.labels(state=state).set_fn(
                    (lambda s: lambda: float(self.count(s)))(state))

    # -- bookkeeping --------------------------------------------------------
    def _set_state(self, m: _Managed, state: str,
                   reason: str = "") -> None:
        with self._lock:
            m.state = state
            m.since = self._clock()
            m.reason = reason
        if self._transitions is not None:
            self._transitions.labels(to=state).inc()
        if self.on_transition is not None:
            try:
                self.on_transition(m.name, state, reason)
            except Exception:  # noqa: BLE001 — observer must not
                pass           # break the state machine

    def count(self, state: str) -> int:
        with self._lock:
            return sum(1 for m in self._replicas.values()
                       if m.state == state)

    def counts(self) -> Dict[str, int]:
        with self._lock:
            out = {s: 0 for s in STATES}
            for m in self._replicas.values():
                out[m.state] += 1
            return out

    def live_count(self) -> int:
        """Replicas that are capacity now or imminently (spawning +
        warming + ready) — what the autoscaler compares to its
        target, so an in-flight spawn is never double-ordered."""
        with self._lock:
            return sum(1 for m in self._replicas.values()
                       if m.state in ("spawning", "warming", "ready"))

    def names(self, *states: str) -> List[str]:
        with self._lock:
            return [m.name for m in self._replicas.values()
                    if not states or m.state in states]

    def replicas(self) -> List[Dict[str, Any]]:
        now = self._clock()
        with self._lock:
            return [{"replica": m.name, "base": m.base,
                     "state": m.state,
                     "inStateSec": round(now - m.since, 3),
                     "reason": m.reason}
                    for m in self._replicas.values()]

    # -- adopt (pre-existing replicas) --------------------------------------
    def adopt(self, replica: str,
              stop_fn: Optional[Callable[[], None]] = None,
              warm: bool = True) -> str:
        """Register an already-running replica (the initial
        ``--fleet-of`` members). ``warm=False`` walks it through the
        warm gate like a fresh spawn."""
        name, base = _normalize(replica)
        m = _Managed(name, base, stop_fn,
                     "ready" if warm else "warming", self._clock())
        with self._lock:
            self._replicas[name] = m
        if warm:
            if self.router is not None:
                self.router.add(base)
            if self.aggregator is not None:
                self.aggregator.add_replica(base)
            self._set_state(m, "ready", "adopted")
        else:
            self._start_thread(self._warm_then_join, m)
        return name

    # -- scale out ----------------------------------------------------------
    def scale_out(self, reason: str = "") -> None:
        """Order one new replica; returns immediately (spawn + warm
        run on a worker thread — warm-up is seconds-to-minutes)."""
        self._start_thread(self._spawn_one, reason)

    def _spawn_one(self, reason: str) -> None:
        placeholder = _Managed(f"(spawning-{id(object()):x})", "",
                               None, "spawning", self._clock())
        with self._lock:
            self._replicas[placeholder.name] = placeholder
        try:
            spec, stop_fn = self._spawn()
        except Exception as e:  # noqa: BLE001 — a failed spawn is a
            # data point for the next evaluation, not a crash
            self._set_state(placeholder, "dead",
                            f"spawn failed: {e}")
            return
        name, base = _normalize(spec)
        with self._lock:
            del self._replicas[placeholder.name]
            m = _Managed(name, base, stop_fn, "warming",
                         self._clock())
            m.reason = reason
            self._replicas[name] = m
        self._set_state(m, "warming", reason)
        self._warm_then_join(m)

    def _warm_then_join(self, m: _Managed) -> None:
        deadline = self._clock() + self.warm_timeout_sec
        artifact_warm = False
        while not self._closed.is_set():
            try:
                status = self._probe(m.base, self.probe_timeout_sec)
                if status.get("servingWarm"):
                    # how the replica warmed: loaded AOT artifacts vs a
                    # cold compile ladder — the fleet-level signal that
                    # the sub-second cold-start path actually engaged
                    artifact_warm = bool(status.get("artifactWarm"))
                    break
            except Exception:  # noqa: BLE001 — not up yet
                pass
            if self._clock() >= deadline:
                self._terminate(m, "warm timeout", state="dead")
                return
            self._closed.wait(self.poll_interval_sec)
        if self._closed.is_set():
            return
        # warm: NOW it may take traffic and be scraped
        if self.router is not None:
            self.router.add(m.base)
        if self.aggregator is not None:
            self.aggregator.add_replica(m.base)
        self._set_state(m, "ready", m.reason or (
            "warmed from artifact" if artifact_warm else "warmed (compile)"))

    # -- scale in -----------------------------------------------------------
    def pick_drain_victim(self) -> Optional[str]:
        """Least-loaded ready replica (fewest in-flight through the
        router), newest first on ties — the cheapest member to lose."""
        with self._lock:
            ready = [m for m in self._replicas.values()
                     if m.state == "ready"]
        if not ready:
            return None
        if self.router is not None:
            ready.sort(key=lambda m: (self.router.inflight(m.name),
                                      -m.since))
        else:
            ready.sort(key=lambda m: -m.since)
        return ready[0].name

    def scale_in(self, name: Optional[str] = None,
                 reason: str = "") -> Optional[str]:
        """Begin draining ``name`` (default: the drain victim);
        returns the name or None when nothing is drainable."""
        victim = name or self.pick_drain_victim()
        if victim is None:
            return None
        with self._lock:
            m = self._replicas.get(victim)
            if m is None or m.state != "ready":
                return None
        self._set_state(m, "draining", reason)
        if self.router is not None:
            self.router.drain(m.name)
        self._start_thread(self._drain_then_stop, m)
        return victim

    def _drain_then_stop(self, m: _Managed) -> None:
        # tell the replica itself: its /status.json flips to
        # lifecycle=draining so the aggregator reclassifies it before
        # its scrapes stop (no pio_fleet_replica_up flap)
        try:
            self._notify_drain(m.base, self.probe_timeout_sec)
        except Exception:  # noqa: BLE001 — an unreachable replica
            pass           # drains by deadline instead
        deadline = self._clock() + self.drain_deadline_sec
        while not self._closed.is_set() and self._clock() < deadline:
            inflight = (self.router.inflight(m.name)
                        if self.router is not None else 0)
            if inflight <= 0:
                break
            self._closed.wait(self.poll_interval_sec)
        self._terminate(m, m.reason or "scale-in", state="terminated")

    # -- hard removal -------------------------------------------------------
    def mark_dead(self, name: str, reason: str = "") -> bool:
        """Chaos path: the replica failed its health signal — remove
        it NOW (best-effort stop, no drain); the autoscaler's next
        evaluation sees the missing capacity and replaces it."""
        with self._lock:
            m = self._replicas.get(name)
            if m is None or m.state in ("terminated", "dead"):
                return False
        self._terminate(m, reason or "died", state="dead")
        return True

    def _terminate(self, m: _Managed, reason: str,
                   state: str) -> None:
        if self.router is not None:
            self.router.remove(m.name)
        if self.aggregator is not None:
            self.aggregator.remove_replica(m.name)
        if m.stop_fn is not None:
            try:
                m.stop_fn()
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
        self._set_state(m, state, reason)
        with self._lock:
            self._replicas.pop(m.name, None)

    # -- plumbing -----------------------------------------------------------
    def _start_thread(self, fn: Callable, *args: Any) -> None:
        t = threading.Thread(target=fn, args=args, daemon=True,
                             name="replica-lifecycle")
        with self._lock:
            self._threads = [th for th in self._threads
                             if th.is_alive()]
            self._threads.append(t)
        t.start()

    def await_ready(self, n: int, timeout_sec: float = 300.0) -> bool:
        """Block until ``n`` replicas are READY (smokes/tests)."""
        deadline = self._clock() + timeout_sec
        while self._clock() < deadline:
            if self.count("ready") >= n:
                return True
            if self._closed.wait(self.poll_interval_sec):
                return False
        return self.count("ready") >= n

    def close(self, stop_replicas: bool = False) -> None:
        """Stop the worker threads (and optionally every managed
        replica — the smoke's teardown)."""
        self._closed.set()
        with self._lock:
            threads = list(self._threads)
            managed = list(self._replicas.values())
        for t in threads:
            t.join(timeout=10)
        if stop_replicas:
            for m in managed:
                if m.stop_fn is not None:
                    try:
                        m.stop_fn()
                    except Exception:  # noqa: BLE001
                        pass


def _normalize(replica: str) -> Tuple[str, str]:
    r = replica.strip().rstrip("/")
    if "://" in r:
        return r.split("://", 1)[1], r
    return r, "http://" + r
