"""SLO-driven autoscaling tier (ISSUE 18, docs/autoscaling.md).

Three cooperating pieces turn a static ``--fleet-of N`` deployment
into an elastic one:

- :class:`QueryRouter` — consistent-hash entity affinity over a
  :class:`HashRing` (sha256-keyed like the serving cache and pinned
  hot tier, so per-replica hit rates survive membership changes),
  with Space-Saving-confirmed hot-key spill, health ejection, and
  bounded idempotent retry;
- :class:`ReplicaLifecycle` — the spawn/warm/ready/drain/terminate
  state machine (warm gates on ``pio_serving_warm``; drain stops new
  assignments and lets in-flight work finish);
- :class:`Autoscaler` — the control loop: out on fast-window SLO burn
  or low capacity headroom, in against the CAPACITY.json knee model
  with hysteresis + cooldown, every decision traced and logged on
  ``/fleet.json``.
"""

from .autoscaler import Autoscaler, AutoscalePolicy
from .lifecycle import ReplicaLifecycle
from .ring import HashRing, key_point
from .router import (
    QueryRouter,
    RouterConfig,
    build_router_app,
    create_router_server,
)

__all__ = [
    "Autoscaler",
    "AutoscalePolicy",
    "HashRing",
    "QueryRouter",
    "ReplicaLifecycle",
    "RouterConfig",
    "build_router_app",
    "create_router_server",
    "key_point",
]
