"""SLO-driven autoscaler (ISSUE 18, docs/autoscaling.md).

One control loop closes the last gap between the repo's measurement
planes and its capacity: PR 15 measured the per-replica knee
(CAPACITY.json) and burn rates, PR 17 merged them fleet-wide
(``pio_fleet_capacity_headroom``, fleet-scoped SLOs) — this loop acts
on them.

**Scale out** when either leading indicator fires:

- the fleet SLO's *fast-window* burn is lit
  (:meth:`~predictionio_tpu.slo.SLOEngine.fast_burning`) — the
  minutes-scale early-warning signal, deliberately not the confirmed
  breach, because capacity added after the slow window confirms is
  capacity added too late;
- capacity headroom (``1 − qps/(knee×replicas)``) drops under
  ``headroom_floor`` — the model-predicted approach to the knee,
  which fires even while latency still looks fine.

**Scale in** only against the knee model, with hysteresis: headroom
must exceed ``headroom_ceiling`` (strictly above the floor)
*continuously* for ``scale_in_sustain_sec``, nothing may be burning,
and the cooldown since the last action must have elapsed. The
floor/ceiling gap plus the sustain window plus the cooldown are what
make the loop flap-free: removing one replica raises utilization by a
factor of ``n/(n−1)``, and the ceiling is chosen so the post-removal
headroom still clears the floor (docs/autoscaling.md has the
arithmetic).

**Heal** is separate from policy: a replica that died (health signal
down) is replaced immediately, cooldown or not — the target count is
the contract, and a corpse mid-ramp must not wait out a timer.

Every decision is traced (PR 12 force-retention, reason
``autoscale``), appended to a bounded decision log surfaced on the
fleet's ``/fleet.json``, and counted in ``pio_autoscale_*`` series.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import asdict, dataclass
from typing import Any, Callable, Dict, List, Optional

from ..concurrency import new_lock

__all__ = ["AutoscalePolicy", "Autoscaler"]


@dataclass
class AutoscalePolicy:
    """The scaling contract (CLI: ``--autoscale --min-replicas
    --max-replicas``)."""

    min_replicas: int = 1
    max_replicas: int = 8
    #: scale out when fleet headroom drops below this
    headroom_floor: float = 0.15
    #: scale in only while headroom exceeds this (must clear the floor
    #: even after losing one replica — see docs/autoscaling.md)
    headroom_ceiling: float = 0.60
    #: the ceiling must hold continuously this long before a scale-in
    scale_in_sustain_sec: float = 30.0
    #: no policy action within this window of the previous one
    cooldown_sec: float = 30.0
    #: evaluation cadence of the control loop
    interval_sec: float = 1.0

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas < min_replicas")
        if self.headroom_ceiling <= self.headroom_floor:
            raise ValueError(
                "headroom_ceiling must exceed headroom_floor "
                "(the hysteresis band)")


class Autoscaler:
    """Evaluates policy against the aggregator's merged signals and
    orders the lifecycle manager around. ``evaluate()`` is one pure
    tick (tests drive it with a fake clock); ``start()`` runs it on a
    timer thread."""

    LOG_LIMIT = 256

    def __init__(self, aggregator, lifecycle,
                 policy: Optional[AutoscalePolicy] = None,
                 registry=None, tracer=None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.agg = aggregator
        self.lifecycle = lifecycle
        self.policy = policy or AutoscalePolicy()
        self.tracer = tracer
        self._clock = clock
        self._lock = new_lock("Autoscaler._lock")
        self._log: deque = deque(maxlen=self.LOG_LIMIT)
        self._removed: List[str] = []   # intentional scale-in exits
        self._seq = 0
        self._target: Optional[int] = None
        self._manual: Optional[int] = None
        self._manual_reason = ""
        self._last_action = -1e18
        self._ceiling_since: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        self._decisions_total = None
        if registry is not None:
            self._decisions_total = registry.counter(
                "pio_autoscale_decisions_total",
                "Control-loop decisions by action (hold|scale_out|"
                "scale_in|replace|manual)")
            registry.gauge(
                "pio_autoscale_target_replicas",
                "The replica count the autoscaler is currently "
                "holding the fleet to"
            ).set_fn(lambda: float(self._target or 0))
        # intentional-exit bookkeeping rides the lifecycle's
        # transition stream (chained — deploy may have its own hook)
        prev = lifecycle.on_transition
        def _on_transition(name: str, state: str,
                           reason: str) -> None:
            if state == "terminated":
                with self._lock:
                    self._removed.append(name)
                    del self._removed[:-self.LOG_LIMIT]
            if prev is not None:
                prev(name, state, reason)
        lifecycle.on_transition = _on_transition

    # -- control ------------------------------------------------------------
    def request_target(self, n: int, reason: str = "") -> int:
        """Manual override (``ptpu fleet scale``): clamp to policy
        bounds and converge on the next evaluation."""
        n = max(self.policy.min_replicas,
                min(self.policy.max_replicas, int(n)))
        with self._lock:
            self._manual = n
            self._manual_reason = reason or "manual scale request"
        return n

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.evaluate()
            except Exception:  # noqa: BLE001 — the loop must outlive
                pass           # any single bad tick
            self._stop.wait(self.policy.interval_sec)

    # -- one tick -----------------------------------------------------------
    def evaluate(self) -> Dict[str, Any]:
        now = self._clock()
        pol = self.policy
        signals = self.agg.capacity_signals()
        headroom = signals.get("headroom")
        burning_fast = (self.agg.slo.fast_burning()
                        if self.agg.slo is not None else [])
        live = self.lifecycle.live_count()
        ready = self.lifecycle.count("ready")

        # heal pass: replicas the aggregator has marked DOWN are
        # corpses — remove + replace outside the cooldown
        dead = [name for name in self.lifecycle.names("ready")
                if self.agg.replica_health(name) == "down"]
        for name in dead:
            self.lifecycle.mark_dead(name, "fleet health: down")
        with self._lock:
            if self._target is None:
                self._target = max(pol.min_replicas, live)
            target = self._target
            manual = self._manual
            manual_reason = self._manual_reason
            cooling = now - self._last_action < pol.cooldown_sec
            # hysteresis sustain tracking
            if headroom is not None \
                    and headroom > pol.headroom_ceiling:
                if self._ceiling_since is None:
                    self._ceiling_since = now
                sustained = (now - self._ceiling_since
                             >= pol.scale_in_sustain_sec)
            else:
                self._ceiling_since = None
                sustained = False

        action, reason = "hold", ""
        if dead:
            action = "replace"
            reason = (f"replaced {len(dead)} dead replica(s): "
                      f"{', '.join(dead)}")
            live = self.lifecycle.live_count()
        elif manual is not None and manual != live:
            action, target = "manual", manual
            reason = manual_reason
        elif manual is not None:
            with self._lock:
                self._manual = None  # converged
            target = manual
        elif burning_fast and live < pol.max_replicas \
                and not cooling:
            action = "scale_out"
            target = min(pol.max_replicas, live + 1)
            reason = ("fleet SLO fast burn lit: "
                      + ", ".join(burning_fast))
        elif headroom is not None and headroom < pol.headroom_floor \
                and live < pol.max_replicas and not cooling:
            action = "scale_out"
            target = min(pol.max_replicas, live + 1)
            reason = (f"headroom {headroom:.3f} under floor "
                      f"{pol.headroom_floor}")
        elif sustained and not burning_fast and not cooling \
                and ready > pol.min_replicas and live > pol.min_replicas:
            action = "scale_in"
            target = max(pol.min_replicas, live - 1)
            reason = (f"headroom {headroom:.3f} over ceiling "
                      f"{pol.headroom_ceiling} for "
                      f"{pol.scale_in_sustain_sec}s")

        # converge toward the target OUTSIDE the lock (lifecycle has
        # its own locks and spawns threads)
        acted = False
        if action == "replace" or live < target:
            missing = max(target - live, 0)
            for _ in range(missing):
                self.lifecycle.scale_out(reason or "below target")
                acted = True
        elif action in ("scale_in", "manual") and live > target:
            for _ in range(live - target):
                if self.lifecycle.scale_in(reason=reason) is None:
                    break
                acted = True
        elif action == "scale_out":
            # target rose but live already matches (a spawn from the
            # previous tick is in flight): no duplicate order
            acted = live < target

        decision = {
            "action": action,
            "reason": reason,
            "headroom": (round(headroom, 4)
                         if headroom is not None else None),
            "qps": round(signals.get("qps") or 0.0, 2),
            "kneeQps": signals.get("kneeQps"),
            "burningFast": burning_fast,
            "live": live,
            "ready": ready,
            "target": target,
            "wallTime": time.time(),
        }
        with self._lock:
            self._target = target
            if action != "hold":
                self._last_action = now
                self._ceiling_since = None
            self._seq += 1
            decision["seq"] = self._seq
        if self._decisions_total is not None:
            self._decisions_total.labels(action=action).inc()
        if action != "hold":
            decision["traceId"] = self._trace(decision)
            with self._lock:
                self._log.append(decision)
        return decision

    def _trace(self, decision: Dict[str, Any]) -> Optional[str]:
        """One span per non-hold decision, force-retained under the
        ``autoscale`` reason so the flight recorder keeps the why of
        every scaling event (PR 12)."""
        if self.tracer is None:
            return None
        trace = self.tracer.begin(
            f"autoscale.{decision['action']}", server="autoscaler")
        for k in ("reason", "headroom", "qps", "live", "target"):
            trace.set_attr(k, decision[k])
        self.tracer.finish(trace, status=200,
                           force_reason="autoscale")
        return trace.trace_id

    # -- read side ----------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        """The ``autoscale`` block of ``/fleet.json``: policy, live
        target, lifecycle counts, the decision log, and the
        intentional-exit list ``ptpu fleet status`` consults to tell
        scale-in from death."""
        with self._lock:
            log = list(self._log)
            removed = list(self._removed)
            target = self._target
        return {
            "enabled": True,
            "running": (self._thread is not None
                        and self._thread.is_alive()),
            "policy": asdict(self.policy),
            "target": target,
            "lifecycle": self.lifecycle.counts(),
            "replicas": self.lifecycle.replicas(),
            "removed": removed,
            "decisions": log,
        }
