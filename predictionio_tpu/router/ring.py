"""Consistent-hash ring with entity affinity (ISSUE 18).

The router's placement primitive: every replica owns ``vnodes``
pseudo-random points on a 64-bit circle; an entity key hashes to a
point and is served by the first replica clockwise from it. Adding or
removing one replica therefore remaps only the arcs that replica's
virtual nodes owned — an expected ``1/N`` of the key space — so the
per-replica serving caches (PR 4) and pinned hot tiers (PR 13), which
key on the same entity id, keep their hit rates through membership
changes. A modulo router would remap almost everything on every scale
event and cold-start the whole fleet.

Hashing is ``sha256`` over the UTF-8 key — the exact idiom of
:func:`~predictionio_tpu.rollout.splitter.cohort_bucket` — never
Python's ``hash()``, so placement is deterministic across processes,
restarts, and interpreter versions. Two routers configured with the
same membership agree on every assignment, which is what lets a
restarted router keep the fleet's cache locality.

The ring itself is unsynchronized on purpose: the
:class:`~predictionio_tpu.router.router.QueryRouter` swaps whole ring
snapshots atomically instead of mutating one under readers.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["HashRing", "key_point"]

#: virtual nodes per member: enough that one member's share has low
#: variance (stddev ~ 1/sqrt(vnodes) of the mean share) while keeping
#: membership changes cheap (vnodes sorted inserts)
DEFAULT_VNODES = 64


def key_point(key: str) -> int:
    """64-bit ring point for an entity key — sha256, the same stable
    digest the rollout splitter's ``cohort_bucket`` uses."""
    digest = hashlib.sha256(
        key.encode("utf-8", "surrogatepass")).digest()
    return int.from_bytes(digest[:8], "big")


class HashRing:
    """Sorted-point consistent-hash ring; lookups are ``O(log(N *
    vnodes))`` bisects."""

    def __init__(self, members: Iterable[str] = (),
                 vnodes: int = DEFAULT_VNODES) -> None:
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = int(vnodes)
        self._points: List[Tuple[int, str]] = []
        self._members: Dict[str, bool] = {}
        for m in members:
            self.add(m)

    # -- membership ---------------------------------------------------------
    def add(self, member: str) -> None:
        if member in self._members:
            return
        self._members[member] = True
        for i in range(self.vnodes):
            # ties between two members' vnodes (astronomically rare)
            # break on the member name, so both orders of construction
            # yield the identical ring
            bisect.insort(self._points,
                          (key_point(f"{member}#{i}"), member))

    def remove(self, member: str) -> None:
        if member not in self._members:
            return
        del self._members[member]
        self._points = [p for p in self._points if p[1] != member]

    def members(self) -> List[str]:
        return sorted(self._members)

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, member: str) -> bool:
        return member in self._members

    # -- assignment ---------------------------------------------------------
    def assign(self, key: str) -> Optional[str]:
        """The key's affinity replica: owner of the first virtual node
        clockwise from the key's point (None on an empty ring)."""
        if not self._points:
            return None
        idx = bisect.bisect_right(self._points, (key_point(key), ""))
        if idx == len(self._points):
            idx = 0
        return self._points[idx][1]

    def preference(self, key: str, n: int) -> List[str]:
        """The first ``n`` DISTINCT members clockwise from the key's
        point — position 0 is the affinity replica, the rest are the
        spill/retry order. Every router computes the same list, so a
        hot key spilled across ``n`` replicas still lands on a stable,
        cache-warm set."""
        if not self._points or n <= 0:
            return []
        out: List[str] = []
        start = bisect.bisect_right(self._points, (key_point(key), ""))
        total = len(self._points)
        for off in range(total):
            member = self._points[(start + off) % total][1]
            if member not in out:
                out.append(member)
                if len(out) >= min(n, len(self._members)):
                    break
        return out

    def describe(self) -> Dict[str, int]:
        """Virtual-node count per member (the balance diagnostic
        ``ptpu fleet route`` prints)."""
        counts: Dict[str, int] = {m: 0 for m in self._members}
        for _pt, m in self._points:
            counts[m] += 1
        return counts
