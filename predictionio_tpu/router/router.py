"""Entity-affinity query router (ISSUE 18, docs/autoscaling.md).

One HTTP proxy in front of N QueryServer replicas. Placement is the
:class:`~predictionio_tpu.router.ring.HashRing`'s consistent-hash
entity affinity — the same entity id always lands on the same replica
while membership holds, so the per-replica serving cache (PR 4) and
pinned hot tier (PR 13) see a concentrated, cacheable key stream
instead of ``1/N``-diluted round-robin traffic. Three mechanisms bend
pure affinity where it would hurt:

- **spill-on-hot-spot** — the router feeds every routed entity into a
  Space-Saving sketch (PR 17, :class:`~predictionio_tpu.obs.hotkeys.
  SpaceSaving`); a key the sketch CONFIRMS is hotter than
  ``spill_share`` of traffic is allowed to spread over the first
  ``spill_fanout`` replicas of its preference list (least-loaded
  first). One viral entity then saturates ``spill_fanout`` replicas
  instead of melting one while the rest idle — and because the
  preference list is ring-stable, the spill set stays cache-warm too.
- **health ejection** — a replica that fails ``eject_failures``
  consecutive transport attempts is ejected from candidate lists for
  ``eject_sec`` (then re-probed by live traffic); an external health
  source (the fleet aggregator's ``pio_fleet_replica_up`` view) can
  veto a replica the same way.
- **bounded retry** — ``/queries.json`` is an idempotent read, so a
  transport failure (or an upstream 503 shed) retries on the next
  replica of the preference list, at most ``retries`` times. Retries
  never cascade: the budget is per-request, not per-replica.

Draining replicas (lifecycle manager, ISSUE 18) stop receiving NEW
assignments the moment :meth:`QueryRouter.drain` removes them from the
ring, while their in-flight requests — tracked here, per backend —
are allowed to finish inside the queue deadline.
"""

from __future__ import annotations

import http.client
import json
import socket
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..concurrency import new_lock
from ..faults import FaultError, declare, fire
from ..obs import MetricsRegistry, SpaceSaving
from ..server.http import (
    HTTPApp,
    HTTPError,
    Request,
    Response,
    json_response,
    make_key_auth,
    mount_metrics,
)
from .ring import DEFAULT_VNODES, HashRing

__all__ = ["RouterConfig", "QueryRouter", "build_router_app",
           "create_router_server"]

#: fault point: fired with ``replica=`` before every forward attempt,
#: so chaos drills kill exactly one replica's traffic
#: (``router.forward=error,replica=host:port`` — the autoscale smoke's
#: mid-ramp corpse)
F_FORWARD = declare("router.forward",
                    "entry of one proxy attempt to a replica")


@dataclass
class RouterConfig:
    """Knobs of the query router."""

    #: virtual nodes per replica on the hash ring
    vnodes: int = DEFAULT_VNODES
    #: extra replicas tried after the first choice fails (transport
    #: error or 503 shed); 0 disables retry
    retries: int = 1
    #: Space-Saving sketch capacity for hot-key confirmation
    hot_keys_k: int = 128
    #: a key must carry at least this share of routed traffic —
    #: sketch-confirmed via the error-adjusted lower bound — to spill
    spill_share: float = 0.10
    #: sketch observations before any spill verdict (a 3-query burst
    #: at boot is not a hot spot)
    spill_min_total: float = 50.0
    #: replicas a confirmed-hot key may spread over
    spill_fanout: int = 2
    #: consecutive transport failures before a replica is ejected
    eject_failures: int = 3
    #: how long an ejected replica sits out before traffic re-probes it
    eject_sec: float = 5.0
    #: per-attempt upstream timeout
    timeout_sec: float = 30.0
    #: ?accessKey= guard on the router's control routes
    accesskey: Optional[str] = None

    def __post_init__(self) -> None:
        if not (0.0 < self.spill_share <= 1.0):
            raise ValueError(
                f"spill_share must be in (0,1]: {self.spill_share}")
        if self.spill_fanout < 1:
            raise ValueError("spill_fanout must be >= 1")


def _default_entity_key(query_json: Any) -> Optional[str]:
    """Entity extraction matching ``QueryServer._entity_of``: every
    bundled template keys queries by ``user``."""
    if isinstance(query_json, dict) and query_json.get("user") is not None:
        return str(query_json["user"])
    return None


class _Backend:
    """Per-replica proxy state. Mutable fields are guarded by the
    router's lock; the HTTP connection cache is per-thread."""

    def __init__(self, name: str, base: str) -> None:
        self.name = name
        self.base = base
        scheme, rest = base.split("://", 1)
        self.scheme = scheme
        hostport = rest.split("/", 1)[0]
        host, _, port = hostport.rpartition(":")
        self.host = host or hostport
        self.port = int(port) if port else (443 if scheme == "https"
                                            else 80)
        self.inflight = 0
        self.consecutive_failures = 0
        self.ejected_until = 0.0
        self.draining = False
        self.requests = 0

    def state(self, now: float) -> str:
        if self.draining:
            return "draining"
        if now < self.ejected_until:
            return "ejected"
        return "ready"


class QueryRouter:
    """The routing brain + forwarding engine; transport-agnostic reads
    (``route_key``) are separable from the HTTP proxy (``forward``) so
    tests exercise placement without sockets."""

    def __init__(self, config: Optional[RouterConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 entity_key: Callable[[Any], Optional[str]] = None,
                 health: Callable[[str], Optional[bool]] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.config = config or RouterConfig()
        self.registry = registry or MetricsRegistry()
        self._entity_key = entity_key or _default_entity_key
        #: external health veto (the aggregator's replica-up view);
        #: None means "no opinion" and the replica stays eligible
        self._health = health
        self._clock = clock
        self._lock = new_lock("QueryRouter._lock")
        self._ring = HashRing(vnodes=self.config.vnodes)
        self._backends: Dict[str, _Backend] = {}
        self._rr = 0  # fallback rotation for keyless queries
        self.hot = SpaceSaving(capacity=self.config.hot_keys_k)
        self._conns = threading.local()

        reg = self.registry
        self._req_total = reg.counter(
            "pio_router_requests_total",
            "Requests forwarded by replica and outcome "
            "(ok|shed|upstream_error|transport_error)")
        self._req_hist = reg.histogram(
            "pio_router_request_seconds",
            "End-to-end proxy time of one routed request (all "
            "attempts, upstream included)")
        self._retries_total = reg.counter(
            "pio_router_retries_total",
            "Retries AWAY from a replica after a failed attempt on it")
        self._spill_total = reg.counter(
            "pio_router_spill_total",
            "Requests a sketch-confirmed hot key placed off its "
            "affinity replica")
        self._ejections_total = reg.counter(
            "pio_router_ejections_total",
            "Replica ejections after consecutive transport failures")
        self._no_backend_total = reg.counter(
            "pio_router_no_backend_total",
            "Requests dropped (503) because no eligible replica "
            "existed")
        self._inflight_gauge = reg.gauge(
            "pio_router_inflight",
            "In-flight proxied requests per replica (the drain gate "
            "reads this)")
        replicas_fam = reg.gauge(
            "pio_router_replicas",
            "Router view of the backend set by state "
            "(ready|draining|ejected)")
        for state in ("ready", "draining", "ejected"):
            replicas_fam.labels(state=state).set_fn(
                (lambda s: lambda: self._count_state(s))(state))

    # -- membership ---------------------------------------------------------
    def add(self, replica: str) -> str:
        """Add a replica (``host:port`` or full URL) to the ring;
        returns its ring name. Idempotent; a draining replica re-added
        resumes taking assignments."""
        name, base = _normalize(replica)
        with self._lock:
            b = self._backends.get(name)
            if b is None:
                b = _Backend(name, base)
                self._backends[name] = b
                self._inflight_gauge.labels(replica=name).set_fn(
                    (lambda bk: lambda: float(bk.inflight))(b))
            b.draining = False
            b.consecutive_failures = 0
            b.ejected_until = 0.0
            if name not in self._ring:
                self._ring.add(name)
        return name

    def drain(self, name: str) -> bool:
        """Stop NEW assignments to ``name``; in-flight requests keep
        their backend (the lifecycle manager polls :meth:`inflight`
        before terminating it)."""
        with self._lock:
            b = self._backends.get(name)
            if b is None:
                return False
            b.draining = True
            self._ring.remove(name)
        return True

    def remove(self, name: str) -> bool:
        """Forget the replica entirely (post-terminate)."""
        with self._lock:
            b = self._backends.pop(name, None)
            self._ring.remove(name)
        return b is not None

    def members(self) -> List[str]:
        with self._lock:
            return self._ring.members()

    def inflight(self, name: str) -> int:
        with self._lock:
            b = self._backends.get(name)
            return b.inflight if b is not None else 0

    def set_health(self, fn: Optional[Callable[[str],
                                               Optional[bool]]]) -> None:
        """Attach/replace the external health veto after construction
        (deploy builds the router before the aggregator exists)."""
        self._health = fn

    def _count_state(self, state: str) -> float:
        now = self._clock()
        with self._lock:
            return float(sum(1 for b in self._backends.values()
                             if b.state(now) == state))

    # -- placement ----------------------------------------------------------
    def _is_hot(self, key: str) -> bool:
        hot = self.hot
        if hot.total < self.config.spill_min_total:
            return False
        for item in hot.top(self.config.hot_keys_k):
            if item["key"] == key:
                # sketch-CONFIRMED: even the pessimistic true count
                # (count - error) clears the share bar
                low = item["count"] - item["error"]
                return low >= self.config.spill_share * hot.total
        return False

    def candidates(self, key: Optional[str]) -> Tuple[List[str], bool]:
        """Ordered replica attempt list for one request, and whether
        hot-key spill widened it. Ejected/draining/veto'd replicas are
        filtered; if that empties the list, every ready replica is
        eligible again (an outage must degrade to round-robin, not to
        0 capacity)."""
        now = self._clock()
        cfg = self.config
        spilled = False
        with self._lock:
            members = self._ring.members()
            if key is not None and members:
                if self._is_hot(key):
                    pref = self._ring.preference(key, cfg.spill_fanout)
                    # least-loaded first among the spill set: the
                    # cheapest of the "power of d choices" placements
                    pref.sort(key=lambda n: self._backends[n].inflight)
                    spilled = True
                    # retry fallbacks beyond the spill set
                    for extra in self._ring.preference(
                            key, cfg.spill_fanout + cfg.retries):
                        if extra not in pref:
                            pref.append(extra)
                else:
                    pref = self._ring.preference(key, 1 + cfg.retries)
            else:
                # keyless query: rotate over the ring
                self._rr += 1
                pref = (members[self._rr % len(members):]
                        + members[:self._rr % len(members)]
                        )[:1 + cfg.retries] if members else []
            eligible = []
            for name in pref:
                b = self._backends.get(name)
                if b is None or b.draining or now < b.ejected_until:
                    continue
                eligible.append(name)
        if not eligible:
            # every preferred replica is ejected: re-admit them rather
            # than fail — live traffic is the re-probe
            with self._lock:
                eligible = [n for n in pref
                            if (b := self._backends.get(n)) is not None
                            and not b.draining]
        if self._health is not None and eligible:
            kept = [n for n in eligible if self._health(n) is not False]
            if kept:
                eligible = kept
        return eligible, spilled

    def route_key(self, key: Optional[str]) -> Optional[str]:
        """Where one entity would land right now (diagnostics +
        tests); records nothing."""
        cand, _ = self.candidates(key)
        return cand[0] if cand else None

    def preference(self, key: str, n: int) -> List[str]:
        """The raw ring preference list (no health filtering) —
        the ``ptpu fleet route --key`` diagnostic."""
        with self._lock:
            return self._ring.preference(key, n)

    # -- forwarding ---------------------------------------------------------
    def forward(self, path: str, body: bytes,
                headers: Dict[str, str]) -> Response:
        """Proxy one request: place, attempt, retry, account."""
        t0 = self._clock()
        key = None
        try:
            key = self._entity_key(json.loads(body.decode("utf-8")))
        except Exception:  # noqa: BLE001 — unparseable body still routes
            pass
        if key is not None:
            self.hot.record(key)
        candidates, spilled = self.candidates(key)
        if not candidates:
            self._no_backend_total.inc()
            raise HTTPError(503, "no live replica to route to")
        affinity = candidates[0] if not spilled else None
        last_err: Optional[str] = None
        resp: Optional[Response] = None
        for attempt, name in enumerate(
                candidates[:1 + self.config.retries]):
            with self._lock:
                b = self._backends.get(name)
                if b is None:
                    continue
                b.inflight += 1
                b.requests += 1
            try:
                status, rbody, rheaders = self._attempt(b, path, body,
                                                        headers)
                transport_err = None
            except (FaultError, OSError, http.client.HTTPException,
                    socket.timeout) as e:
                transport_err = str(e) or type(e).__name__
            finally:
                with self._lock:
                    if b is not None:
                        b.inflight -= 1
            if transport_err is not None:
                last_err = transport_err
                self._note_failure(b)
                self._req_total.labels(
                    replica=name, outcome="transport_error").inc()
                self._retries_total.labels(replica=name).inc()
                continue
            self._note_success(b)
            if status == 503 and attempt < self.config.retries:
                # an idempotent read shed by one replica can still be
                # answered by the next — bounded, like everything here
                self._req_total.labels(replica=name,
                                       outcome="shed").inc()
                self._retries_total.labels(replica=name).inc()
                last_err = "503 shed"
                continue
            outcome = ("ok" if status < 400
                       else "shed" if status == 503
                       else "upstream_error")
            self._req_total.labels(replica=name, outcome=outcome).inc()
            if spilled and key is not None:
                self._spill_total.labels(replica=name).inc()
            resp = Response(status=status, body=rbody,
                            content_type=rheaders.get(
                                "Content-Type", "application/json"))
            for h in ("X-Request-ID", "traceparent",
                      "X-Trace-Retained", "Retry-After"):
                if h in rheaders:
                    resp.headers[h] = rheaders[h]
            resp.headers["X-Routed-To"] = name
            if affinity is not None and name != affinity:
                resp.headers["X-Routed-Retry"] = str(attempt)
            break
        self._req_hist.observe(self._clock() - t0)
        if resp is None:
            raise HTTPError(
                503, f"every candidate replica failed "
                     f"({last_err or 'no attempt made'})")
        return resp

    def _attempt(self, b: _Backend, path: str, body: bytes,
                 headers: Dict[str, str]
                 ) -> Tuple[int, bytes, Dict[str, str]]:
        """One keep-alive HTTP attempt against a backend; raises on
        transport failure. The fault point fires FIRST so a chaos
        spec matched to this replica kills the attempt exactly like a
        dead socket."""
        fire(F_FORWARD, replica=b.name)
        conn = self._conn(b)
        try:
            conn.request("POST", path, body=body, headers={
                "Content-Type": headers.get("Content-Type",
                                            "application/json"),
                **{k: v for k, v in headers.items()
                   if k.lower() in ("traceparent", "x-request-id",
                                    "accept")},
            })
            r = conn.getresponse()
            data = r.read()
            return r.status, data, dict(r.getheaders())
        except Exception:
            self._drop_conn(b)
            raise

    def _conn(self, b: _Backend) -> http.client.HTTPConnection:
        cache = getattr(self._conns, "by_base", None)
        if cache is None:
            cache = {}
            self._conns.by_base = cache
        conn = cache.get(b.base)
        if conn is None:
            cls = (http.client.HTTPSConnection
                   if b.scheme == "https"
                   else http.client.HTTPConnection)
            conn = cls(b.host, b.port,
                       timeout=self.config.timeout_sec)
            cache[b.base] = conn
        return conn

    def _drop_conn(self, b: _Backend) -> None:
        cache = getattr(self._conns, "by_base", None)
        if cache is not None:
            conn = cache.pop(b.base, None)
            if conn is not None:
                try:
                    conn.close()
                except Exception:  # noqa: BLE001
                    pass

    def _note_failure(self, b: _Backend) -> None:
        ejected = False
        with self._lock:
            b.consecutive_failures += 1
            if (b.consecutive_failures >= self.config.eject_failures
                    and self._clock() >= b.ejected_until):
                b.ejected_until = self._clock() + self.config.eject_sec
                ejected = True
        if ejected:
            self._ejections_total.labels(replica=b.name).inc()

    def _note_success(self, b: _Backend) -> None:
        with self._lock:
            b.consecutive_failures = 0
            b.ejected_until = 0.0

    # -- read side ----------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        now = self._clock()
        with self._lock:
            backends = [{
                "replica": b.name,
                "url": b.base,
                "state": b.state(now),
                "inflight": b.inflight,
                "requests": b.requests,
                "consecutiveFailures": b.consecutive_failures,
                "ejectedForSec": (round(b.ejected_until - now, 3)
                                  if now < b.ejected_until else 0.0),
            } for b in self._backends.values()]
            vnodes = self._ring.describe()
        return {
            "server": "router",
            "replicas": backends,
            "ring": {"vnodes": self.config.vnodes,
                     "points": vnodes},
            "retries": self.config.retries,
            "spill": {"share": self.config.spill_share,
                      "fanout": self.config.spill_fanout,
                      "minTotal": self.config.spill_min_total},
            "hotKeys": self.hot.snapshot(),
        }


def _normalize(replica: str) -> Tuple[str, str]:
    r = replica.strip().rstrip("/")
    if "://" in r:
        return r.split("://", 1)[1], r
    return r, "http://" + r


def build_router_app(router: QueryRouter) -> HTTPApp:
    """The router's HTTP surface: the proxied query route plus its own
    telemetry (its registry is NOT scraped by the fleet aggregator —
    the replicas' merged series stay the source of serving truth; the
    ``pio_router_*`` families describe the routing tier itself)."""
    app = HTTPApp(name="router")
    mount_metrics(app, router.registry, server_name="router",
                  status=router.status, runtime=False, tracer=False)
    _auth = make_key_auth(router.config.accesskey)

    @app.route("POST", "/queries.json")
    def queries(req: Request) -> Response:
        return router.forward("/queries.json", req.body, req.headers)

    @app.route("GET", "/route.json")
    def route_json(req: Request) -> Response:
        payload = router.status()
        key = req.query.get("key")
        if key is not None:
            payload["key"] = key
            payload["affinity"] = router.route_key(key)
            payload["preference"] = router.preference(
                key, 1 + router.config.retries)
        return json_response(payload)

    @app.route("POST", "/drain")
    def drain(req: Request) -> Response:
        _auth(req)
        name = req.query.get("replica") or ""
        if not router.drain(name):
            raise HTTPError(404, f"unknown replica {name!r}")
        return json_response({"draining": name})

    return app


def create_router_server(router: QueryRouter, host: str = "0.0.0.0",
                         port: int = 8100, ssl_context=None):
    """Bind the router's server (caller starts it)."""
    from ..server.http import AppServer

    return AppServer(build_router_app(router), host=host, port=port,
                     ssl_context=ssl_context)
