"""Ring attention: sequence-parallel attention over the device mesh.

Long-context support as core infrastructure (the distributed design the
framework is built around, SURVEY §2.3 — the reference has no sequence
models at all, so this is new capability, not a port): queries, keys and
values are sharded along the SEQUENCE axis across the mesh; each device
computes blockwise attention against its resident KV block while the KV
blocks rotate around the ring via ``ppermute`` over ICI — full attention
over a sequence P× longer than one device could hold, with no all-gather
of the sequence anywhere.

Numerics: the classic streaming-softmax accumulation (running max ``m``,
normalizer ``l``, weighted accumulator) — each incoming KV block updates
the triple exactly, so the result equals dense softmax attention to
float rounding, block order notwithstanding. The (m, l, acc) triple is
f32 regardless of the q/k/v wire dtype, with
``preferred_element_type=f32`` on every contraction — the same
accumulate-in-f32 contract ``ptpu check`` enforces on Pallas scratch
(``low-precision-accumulator``, docs/static-analysis.md): bf16 belongs
on the wire, never in the running sum (a bf16 ``l`` visibly skews long
-sequence attention weights).

The op is jit/shard_map-first: no data-dependent Python control flow,
static shapes, a ``lax.fori_loop`` of P ring steps.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _ring_attention_local(q, k, v, kmask, *, axis_name: str,
                          causal: bool, scale: float):
    """Per-device body under shard_map. q/k/v: [B, S_loc, H, D] (this
    device's sequence chunk); kmask: [B, S_loc] bool key-validity (all
    True when no padding) — it rotates around the ring WITH its k/v
    block. Returns the local output chunk."""
    n_dev = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, S_loc, H, D = q.shape

    q_pos = idx * S_loc + jnp.arange(S_loc)  # global query positions

    # the accumulators join a carry with device-varying k/v —
    # shard_map's varying-axis typing requires the whole carry to agree
    # (pcast replaces the deprecated pvary; keep a fallback for older
    # jax)
    if hasattr(jax.lax, "pcast"):
        def _vary(x):
            return jax.lax.pcast(x, (axis_name,), to="varying")
    elif hasattr(jax.lax, "pvary"):
        def _vary(x):
            return jax.lax.pvary(x, (axis_name,))
    else:
        def _vary(x):
            # pre-varying-type jax (check_rep-era shard_map): there is
            # no per-axis replication typing to satisfy — identity
            return x
    m0 = _vary(jnp.full((B, H, S_loc), -jnp.inf, jnp.float32))
    l0 = _vary(jnp.zeros((B, H, S_loc), jnp.float32))
    acc0 = _vary(jnp.zeros((B, S_loc, H, D), jnp.float32))

    def step(j, carry):
        k_blk, v_blk, km_blk, m, l, acc = carry
        # rotate at the START for steps > 0: n_dev blocks need only
        # n_dev-1 rotations, and a trailing rotation would pay one
        # discarded ICI hop per block per call. The predicate is the
        # loop counter — identical on every device, so the collective
        # stays globally consistent inside lax.cond.
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

        def rotate(kv):
            return tuple(jax.lax.ppermute(x, axis_name, perm)
                         for x in kv)

        k_blk, v_blk, km_blk = jax.lax.cond(
            j > 0, rotate, lambda kv: kv, (k_blk, v_blk, km_blk))
        # after j rotations this device holds the KV block originally
        # owned by device (idx - j) mod n_dev
        kv_owner = (idx - j) % n_dev
        kv_pos = kv_owner * S_loc + jnp.arange(S_loc)

        # [B, H, Sq, Sk] block scores in f32 (inputs may be bf16)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = q_pos[:, None] >= kv_pos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        s = jnp.where(km_blk[:, None, None, :], s, -jnp.inf)

        # streaming softmax: fold this block into (m, l, acc)
        blk_max = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        # rows with nothing attendable yet keep m=-inf; exp(-inf - -inf)
        # would be NaN — substitute 0 for the shift in that case
        shift = jnp.where(jnp.isinf(m_new), 0.0, m_new)
        p = jnp.exp(s - shift[..., None])  # masked slots: exp(-inf)=0
        corr = jnp.where(jnp.isinf(m), 0.0, jnp.exp(m - shift))
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p,
                        v_blk.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        acc_new = acc * corr.transpose(0, 2, 1)[..., None] + pv
        return k_blk, v_blk, km_blk, m_new, l_new, acc_new

    _, _, _, m, l, acc = jax.lax.fori_loop(0, n_dev, step,
                                           (k, v, kmask, m0, l0, acc0))
    # fully-masked rows (can't happen for causal self-attention, where
    # position t always sees itself) would have l=0; keep them 0, not NaN
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (acc / denom).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mesh: Optional[Mesh] = None, axis: str = "data",
                   causal: bool = False,
                   scale: Optional[float] = None,
                   key_valid: Optional[jax.Array] = None) -> jax.Array:
    """Sequence-parallel multi-head attention.

    q/k/v: ``[batch, seq, heads, head_dim]`` with the sequence axis
    sharded over ``mesh`` axis ``axis`` (``seq`` must divide evenly by
    that axis size). ``key_valid`` ([batch, seq] bool) masks key
    positions — padding slots in right-aligned sequence-model windows —
    on BOTH paths (the mask rotates around the ring with its KV block).
    Returns attention output with the same sharding. With ``mesh=None``
    this is plain (single-device) blockwise attention — the same
    contract, ring of length 1.
    """
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    fn = _compiled(mesh, axis, causal, scale)
    if key_valid is None:
        key_valid = jnp.ones(q.shape[:2], bool)
    if mesh is None:
        return fn(q, k, v, key_valid)
    sharding = NamedSharding(mesh, P(None, axis, None, None))
    km_sharding = NamedSharding(mesh, P(None, axis))
    return fn(jax.device_put(q, sharding), jax.device_put(k, sharding),
              jax.device_put(v, sharding),
              jax.device_put(key_valid, km_sharding))


_fn_cache: dict = {}


def _compiled(mesh, axis: str, causal: bool, scale: float):
    """Cached jitted entry per (mesh, axis, causal, scale) — a fresh
    jax.jit per call would re-trace every invocation (~200x the cost of
    the cached dispatch; same convention as models/als.py). The Mesh
    itself keys the cache (hashable, value-compared over devices AND
    axis layout)."""
    key = (mesh, axis, causal, scale)
    fn = _fn_cache.get(key)
    if fn is None:
        if mesh is None:
            def nodist(q, k, v, key_valid):
                return _ring_attention_local_nodist(
                    q, k, v, causal=causal, scale=scale,
                    key_valid=key_valid)
            fn = jax.jit(nodist)
        else:
            from ..parallel.collectives import shard_map_compat

            spec = P(None, axis, None, None)
            km_spec = P(None, axis)
            fn = jax.jit(shard_map_compat(
                functools.partial(_ring_attention_local, axis_name=axis,
                                  causal=causal, scale=scale),
                mesh, in_specs=(spec, spec, spec, km_spec),
                out_specs=spec))
        _fn_cache[key] = fn
    return fn


def _ring_attention_local_nodist(q, k, v, *, causal: bool, scale: float,
                                 key_valid=None):
    """Single-device reference/fallback: dense softmax attention with
    the same masking and dtype conventions. ``key_valid`` ([B, Sk]
    bool) additionally masks key positions (padding slots in
    right-aligned sequence-model windows); fully-masked query rows
    return 0, never NaN."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.arange(S)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    if key_valid is not None:
        s = jnp.where(key_valid[:, None, None, :], s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.where(jnp.isinf(m), 0.0, m)  # all-masked rows
    p = jnp.exp(s - m)
    denom = p.sum(axis=-1, keepdims=True)
    p = jnp.where(denom > 0, p / jnp.maximum(denom, 1e-30), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                      preferred_element_type=jnp.float32
                      ).astype(q.dtype)


def sequence_shard(x: jax.Array, mesh: Mesh, axis: str = "data"
                   ) -> jax.Array:
    """Shard ``[batch, seq, ...]`` along the sequence dimension over a
    mesh axis (the layout :func:`ring_attention` consumes)."""
    spec = P(*([None, axis] + [None] * (x.ndim - 2)))
    return jax.device_put(x, NamedSharding(mesh, spec))
