"""Batched small linear solves for alternating least squares.

The per-row normal equations of ALS are rank×rank SPD systems — hundreds
of thousands of them per half-iteration (the role of the per-user LAPACK
calls MLlib's ALS makes inside each Spark task,
``ALSAlgorithm.scala:75-85``). XLA's batched Cholesky lowers each tiny
factorization to a serial column loop that leaves the chip almost idle
(measured: 1.15s for 138k×64×64 on a v5e — ~20 GFLOP/s). The Pallas
kernel here instead lays the batch out **along the 128 vector lanes**
(``[col, row, batch]``) so one program factors 128 matrices in lockstep:
every Cholesky column step is a full-width VPU op, and storing L by
columns makes both triangular sweeps column-access-only (the backward
substitution against L^T reads columns of L, not rows).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

#: batch lanes per Pallas program — the TPU vector lane width.
_LANES = 128


#: past this padded rank the [rp, rp, 128] block + a same-size scratch
#: exceed VMEM (measured chip OOM at rp=128: 2×8.4MB). Up to _RP_ALIAS
#: the kernel factors IN PLACE in an aliased input/output block (one
#: buffer); beyond it no 128-lane layout fits (the lane dim cannot
#: shrink below 128 — Mosaic rejects sub-lane minor blocks) and
#: ``solve_spd_batch`` routes to XLA.
_RP_SCRATCH = 88   # scratch variant: 2·rp²·128·4B ≤ ~8MB
_RP_ALIAS = 128    # in-place variant: rp²·128·4B ≤ ~8.4MB
_PANEL = 8         # column-panel width of the big-rank trailing update


def _chol_body(A, b_ref, x_ref, acc, lref=None):
    """Factor + solve 128 SPD systems in lockstep.

    A: writable [r, r, B] ref (column, row, batch-in-lanes) already
    holding the input; b_ref/x_ref: [r, B]. The factorization happens
    in place: after step k, leading index k is column k of L (zeros
    above the diagonal). Both substitution sweeps are formulated
    column-access-only (forward right-looking, backward left-looking),
    so L is never transposed.

    With ``lref`` (a [r, B] scratch), the trailing rank-1 update runs
    in COLUMN PANELS of ``_PANEL`` instead of one full-matrix
    expression: ``A[:] - l⊗l`` materializes two matrix-sized
    temporaries on the VMEM stack (2×8.4MB at r=128 — the measured
    chip OOM even after the input/scratch aliasing), while the
    panelized form's temporaries are ``_PANEL``·r·B floats.
    """
    r = A.shape[0]
    B = A.shape[2]
    rows = jax.lax.broadcasted_iota(jnp.int32, (r, B), 0)

    def at_row(v, k):
        # extract row k of a [r, B] VALUE as [1, B] — Pallas TPU has no
        # value-level dynamic_slice, so use a masked lane reduction
        return jnp.sum(v * (rows == k), axis=0, keepdims=True)

    def factor_step(k, carry):
        colk = A[k]  # [r, B]
        piv = at_row(colk, k)  # [1, B]
        inv_sqrt = jax.lax.rsqrt(jnp.maximum(piv, 1e-30))
        l = colk * inv_sqrt * (rows >= k)
        if lref is None:
            A[:] = A[:] - l[:, None, :] * l[None, :, :]
        else:
            lref[:] = l

            def panel(ci, c):
                c0 = ci * _PANEL
                lp = lref[pl.ds(c0, _PANEL)]          # [P, B]
                A[pl.ds(c0, _PANEL)] = (
                    A[pl.ds(c0, _PANEL)]
                    - lp[:, None, :] * l[None, :, :])  # [P, r, B] temps
                return c

            jax.lax.fori_loop(0, r // _PANEL, panel, 0, unroll=False)
        A[k] = l
        return carry

    jax.lax.fori_loop(0, r, factor_step, 0, unroll=False)

    # forward substitution: L y = b  (acc morphs b → y)
    acc[:] = b_ref[:]

    def fwd_step(k, carry):
        Lk = A[k]  # [r, B] — column k of L
        lkk = at_row(Lk, k)
        yk = at_row(acc[:], k) / jnp.maximum(lkk, 1e-30)
        acc[:] = jnp.where(rows == k, yk,
                           acc[:] - Lk * yk * (rows > k))
        return carry

    jax.lax.fori_loop(0, r, fwd_step, 0, unroll=False)

    # backward substitution, left-looking: x_k = (y_k - Σ_{j>k} L[j,k]·x_j)
    # / L[k,k]. The sum runs over COLUMN k of L — exactly what the column
    # storage indexes. ``acc`` rows > k already hold x, rows ≤ k still y.
    def bwd_step(i, carry):
        k = r - 1 - i
        Lk = A[k]  # [r, B] — column k of L
        lkk = at_row(Lk, k)
        s = jnp.sum(Lk * acc[:] * (rows > k), axis=0, keepdims=True)
        xk = (at_row(acc[:], k) - s) / jnp.maximum(lkk, 1e-30)
        acc[:] = jnp.where(rows == k, xk, acc[:])
        return carry

    jax.lax.fori_loop(0, r, bwd_step, 0, unroll=False)
    # write batch-major [B, r]: emitting the transpose HERE (one small
    # VMEM shuffle per block) instead of returning [r, B] and lazily
    # transposing outside makes the pallas output physically row-major.
    # The lazy transpose was implemented by XLA as a layout flip
    # ({0,1}) that propagated through reshape into the training loop's
    # factor carry — and gathering 20M rows from a {0,1}-laid factor
    # table ran at ~40 GB/s vs ~260 GB/s row-major (the round-4 trace's
    # dominant cost, fusion.534).
    x_ref[:] = acc[:].T


def _chol_solve_kernel(a_ref, b_ref, x_ref, A, acc):
    """Scratch variant (rp <= _RP_SCRATCH): copy the input block into
    VMEM scratch and factor there."""
    A[:] = a_ref[:]
    _chol_body(A, b_ref, x_ref, acc)


def _chol_solve_kernel_inplace(a_ref, b_ref, aout_ref, x_ref, acc,
                               lref):
    """Aliased variant (rp <= _RP_ALIAS): ``aout_ref`` IS ``a_ref``
    (input_output_aliases), so the factorization reuses the one block;
    the panelized update (``lref``) keeps kernel temporaries off the
    matrix scale — together these are what let rank 128 fit VMEM."""
    _chol_body(aout_ref, b_ref, x_ref, acc, lref=lref)


try:  # pallas import kept lazy-safe: CPU-only installs still work
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False


@functools.partial(jax.jit, static_argnames=("interpret",))
def _solve_spd_pallas(A: jax.Array, b: jax.Array,
                      interpret: bool = False) -> jax.Array:
    """Pallas path: A [n, r, r] SPD (jitter already applied), b [n, r].
    Requires r <= _RP_ALIAS after sublane padding (the caller routes
    larger ranks to XLA)."""
    n, r = A.shape[0], A.shape[-1]
    rp = max(((r + 7) // 8) * 8, 8)
    assert rp <= _RP_ALIAS, f"rank {r} exceeds the Pallas VMEM budget"
    lanes = _LANES
    np_ = ((n + lanes - 1) // lanes) * lanes
    # pad rank with identity (keeps matrices SPD) and batch with identity
    if rp != r or np_ != n:
        eye = jnp.eye(rp, dtype=A.dtype)
        Ap = jnp.zeros((np_, rp, rp), A.dtype) + eye
        Ap = Ap.at[:n, :r, :r].set(A)
        bp = jnp.zeros((np_, rp), b.dtype).at[:n, :r].set(b)
    else:
        Ap, bp = A, b
    # batch-in-lanes layout: [col, row, batch] (A is symmetric, so the
    # (row, col) vs (col, row) choice is immaterial on input)
    At = jnp.transpose(Ap, (2, 1, 0))
    bt = jnp.transpose(bp, (1, 0))
    mat_spec = pl.BlockSpec((rp, rp, lanes), lambda i: (0, 0, i),
                            memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((rp, lanes), lambda i: (0, i),
                            memory_space=pltpu.VMEM)
    # solutions come out batch-major [np_, rp] (see _chol_body's final
    # write) so no downstream transpose/layout-flip reaches the caller
    xvec_spec = pl.BlockSpec((lanes, rp), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    if rp <= _RP_SCRATCH:
        # scratch variant: input block + same-size scratch fit VMEM
        xrows = pl.pallas_call(
            _chol_solve_kernel,
            grid=(np_ // lanes,),
            in_specs=[mat_spec, vec_spec],
            out_specs=xvec_spec,
            out_shape=jax.ShapeDtypeStruct((np_, rp), A.dtype),
            scratch_shapes=[
                pltpu.VMEM((rp, rp, lanes), jnp.float32),
                pltpu.VMEM((rp, lanes), jnp.float32),
            ],
            interpret=interpret,
        )(At, bt)
    else:
        # in-place variant for big ranks. Two VMEM tricks, both
        # necessary at rp=128 (measured chip OOMs otherwise):
        # - the matrix block doubles as an output (input_output_aliases)
        #   and the factorization runs in place, and
        # - each 128-lane slice is a GRIDLESS pallas_call driven by
        #   ``lax.map``: with a grid, Mosaic double-buffers the in and
        #   out blocks for pipelining (4×8.4MB > the 16MB scoped limit);
        #   gridless, one buffer suffices.
        nb = np_ // lanes
        Ab = jnp.moveaxis(At.reshape(rp, rp, nb, lanes), 2, 0)
        bb = jnp.moveaxis(bt.reshape(rp, nb, lanes), 1, 0)
        whole = pl.BlockSpec(memory_space=pltpu.VMEM)

        def one(args):
            a, b2 = args
            _, x = pl.pallas_call(
                _chol_solve_kernel_inplace,
                in_specs=[whole, whole],
                out_specs=[whole, whole],
                out_shape=[
                    jax.ShapeDtypeStruct((rp, rp, lanes), A.dtype),
                    jax.ShapeDtypeStruct((lanes, rp), A.dtype),
                ],
                input_output_aliases={0: 0},
                scratch_shapes=[
                    pltpu.VMEM((rp, lanes), jnp.float32),
                    pltpu.VMEM((rp, lanes), jnp.float32),
                ],
                interpret=interpret,
            )(a, b2)
            return x

        xs = jax.lax.map(one, (Ab, bb))          # [nb, lanes, rp]
        xrows = xs.reshape(np_, rp)
    return xrows[:n, :r]


def _solver_mode() -> str:
    """"pallas" | "xla" | "auto" — "auto" defers the choice to LOWERING
    time via ``lax.platform_dependent``, so the decision tracks the
    platform the arrays actually compile for. (Consulting
    ``jax.devices()[0]`` here is wrong on hosts where a TPU tunnel
    plugin is the default backend but the computation runs on a virtual
    CPU mesh — the dryrun topology — and picked the Pallas kernel for a
    CPU lowering.)"""
    if not _HAVE_PALLAS:
        return "xla"
    mode = os.environ.get("PTPU_SPD_SOLVER", "auto")
    return mode if mode in ("pallas", "xla") else "auto"


def solve_spd_batch(A: jax.Array, b: jax.Array,
                    jitter: float = 1e-6) -> jax.Array:
    """Solve ``A[i] x = b[i]`` for a batch of SPD matrices.

    A: [n, r, r], b: [n, r] → x: [n, r]. A small diagonal jitter keeps
    Cholesky stable for rows with empty histories (A = λI only).

    On TPU this dispatches to the lane-batched Pallas Cholesky kernel;
    on CPU (tests) it uses XLA's ``cho_factor``/``cho_solve``. Override
    with ``PTPU_SPD_SOLVER={auto,pallas,xla}``.
    """
    r = A.shape[-1]
    A = A + jitter * jnp.eye(r, dtype=A.dtype)

    def _pallas(A, b):
        lead = A.shape[:-2]  # arbitrary leading batch dims, like LAPACK's
        x = _solve_spd_pallas(A.reshape(-1, r, r), b.reshape(-1, r))
        return x.reshape(*lead, r)

    def _xla(A, b):
        chol, lower = jax.scipy.linalg.cho_factor(A)
        return jax.scipy.linalg.cho_solve((chol, lower),
                                          b[..., None])[..., 0]

    # the Pallas kernel's VMEM scratch is f32; non-f32 systems take the
    # XLA path rather than hitting a dtype-mismatched kernel. Ranks past
    # the VMEM budget (_RP_ALIAS) have no 128-lane Pallas layout at all.
    mode = _solver_mode()
    rp = max(((r + 7) // 8) * 8, 8)
    if A.dtype != jnp.float32 or mode == "xla" or rp > _RP_ALIAS:
        return _xla(A, b)
    if mode == "pallas":
        return _pallas(A, b)
    # "auto": pick per LOWERING platform (Mosaic lowers on TPU only).
    # A cpu-default process can never lower the Pallas branch anywhere,
    # and this jax's platform_dependent still tries to when the call
    # sits inside a fori_loop (the fused trainer) — short-circuit. The
    # TPU-plugin-default host running a virtual CPU mesh (the dryrun
    # topology the lowering-time gate exists for) keeps the deferral.
    if jax.default_backend() == "cpu":
        return _xla(A, b)
    return jax.lax.platform_dependent(A, b, tpu=_pallas, default=_xla)


def gramian(factors: jax.Array) -> jax.Array:
    """``F^T F`` in float32 — the rank×rank Gramian shared by every row's
    normal equations (computed once per half-iteration; under a sharded
    ``factors`` XLA lowers the contraction to partial products + an
    all-reduce over the mesh)."""
    f32 = factors.astype(jnp.float32)
    return f32.T @ f32
