"""Batched small linear solves for alternating least squares.

The per-row normal equations of ALS are rank×rank SPD systems — thousands
of them per update. Batched Cholesky maps them onto the MXU as one fused
kernel (vmapped ``cho_factor``/``cho_solve``), replacing the per-user
LAPACK calls MLlib's ALS makes inside each Spark task.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def solve_spd_batch(A: jax.Array, b: jax.Array,
                    jitter: float = 1e-6) -> jax.Array:
    """Solve ``A[i] x = b[i]`` for a batch of SPD matrices.

    A: [n, r, r], b: [n, r] → x: [n, r]. A small diagonal jitter keeps
    Cholesky stable for rows with empty histories (A = λI only).
    """
    r = A.shape[-1]
    A = A + jitter * jnp.eye(r, dtype=A.dtype)
    chol, lower = jax.scipy.linalg.cho_factor(A)
    return jax.scipy.linalg.cho_solve((chol, lower), b[..., None])[..., 0]


def gramian(factors: jax.Array) -> jax.Array:
    """``F^T F`` in float32 — the rank×rank Gramian shared by every row's
    normal equations (computed once per half-iteration; under a sharded
    ``factors`` XLA lowers the contraction to partial products + an
    all-reduce over the mesh)."""
    f32 = factors.astype(jnp.float32)
    return f32.T @ f32
