"""Fused gather+Gramian Pallas kernel — the HBM-roofline attack.

BENCH_r05 showed ALS training bandwidth-bound, not compute-bound: 75%
HBM utilization at 0.6% MFU (1.6% at rank 128). The reason is the shape
of the inner loop: the XLA half-step materializes the gathered factor
tensor ``F = fixed[indices]`` as a ``[B, L, r]`` HBM temp (written once,
read back at least once) before the weighted-Gramian einsum ever runs —
≥3 HBM touches per gathered element for ~2r flops each. This is exactly
the embedding-gather access pattern Tensor Casting (arXiv 2010.13100)
co-designs TPU kernels for.

This kernel fuses the gather INTO the Gramian accumulation:

- per history chunk, the chunk's indices hop from their VMEM block into
  a small SMEM tile, whose scalar reads drive per-row DMAs that pull
  fixed-factor rows from HBM directly into double-buffered ``[chunk,r]``
  VMEM tiles — the next chunk's DMAs in flight while the MXU contracts
  the current one (bf16 on the wire when the caller passes the
  ``ALSParams.gather_dtype`` shadow);
- ``Σ_l wa·f fᵀ`` accumulates in an f32 VMEM scratch tile; the fused
  RHS ``Σ_l wb·f`` rides the same resident chunk, so the SPD solve
  consumes kernel outputs directly;
- the ``[B, L, r]`` gather temp never exists in HBM.

Per gathered entry (~2r+2r flops of Gramian+RHS work) the HBM traffic
drops from ``~3·r·4`` B (write + read-back of the temp, plus the table
read) to ``r·wire_bytes + 12`` B (the row DMA plus index and weights) —
arithmetic intensity rises ~3x on the f32 wire and ~6x on the bf16
wire, enough to lift the op off the HBM roof (the roofline probe's
``arithmetic_intensity`` field measures the achieved number).

Entry points:

- :func:`fused_gram` — the kernel itself (``interpret=True`` runs it
  on any backend for tests/debugging);
- :func:`fused_gram_dispatch` — backend-aware: compiled kernel on TPU,
  interpret-mode kernel elsewhere (explicit ``gram_mode="fused"`` on a
  CPU is a debugging run), XLA reference on TPUs whose Mosaic can't
  lower the kernel;
- :func:`fused_gram_reference` — the jnp mirror used for fallback and
  accuracy tests;
- :func:`fused_gram_supported` — one-shot lowering probe.

Wired as ``ALSParams(gram_mode="fused")`` through
``models/als.py::_lhs_fn`` (which owns the only gather) and picked by
``gram_mode="auto"`` via :mod:`.gram_autotune`. See docs/kernels.md for
the VMEM budget math and the overlapped-all-reduce mesh schedule.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover — pallas not in this jax build
    _HAVE_PALLAS = False

#: rows of A/b produced per grid step. Small on purpose: each row's
#: history chunks pipeline through the double buffer, so the block size
#: only bounds the weight blocks and the output tile.
_BLOCK_ROWS = 8

#: history slots DMA'd per double-buffer fill. Bounds the VMEM working
#: set at ``2·chunk·r·wire_bytes`` (512 KiB at r=128 f32, half that on
#: the bf16 wire) and the SMEM index tile at ``2·chunk·4`` = 4 KiB,
#: however long the padded history L grows — bucketed layouts reach
#: L=8192, which would fit neither VMEM nor SMEM un-chunked.
_L_CHUNK = 512


def fused_vmem_bytes(L: int, rank: int, wire_bytes: int = 4,
                     block_rows: int = _BLOCK_ROWS,
                     chunk: int = _L_CHUNK) -> int:
    """VMEM bytes the kernel holds live per core (docs/kernels.md):
    double-buffered factor tiles, the three weight/index blocks, the
    f32 accumulators and the output tile."""
    chunk = min(chunk, L)
    fbuf = 2 * chunk * rank * wire_bytes
    blocks = 3 * block_rows * L * 4           # idx + wa + wb blocks
    acc = rank * rank * 4 + rank * 4          # f32 accumulators
    out = block_rows * (rank * rank + rank) * 4
    return fbuf + blocks + acc + out


def _fused_gram_kernel(n_chunks: int, chunk: int,
                       idx_ref, wa_ref, wb_ref, tab_ref,
                       A_ref, b_ref, fbuf, ibuf, acc, bacc,
                       sems, isems):
    """One ``[BR, L]`` block: for each row, stream its history through
    the double-buffered ``[chunk, r]`` VMEM tile (per-slot HBM row DMAs
    for step s+1 issued before step s's contraction waits) and
    accumulate ``Σ wa·f fᵀ`` / ``Σ wb·f`` in f32 VMEM. The flat step
    sequence walks (row, chunk) pairs so the pipeline never drains
    between rows."""
    BR, Lp = idx_ref.shape

    def fetch(s, slot):
        row = s // n_chunks
        base = (s % n_chunks) * chunk
        # the chunk's indices hop VMEM→SMEM first: row DMAs need
        # scalar source addresses, and a [BR, L] SMEM *block* would
        # blow the scalar-memory budget at bucketed L
        icopy = pltpu.make_async_copy(
            idx_ref.at[pl.ds(row, 1), pl.ds(base, chunk)],
            ibuf.at[pl.ds(slot, 1), :],
            isems.at[slot])
        icopy.start()
        icopy.wait()

        def issue(l, c):
            pltpu.make_async_copy(
                tab_ref.at[pl.ds(ibuf[slot, l], 1), :],
                fbuf.at[slot, pl.ds(l, 1), :],
                sems.at[slot]).start()
            return c

        jax.lax.fori_loop(0, chunk, issue, 0, unroll=False)

    def drain(slot):
        # the wait descriptor only carries the copy SIZE (one [1, r]
        # row); a fixed source slice stands in for all of them
        def wait(l, c):
            pltpu.make_async_copy(
                tab_ref.at[pl.ds(0, 1), :],
                fbuf.at[slot, pl.ds(l, 1), :],
                sems.at[slot]).wait()
            return c

        jax.lax.fori_loop(0, chunk, wait, 0, unroll=False)

    n_steps = BR * n_chunks
    fetch(0, 0)

    def step(s, carry):
        slot = jax.lax.rem(s, 2)

        @pl.when(s + 1 < n_steps)
        def _():
            fetch(s + 1, jax.lax.rem(s + 1, 2))

        drain(slot)
        row = s // n_chunks
        ch = s % n_chunks
        # upcast AFTER the wire: bf16 rows contract with f32
        # accumulation (preferred_element_type), the TPU-native
        # mixed-precision idiom — the HBM bytes were the bf16 rows
        F = fbuf[slot].astype(jnp.float32)               # [chunk, r]
        wa = wa_ref[pl.ds(row, 1), pl.ds(ch * chunk, chunk)]
        wb = wb_ref[pl.ds(row, 1), pl.ds(ch * chunk, chunk)]
        G = jax.lax.dot_general(
            F * wa.reshape(chunk, 1), F, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [r, r]
        bb = jax.lax.dot_general(
            wb, F, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [1, r]

        @pl.when(ch == 0)
        def _():
            acc[:] = G
            bacc[:] = bb

        @pl.when(ch > 0)
        def _():
            acc[:] = acc[:] + G
            bacc[:] = bacc[:] + bb

        @pl.when(ch == n_chunks - 1)
        def _():
            A_ref[pl.ds(row, 1)] = acc[:][None]
            b_ref[pl.ds(row, 1)] = bacc[:]

        return carry

    jax.lax.fori_loop(0, n_steps, step, 0, unroll=False)


def _pad_axis(x: jax.Array, axis: int, to: int) -> jax.Array:
    n = x.shape[axis]
    if n == to:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, to - n)
    return jnp.pad(x, pad)


@functools.partial(jax.jit, static_argnames=("block_rows", "chunk",
                                             "interpret"))
def fused_gram(table: jax.Array, idx: jax.Array, wa: jax.Array,
               wb: jax.Array, *, block_rows: int = _BLOCK_ROWS,
               chunk: Optional[int] = None,
               interpret: bool = False
               ) -> Tuple[jax.Array, jax.Array]:
    """Fused gather + weighted Gramian from an HBM-resident ``table``
    [m, r] (f32, or the bf16 shadow for a bf16 wire): returns
    ``(A [B, r, r] f32, b [B, r] f32)`` with ``A[i] = Σ_l wa[i,l]·f fᵀ``
    and ``b[i] = Σ_l wb[i,l]·f`` over ``f = table[idx[i, l]]``.

    Padding slots must carry w=0 (idx may point at any valid row);
    B and L are padded to block multiples internally and sliced back —
    ragged tails are the caller's normal case, not an error."""
    assert _HAVE_PALLAS, "pallas unavailable in this jax build"
    B, L = idx.shape
    m, r = table.shape
    Lc = min(chunk or _L_CHUNK, L)
    Lp = -(-L // Lc) * Lc
    Bp = max(-(-B // block_rows) * block_rows, block_rows)
    idx = _pad_axis(_pad_axis(idx.astype(jnp.int32), 1, Lp), 0, Bp)
    wa = _pad_axis(_pad_axis(wa.astype(jnp.float32), 1, Lp), 0, Bp)
    wb = _pad_axis(_pad_axis(wb.astype(jnp.float32), 1, Lp), 0, Bp)
    # `ptpu check` (vmem-overbudget) proves this bound statically over
    # the autotune rank grid; assert it at trace time too, so an
    # exotic (L, rank, chunk) combination from a caller-supplied
    # override fails loudly on the host instead of OOMing VMEM
    # mid-train (shapes are static under jit — this costs nothing)
    assert fused_vmem_bytes(Lp, r, table.dtype.itemsize, block_rows,
                            Lc) < 16 * 1024 * 1024, \
        f"fused_gram VMEM working set exceeds the ~16 MiB/core " \
        f"budget at rank {r}, chunk {Lc}, L {Lp} (docs/kernels.md)"
    n_chunks = Lp // Lc
    kernel = functools.partial(_fused_gram_kernel, n_chunks, Lc)
    A, b = pl.pallas_call(
        kernel,
        grid=(Bp // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, Lp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, Lp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, Lp), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            # the factor table STAYS in HBM — rows are DMA'd on demand;
            # this is the whole point (a VMEM-resident BlockSpec would
            # cap m·r at the ~16MB core budget)
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, r, r), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_rows, r), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Bp, r, r), jnp.float32),
            jax.ShapeDtypeStruct((Bp, r), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, Lc, r), table.dtype),   # row double buffer
            pltpu.SMEM((2, Lc), jnp.int32),        # staged index chunk
            pltpu.VMEM((r, r), jnp.float32),       # Gramian accumulator
            pltpu.VMEM((1, r), jnp.float32),       # RHS accumulator
            pltpu.SemaphoreType.DMA((2,)),         # row DMAs
            pltpu.SemaphoreType.DMA((2,)),         # index staging
        ],
        interpret=interpret,
    )(idx, wa, wb, table)
    return A[:B], b[:B]


def fused_gram_reference(table: jax.Array, idx: jax.Array,
                         wa: jax.Array, wb: jax.Array
                         ) -> Tuple[jax.Array, jax.Array]:
    """jnp mirror of the kernel (gather, upcast, f32 contraction) —
    the fallback on TPUs whose Mosaic can't lower the kernel, and the
    oracle for the accuracy tests. Materializes the gather temp: this
    is the baseline the kernel exists to beat."""
    F = table[idx].astype(jnp.float32)  # [B, L, r]
    A = jnp.einsum("blr,bls,bl->brs", F, F, wa.astype(jnp.float32))
    b = jnp.einsum("blr,bl->br", F, wb.astype(jnp.float32))
    return A, b


def _tpu_attached() -> bool:
    try:
        dev = jax.devices()[0]
        return dev.platform == "tpu" or dev.device_kind.startswith("TPU")
    except Exception:  # noqa: BLE001 — no backend at all
        return False


_support: dict = {}


def fused_gram_supported() -> bool:
    """Probe ONCE whether the fused kernel lowers+compiles on the
    attached backend. True only on a TPU whose Mosaic build accepts the
    kernel (per-row dynamic-index DMA support is version-dependent);
    ``gram_mode="auto"`` consumers use this to fall back to einsum
    instead of raising mid-train."""
    if not _HAVE_PALLAS or not _tpu_attached():
        return False
    cached = _support.get("tpu")
    if cached is not None:
        return cached
    try:
        tab = jnp.zeros((256, 64), jnp.float32)
        idx = jnp.zeros((_BLOCK_ROWS, 128), jnp.int32)
        w = jnp.zeros((_BLOCK_ROWS, 128), jnp.float32)
        jax.jit(fused_gram).lower(tab, idx, w, w).compile()
        ok = True
    except Exception:  # noqa: BLE001 — lowering not supported
        ok = False
    _support["tpu"] = ok
    return ok


def reset_support_cache_for_tests() -> None:
    _support.clear()


def fused_gram_dispatch(table: jax.Array, idx: jax.Array, wa: jax.Array,
                        wb: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Backend-aware fused entry (the ``gram_mode="fused"`` realization
    ``models/als.py::_lhs_fn`` calls):

    - TPU with Mosaic support → the compiled kernel; a CPU lowering of
      the same trace (virtual-mesh dryruns) runs it interpreted, so the
      numbers match the device run;
    - TPU without support → the XLA reference (graceful, not fatal);
    - no TPU → interpret-mode kernel: an explicit ``gram_mode="fused"``
      on CPU is a debugging run and should exercise the REAL kernel
      (this is what tier-1 covers without a TPU).
    """
    if not _HAVE_PALLAS:
        return fused_gram_reference(table, idx, wa, wb)
    if _tpu_attached():
        if not fused_gram_supported():
            return fused_gram_reference(table, idx, wa, wb)
        return jax.lax.platform_dependent(
            table, idx, wa, wb,
            tpu=lambda t, i, a, b: fused_gram(t, i, a, b),
            default=lambda t, i, a, b: fused_gram(t, i, a, b,
                                                  interpret=True))
    return fused_gram(table, idx, wa, wb, interpret=True)
