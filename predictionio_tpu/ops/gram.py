"""Weighted-gram variants for the ALS normal equations.

The per-row system build Σ_l w·f fᵀ is where the FLOPs are
(``ALSAlgorithm.scala:75-85`` role). At rank 64 the straightforward
batched einsum ``[B,L,64]→[B,64,64]`` runs M=N=64 matmuls on a 128×128
MXU — a quarter of the array (measured ~3-5 TF/s f32 on a v5e whose
bf16 peak is 197, BASELINE.md).

``gram_pairs`` packs TWO rank-64 systems per MXU tile: rows are paired
along the feature axis, one ``[B/2, L, 128]²`` einsum produces
``[B/2, 128, 128]`` tiles whose two diagonal 64×64 blocks are the two
rows' grams. The multiply count doubles (the off-diagonal blocks are
discarded) but every multiply now runs on a FULL MXU tile — a net win
exactly when the op is MXU-bound, which ``benchmarks/gram_profile.py``
measures per shape. Opt-in via ``ALSParams(gram_mode="pair")``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def gram_weighted(F: jax.Array, w: jax.Array,
                  bf16: bool = False) -> jax.Array:
    """Baseline batched weighted gram: ``A[..., i, :, :] = Σ_l w·f fᵀ``.
    F: [..., L, r], w: [..., L] → [..., r, r]."""
    if bf16:
        Fw = (F * w[..., None]).astype(jnp.bfloat16)
        Fc = F.astype(jnp.bfloat16)
        return jnp.einsum("...lr,...ls->...rs", Fw, Fc,
                          preferred_element_type=jnp.float32)
    # F may still be a bf16 gather shadow even when the bf16 *compute*
    # mode is off — pin the accumulator wide either way
    return jnp.einsum("...lr,...ls,...l->...rs", F, F, w,
                      preferred_element_type=jnp.float32)


def gram_pairs(F: jax.Array, w: jax.Array,
               bf16: bool = False) -> jax.Array:
    """Pair-packed weighted gram (see module docstring): same result as
    :func:`gram_weighted` with rows packed two-per-MXU-tile. Requires an
    EVEN number of rows on the second-to-last batch axis (callers fall
    back to :func:`gram_weighted` otherwise)."""
    *lead, n, L, r = F.shape
    assert n % 2 == 0, "gram_pairs needs an even row count"
    F0, F1 = F[..., 0::2, :, :], F[..., 1::2, :, :]
    Fp = jnp.concatenate([F0, F1], axis=-1)  # [..., n/2, L, 2r]
    Wp = jnp.concatenate([F0 * w[..., 0::2, :, None],
                          F1 * w[..., 1::2, :, None]], axis=-1)
    if bf16:
        Fp = Fp.astype(jnp.bfloat16)
        Wp = Wp.astype(jnp.bfloat16)
    G2 = jnp.einsum("...lr,...ls->...rs", Wp, Fp,
                    preferred_element_type=jnp.float32)
    # [..., n/2, 2r, 2r] → the two diagonal blocks, interleaved back
    A0 = G2[..., :r, :r]
    A1 = G2[..., r:, r:]
    return jnp.stack([A0, A1], axis=-3).reshape(*lead, n, r, r)


def _pair_padded(F: jax.Array, w: jax.Array, bf16: bool) -> jax.Array:
    """:func:`gram_pairs` for ANY row count: an odd batch is padded
    with one zero row (its gram is exactly zero) and sliced back. This
    is the ONE place odd-row handling lives — callers never assert
    evenness themselves (callers used to silently fall back to the
    einsum path on odd B, so the measured pair win evaporated on any
    odd tail block)."""
    n = F.shape[-3]
    if n % 2 == 0:
        return gram_pairs(F, w, bf16=bf16)
    padF = [(0, 0)] * F.ndim
    padF[-3] = (0, 1)
    padw = [(0, 0)] * w.ndim
    padw[-2] = (0, 1)
    out = gram_pairs(jnp.pad(F, padF), jnp.pad(w, padw), bf16=bf16)
    return out[..., :n, :, :]


def gram_dispatch(F: jax.Array, w: jax.Array, mode: str,
                  bf16: bool = False) -> jax.Array:
    """``mode``: "einsum" (baseline), "pair", "fused", or "auto".

    "auto" resolves through the persistent shape-keyed table
    (:mod:`.gram_autotune`): measured winners recorded by the bench's
    gram race / ``gram_profile.py --record``, then packaged defaults,
    then an MXU-tile-occupancy heuristic. The resolution happens at
    trace time (mode and shapes are static), so the choice costs
    nothing at run time.

    "fused" here means the caller materialized the gather before
    dispatching — with ``F`` already in hand there is nothing left to
    fuse, so it degrades to the baseline einsum. The fused entry point
    is ``models/als.py::_lhs_fn`` (table + indices, via
    :mod:`.fused_gram`), which intercepts the mode BEFORE the gather
    exists; landing here is the documented fallback for layouts the
    kernel doesn't cover (L-axis-sharded skinny buckets).

    Odd row counts are handled HERE (pad-and-slice, :func:`_pair_padded`)
    — "pair" applies to any B."""
    if mode == "auto":
        from .gram_autotune import best_mode

        mode = best_mode(F.shape[-1], bf16=bf16)
        if mode == "pair":
            # the autotuned winner describes the ACCELERATOR; on a CPU
            # lowering of the same trace (virtual-mesh dryruns on hosts
            # where the TPU plugin is the default backend) pair's 2x
            # multiplies are a pure loss — pick per lowering platform,
            # mirroring solve.py's platform gate
            return jax.lax.platform_dependent(
                F, w,
                tpu=lambda F, w: _pair_padded(F, w, bf16=bf16),
                default=lambda F, w: gram_weighted(F, w, bf16=bf16))
        return gram_weighted(F, w, bf16=bf16)
    if mode == "pair":
        return _pair_padded(F, w, bf16=bf16)
    return gram_weighted(F, w, bf16=bf16)


# -- VMEM-table fused gather+gram (Pallas) ----------------------------------
#
# The XLA half-step materializes F = table[idx] ([B, L, r] f32) in HBM
# and reads it back for the gram — ≥3 HBM touches per gathered element.
# When the FIXED factor table fits VMEM (27k items × rank 64 × 4B =
# 6.9MB on a ~16MB/core budget), this kernel streams only idx+weights
# (8B/entry) from HBM, gathers from the resident table, and runs the
# pair-packed MXU contraction entirely on-chip. Arithmetic intensity per
# entry goes from ~11 to ~1000 flops/byte — the HBM bound disappears.
#
# Mosaic's dynamic (vector-index) gather support is version-dependent;
# ``gram_table_supported()`` probes lowering once so callers can fall
# back to the XLA paths.

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False

#: rows of A/b produced per kernel invocation step (must be even: the
#: MXU contraction packs two rows per 128-wide tile)
_BLOCK_ROWS = 16


def _gram_table_kernel(tab_ref, idx_ref, wa_ref, wb_ref, A_ref, b_ref):
    """One [Bt, L] block: per row pair, gather the pair's history rows
    from the VMEM-resident table, weight, and contract as ONE
    [L, 2r]ᵀ[L, 2r] MXU matmul whose diagonal r×r blocks are the two
    rows' grams (plus a [2, L]×[L, 2r] matmul for the b vectors)."""
    Bt, L = idx_ref.shape
    r = tab_ref.shape[1]
    tab = tab_ref[:]

    def step(p, carry):
        i0 = 2 * p
        idx2 = idx_ref[pl.ds(i0, 2), :]                        # [2, L]
        wa2 = wa_ref[pl.ds(i0, 2), :]
        wb2 = wb_ref[pl.ds(i0, 2), :]
        F2 = tab[idx2.reshape(2 * L)]                          # [2L, r]
        F0, F1 = F2[:L], F2[L:]
        Fp = jnp.concatenate([F0, F1], axis=1)                 # [L, 2r]
        Wp = jnp.concatenate([F0 * wa2[0][:, None],
                              F1 * wa2[1][:, None]], axis=1)
        G2 = jax.lax.dot_general(
            Wp, Fp, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [2r, 2r]
        B2 = jax.lax.dot_general(
            wb2, Fp, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # [2, 2r]
        A_ref[pl.ds(i0, 1), :, :] = G2[None, :r, :r]
        A_ref[pl.ds(i0 + 1, 1), :, :] = G2[None, r:, r:]
        b_ref[pl.ds(i0, 1), :] = B2[None, 0, :r]
        b_ref[pl.ds(i0 + 1, 1), :] = B2[None, 1, r:]
        return carry

    jax.lax.fori_loop(0, Bt // 2, step, 0, unroll=False)


def gram_table_pallas(table: jax.Array, idx: jax.Array, wa: jax.Array,
                      wb: jax.Array, interpret: bool = False):
    """Fused gather+gram from a VMEM-resident ``table`` [m, r]:
    returns (A [B, r, r], b [B, r]) with
    ``A[i] = Σ_l wa[i,l]·f fᵀ`` and ``b[i] = Σ_l wb[i,l]·f`` over
    ``f = table[idx[i,l]]``. Pad slots carry w=0 (idx may point
    anywhere valid). B is padded to the block size internally."""
    assert _HAVE_PALLAS, "pallas unavailable"
    B, L = idx.shape
    m, r = table.shape
    Bp = -(-B // _BLOCK_ROWS) * _BLOCK_ROWS
    if Bp != B:
        pad = ((0, Bp - B), (0, 0))
        idx = jnp.pad(idx, pad)
        wa = jnp.pad(wa, pad)
        wb = jnp.pad(wb, pad)
    A, b = pl.pallas_call(
        _gram_table_kernel,
        grid=(Bp // _BLOCK_ROWS,),
        in_specs=[
            pl.BlockSpec((m, r), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK_ROWS, L), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK_ROWS, L), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK_ROWS, L), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((_BLOCK_ROWS, r, r), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BLOCK_ROWS, r), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Bp, r, r), jnp.float32),
            jax.ShapeDtypeStruct((Bp, r), jnp.float32),
        ],
        interpret=interpret,
    )(table, idx, wa, wb)
    return A[:B], b[:B]


_table_support: dict = {}


def gram_table_supported() -> bool:
    """Probe once whether the fused table kernel LOWERS on the attached
    backend (Mosaic's vector-gather support is version-dependent)."""
    if not _HAVE_PALLAS:
        return False
    try:
        dev = jax.devices()[0]
        if not (dev.platform == "tpu"
                or dev.device_kind.startswith("TPU")):
            return False
    except Exception:  # pragma: no cover
        return False
    cached = _table_support.get("tpu")
    if cached is not None:
        return cached
    try:
        tab = jnp.zeros((128, 64), jnp.float32)
        idx = jnp.zeros((_BLOCK_ROWS, 128), jnp.int32)
        w = jnp.zeros((_BLOCK_ROWS, 128), jnp.float32)
        jax.jit(gram_table_pallas).lower(tab, idx, w, w).compile()
        ok = True
    except Exception:  # noqa: BLE001 — lowering not supported
        ok = False
    _table_support["tpu"] = ok
    return ok
