"""Weighted-gram variants for the ALS normal equations.

The per-row system build Σ_l w·f fᵀ is where the FLOPs are
(``ALSAlgorithm.scala:75-85`` role). At rank 64 the straightforward
batched einsum ``[B,L,64]→[B,64,64]`` runs M=N=64 matmuls on a 128×128
MXU — a quarter of the array (measured ~3-5 TF/s f32 on a v5e whose
bf16 peak is 197, BASELINE.md).

``gram_pairs`` packs TWO rank-64 systems per MXU tile: rows are paired
along the feature axis, one ``[B/2, L, 128]²`` einsum produces
``[B/2, 128, 128]`` tiles whose two diagonal 64×64 blocks are the two
rows' grams. The multiply count doubles (the off-diagonal blocks are
discarded) but every multiply now runs on a FULL MXU tile — a net win
exactly when the op is MXU-bound, which ``benchmarks/gram_profile.py``
measures per shape. Opt-in via ``ALSParams(gram_mode="pair")``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def gram_weighted(F: jax.Array, w: jax.Array,
                  bf16: bool = False) -> jax.Array:
    """Baseline batched weighted gram: ``A[..., i, :, :] = Σ_l w·f fᵀ``.
    F: [..., L, r], w: [..., L] → [..., r, r]."""
    if bf16:
        Fw = (F * w[..., None]).astype(jnp.bfloat16)
        Fc = F.astype(jnp.bfloat16)
        return jnp.einsum("...lr,...ls->...rs", Fw, Fc,
                          preferred_element_type=jnp.float32)
    return jnp.einsum("...lr,...ls,...l->...rs", F, F, w)


def gram_pairs(F: jax.Array, w: jax.Array,
               bf16: bool = False) -> jax.Array:
    """Pair-packed weighted gram (see module docstring): same result as
    :func:`gram_weighted` with rows packed two-per-MXU-tile. Requires an
    EVEN number of rows on the second-to-last batch axis (callers fall
    back to :func:`gram_weighted` otherwise)."""
    *lead, n, L, r = F.shape
    assert n % 2 == 0, "gram_pairs needs an even row count"
    F0, F1 = F[..., 0::2, :, :], F[..., 1::2, :, :]
    Fp = jnp.concatenate([F0, F1], axis=-1)  # [..., n/2, L, 2r]
    Wp = jnp.concatenate([F0 * w[..., 0::2, :, None],
                          F1 * w[..., 1::2, :, None]], axis=-1)
    if bf16:
        Fp = Fp.astype(jnp.bfloat16)
        Wp = Wp.astype(jnp.bfloat16)
    G2 = jnp.einsum("...lr,...ls->...rs", Wp, Fp,
                    preferred_element_type=jnp.float32)
    # [..., n/2, 2r, 2r] → the two diagonal blocks, interleaved back
    A0 = G2[..., :r, :r]
    A1 = G2[..., r:, r:]
    return jnp.stack([A0, A1], axis=-3).reshape(*lead, n, r, r)


def gram_dispatch(F: jax.Array, w: jax.Array, mode: str,
                  bf16: bool = False) -> jax.Array:
    """``mode``: "einsum" (baseline), "pair", or "auto" (currently the
    baseline; flips per-shape once gram_profile.py numbers land)."""
    if mode == "pair" and F.shape[-3] % 2 == 0:
        return gram_pairs(F, w, bf16=bf16)
    return gram_weighted(F, w, bf16=bf16)
