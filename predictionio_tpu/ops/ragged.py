"""Ragged→dense packing for TPU-friendly layouts.

Event logs are ragged and string-keyed (SURVEY §7 hard part 2): each user
has a variable-length rating history. XLA wants static shapes, so the host
packs COO ratings into padded per-row histories once, before the training
loop — ``[n_rows, max_len]`` index + weight matrices where padding carries
weight 0 and a sentinel index that still gathers safely. The device never
sees ragged data; the train loop is pure static-shape array code.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

#: With no explicit cap, the dense [n_rows, max_len] matrices are bounded
#: to this many entries; beyond it the longest histories are truncated to
#: the smallest length covering 99.9% of rows (skew guard: one heavy item
#: must not inflate every row — MovieLens-20M's top item has ~100k raters).
AUTO_CAP_ENTRIES = 200_000_000


@dataclass(frozen=True)
class PaddedHistories:
    """Per-row padded histories: ``indices[i, k]`` is the k-th counterpart
    id for row i (0-padded), ``values[i, k]`` its rating (0-padded), and
    ``counts[i]`` the true history length."""

    indices: np.ndarray  # [n_rows, max_len] int32
    values: np.ndarray   # [n_rows, max_len] float32
    counts: np.ndarray   # [n_rows] int32

    @property
    def n_rows(self) -> int:
        return self.indices.shape[0]

    @property
    def max_len(self) -> int:
        return self.indices.shape[1]


def pack_histories(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                   n_rows: int, max_len: Optional[int] = None,
                   pad_rows_to: int = 1) -> PaddedHistories:
    """Pack COO triples into row-major padded histories.

    ``max_len`` caps history length (longest-kept-first is NOT applied;
    entries beyond the cap are dropped in input order — callers wanting
    recency should pre-sort). ``pad_rows_to`` rounds the row count up so
    the leading axis divides evenly across mesh shards.
    """
    order = np.argsort(rows, kind="stable")
    rows_s, cols_s, vals_s = rows[order], cols[order], vals[order]
    counts = np.bincount(rows_s, minlength=n_rows).astype(np.int32)
    L = resolve_max_len(counts, n_rows, max_len)

    n_pad = ((n_rows + pad_rows_to - 1) // pad_rows_to) * pad_rows_to
    indices = np.zeros((n_pad, L), dtype=np.int32)
    values = np.zeros((n_pad, L), dtype=np.float32)

    # position of each entry within its row
    starts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    pos_in_row = np.arange(len(rows_s)) - starts[rows_s]
    keep = pos_in_row < L
    indices[rows_s[keep], pos_in_row[keep]] = cols_s[keep]
    values[rows_s[keep], pos_in_row[keep]] = vals_s[keep]
    kept_counts = np.minimum(counts, L)
    out_counts = np.zeros(n_pad, dtype=np.int32)
    out_counts[:n_rows] = kept_counts
    return PaddedHistories(indices=indices, values=values, counts=out_counts)


def transpose_coo(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Swap the roles of rows and cols (users↔items)."""
    return cols, rows, vals


def resolve_max_len(counts: np.ndarray, n_rows: int,
                    max_len: Optional[int]) -> int:
    """Padded history length: the explicit cap, or the longest row with
    the 99.9th-percentile auto-cap (warning when entries get dropped)."""
    if max_len is not None:
        return max(int(max_len), 1)
    L = int(counts.max(initial=1))
    if n_rows * L > AUTO_CAP_ENTRIES:
        capped = int(np.quantile(counts, 0.999)) or 1
        capped = max(capped, AUTO_CAP_ENTRIES // max(n_rows, 1))
        if capped < L:
            dropped = int(np.maximum(counts - capped, 0).sum())
            log.warning(
                "pack_histories: capping history length %d → %d "
                "(99.9th pct; dense layout would be %d×%d); dropping "
                "%d/%d entries from the heaviest rows. Set max_len to "
                "override.", L, capped, n_rows, L, dropped,
                int(counts.sum()))
            L = capped
    return max(L, 1)


def pack_histories_device(rows: np.ndarray, cols: np.ndarray,
                          vals: np.ndarray, n_rows: int, max_len: int,
                          pad_rows_to: int = 1) -> PaddedHistories:
    """Device-side :func:`pack_histories`: one jitted sort + scatter.

    Packing 20M MovieLens-shaped entries takes ~10s of host numpy
    (argsort + fancy-index scatters) but milliseconds as a compiled XLA
    program, so the COO triples ship to the device raw and the padded
    layout is built there. Semantics match the host packer: stable
    within-row input order, entries beyond ``max_len`` dropped, rows
    padded to a ``pad_rows_to`` multiple.

    Returns the padded arrays as ``jax.Array``s still resident on device
    (duck-typed into ``PaddedHistories``) so the training loop can shard
    them without a host round-trip.
    """
    import jax.numpy as jnp

    L = max(int(max_len), 1)
    n_pad = ((n_rows + pad_rows_to - 1) // pad_rows_to) * pad_rows_to
    idx, val, cnt = _pack_on_device(
        jnp.asarray(rows, dtype=jnp.int32),
        jnp.asarray(cols, dtype=jnp.int32),
        jnp.asarray(vals, dtype=jnp.float32),
        n_rows=n_rows, L=L, n_pad=n_pad)
    return PaddedHistories(indices=idx, values=val, counts=cnt)


def _pack_on_device(r, c, v, *, n_rows: int, L: int, n_pad: int):
    import jax

    global _pack_jit
    if _pack_jit is None:
        import jax.numpy as jnp

        def pack(r, c, v, n_rows, L, n_pad):
            nnz = r.shape[0]
            order = jnp.argsort(r, stable=True)
            rs, cs, vs = r[order], c[order], v[order]
            counts = jnp.bincount(rs, length=n_rows).astype(jnp.int32)
            starts = jnp.concatenate(
                [jnp.zeros(1, jnp.int32),
                 jnp.cumsum(counts, dtype=jnp.int32)])
            pos = jnp.arange(nnz, dtype=jnp.int32) - starts[rs]
            flat = rs * jnp.int32(L) + pos
            oob = jnp.int32(n_pad * L)  # mode="drop" sentinel for pos >= L
            flat = jnp.where(pos < L, flat, oob)
            idx = jnp.zeros(n_pad * L, jnp.int32).at[flat].set(
                cs, mode="drop")
            val = jnp.zeros(n_pad * L, jnp.float32).at[flat].set(
                vs, mode="drop")
            cnt = jnp.zeros(n_pad, jnp.int32).at[:n_rows].set(
                jnp.minimum(counts, L))
            return idx.reshape(n_pad, L), val.reshape(n_pad, L), cnt

        _pack_jit = jax.jit(pack,
                            static_argnames=("n_rows", "L", "n_pad"))
    return _pack_jit(r, c, v, n_rows=n_rows, L=L, n_pad=n_pad)


_pack_jit = None
