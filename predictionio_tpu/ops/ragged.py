"""Ragged→dense packing for TPU-friendly layouts.

Event logs are ragged and string-keyed (SURVEY §7 hard part 2): each user
has a variable-length rating history. XLA wants static shapes, so the host
packs COO ratings into padded per-row histories once, before the training
loop — ``[n_rows, max_len]`` index + weight matrices where padding carries
weight 0 and a sentinel index that still gathers safely. The device never
sees ragged data; the train loop is pure static-shape array code.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

#: With no explicit cap, the dense [n_rows, max_len] matrices are bounded
#: to this many entries; beyond it the longest histories are truncated to
#: the smallest length covering 99.9% of rows (skew guard: one heavy item
#: must not inflate every row — MovieLens-20M's top item has ~100k raters).
AUTO_CAP_ENTRIES = 200_000_000


def _host(*arrays) -> tuple:
    """Explicit host landing for pack results.

    The only device-resident form of a pack should be the BLOCKED
    (mesh-shaped) copies training actually reads
    (``PackedRatings.blocked``); keeping the raw pack on device too made
    every pack live twice in HBM — measured as the eval sweep's
    RESOURCE_EXHAUSTED with fold packs held by the fast-eval cache. All
    intentional D2H transfers of this module funnel through here, so
    the hot-path lint has exactly one blessed sync site.
    """
    # ptpu: allow[host-sync-in-hot-path] — the pack's one intended D2H
    return tuple(np.asarray(a) for a in arrays)


def _c_contig(arr: np.ndarray, dtype) -> np.ndarray:
    """Contiguous host buffer for the native codec (host→host: inputs
    are already numpy when the native lane is reachable)."""
    # ptpu: allow[host-sync-in-hot-path] — C++ codec needs C buffers
    return np.ascontiguousarray(arr, dtype=dtype)


@dataclass(frozen=True)
class PaddedHistories:
    """Per-row padded histories: ``indices[i, k]`` is the k-th counterpart
    id for row i (0-padded), ``values[i, k]`` its rating (0-padded), and
    ``counts[i]`` the true history length."""

    indices: np.ndarray  # [n_rows, max_len] int32
    values: np.ndarray   # [n_rows, max_len] float32
    counts: np.ndarray   # [n_rows] int32

    @property
    def n_rows(self) -> int:
        return self.indices.shape[0]

    @property
    def max_len(self) -> int:
        return self.indices.shape[1]


def pack_histories(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                   n_rows: int, max_len: Optional[int] = None,
                   pad_rows_to: int = 1) -> PaddedHistories:
    """Pack COO triples into row-major padded histories.

    ``max_len`` caps history length (longest-kept-first is NOT applied;
    entries beyond the cap are dropped in input order — callers wanting
    recency should pre-sort). ``pad_rows_to`` rounds the row count up so
    the leading axis divides evenly across mesh shards.
    """
    order = np.argsort(rows, kind="stable")
    rows_s, cols_s, vals_s = rows[order], cols[order], vals[order]
    counts = np.bincount(rows_s, minlength=n_rows).astype(np.int32)
    L = resolve_max_len(counts, n_rows, max_len)

    n_pad = ((n_rows + pad_rows_to - 1) // pad_rows_to) * pad_rows_to
    indices = np.zeros((n_pad, L), dtype=np.int32)
    values = np.zeros((n_pad, L), dtype=np.float32)

    # position of each entry within its row
    starts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    pos_in_row = np.arange(len(rows_s)) - starts[rows_s]
    keep = pos_in_row < L
    indices[rows_s[keep], pos_in_row[keep]] = cols_s[keep]
    values[rows_s[keep], pos_in_row[keep]] = vals_s[keep]
    kept_counts = np.minimum(counts, L)
    out_counts = np.zeros(n_pad, dtype=np.int32)
    out_counts[:n_rows] = kept_counts
    return PaddedHistories(indices=indices, values=values, counts=out_counts)


def transpose_coo(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Swap the roles of rows and cols (users↔items)."""
    return cols, rows, vals


@dataclass(frozen=True)
class SplitHistories:
    """Row-split packing: every real row longer than ``max_len`` becomes
    ⌈count/L⌉ *virtual rows* of up to L entries each, so **no entry is
    ever dropped** regardless of skew (MLlib uses every rating —
    ``ALSAlgorithm.scala:75-85``; a zipf item catalog must too). The ALS
    update computes per-virtual-row normal-equation partials and
    scatter-adds them onto the owning real row before solving.

    ``indices/values`` are ``[n_virtual_pad, L]`` like
    :class:`PaddedHistories`; ``counts`` holds per-*virtual*-row entry
    counts; ``row_ids[v]`` is the real row owning virtual row v
    (``n_rows`` sentinel on padding rows — scatter mode="drop" territory);
    ``real_counts`` are true per-real-row totals (regularization scaling).
    """

    indices: np.ndarray      # [n_virtual_pad, L] int32
    values: np.ndarray       # [n_virtual_pad, L] float32
    counts: np.ndarray       # [n_virtual_pad] int32 (per virtual row)
    row_ids: np.ndarray      # [n_virtual_pad] int32 → real row (or n_rows)
    real_counts: np.ndarray  # [n_rows_pad] int32
    n_rows: int              # real rows (unpadded)

    @property
    def n_virtual(self) -> int:
        return self.indices.shape[0]

    @property
    def n_rows_padded(self) -> int:
        return self.real_counts.shape[0]

    @property
    def max_len(self) -> int:
        return self.indices.shape[1]


def split_layout(counts: np.ndarray, max_len: int,
                 pad_rows_to: int = 1) -> Tuple[np.ndarray, int, int]:
    """Host-side split bookkeeping: per-real-row virtual-row counts, the
    total virtual rows, and the padded virtual row count. Split shapes are
    data-dependent, so this must run on the host before the static-shape
    device pack."""
    groups = -(-counts // max_len)  # ceil; 0-count rows get 0 virtual rows
    n_virtual = int(groups.sum())
    n_vpad = max(((n_virtual + pad_rows_to - 1) // pad_rows_to)
                 * pad_rows_to, pad_rows_to)
    return groups.astype(np.int64), n_virtual, n_vpad


def pack_histories_split(rows: np.ndarray, cols: np.ndarray,
                         vals: np.ndarray, n_rows: int, max_len: int,
                         pad_rows_to: int = 1) -> SplitHistories:
    """Host-numpy split packing (see :class:`SplitHistories`)."""
    L = max(int(max_len), 1)
    order = np.argsort(rows, kind="stable")
    rs, cs, vs = rows[order], cols[order], vals[order]
    counts = np.bincount(rs, minlength=n_rows).astype(np.int64)
    groups, n_virtual, n_vpad = split_layout(counts, L, pad_rows_to)
    gstarts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(groups, out=gstarts[1:])

    starts = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    pos = np.arange(len(rs)) - starts[rs]
    vrow = gstarts[rs] + pos // L
    vpos = pos % L

    indices = np.zeros((n_vpad, L), dtype=np.int32)
    values = np.zeros((n_vpad, L), dtype=np.float32)
    indices[vrow, vpos] = cs
    values[vrow, vpos] = vs

    row_ids = np.full(n_vpad, n_rows, dtype=np.int32)
    row_ids[:n_virtual] = np.repeat(
        np.arange(n_rows, dtype=np.int32), groups)
    vcounts = np.zeros(n_vpad, dtype=np.int32)
    # entries in virtual row v of row r: min(L, count_r - k·L)
    k_within = np.arange(n_virtual) - gstarts[row_ids[:n_virtual]]
    vcounts[:n_virtual] = np.minimum(
        counts[row_ids[:n_virtual]] - k_within * L, L).astype(np.int32)

    n_rows_pad = max(((n_rows + pad_rows_to - 1) // pad_rows_to)
                     * pad_rows_to, pad_rows_to)
    real_counts = np.zeros(n_rows_pad, dtype=np.int32)
    real_counts[:n_rows] = counts
    return SplitHistories(indices=indices, values=values, counts=vcounts,
                          row_ids=row_ids, real_counts=real_counts,
                          n_rows=n_rows)


def pack_histories_split_device(rows: np.ndarray, cols: np.ndarray,
                                vals: np.ndarray, n_rows: int,
                                max_len: int,
                                pad_rows_to: int = 1) -> SplitHistories:
    """Device-side split packing: the host computes only the cheap
    bincount-derived layout (shapes must be static); the heavy sort +
    scatters run as one jitted XLA program, mirroring
    :func:`pack_histories_device`."""
    import jax.numpy as jnp

    L = max(int(max_len), 1)
    # COO triples may arrive as device arrays: land rows ONCE here for
    # the host-side layout math (shapes must be static), instead of a
    # fresh implicit transfer per use. ptpu: allow[host-sync-in-hot-path]
    rows = np.asarray(rows)
    counts_h = np.bincount(rows, minlength=n_rows)
    groups, n_virtual, n_vpad = split_layout(counts_h, L, pad_rows_to)
    n_rows_pad = max(((n_rows + pad_rows_to - 1) // pad_rows_to)
                     * pad_rows_to, pad_rows_to)
    idx, val, vcnt, row_ids, real_counts = _pack_split_on_device(
        jnp.asarray(rows, dtype=jnp.int32),
        jnp.asarray(cols, dtype=jnp.int32),
        jnp.asarray(vals, dtype=jnp.float32),
        jnp.asarray(groups, dtype=jnp.int32),
        n_rows=n_rows, L=L, n_vpad=n_vpad, n_virtual=n_virtual,
        n_rows_pad=n_rows_pad)
    # host-land for the same reason as the bucketed pack: only the
    # blocked copies belong in HBM
    idx, val, vcnt, row_ids, real_counts = _host(
        idx, val, vcnt, row_ids, real_counts)
    return SplitHistories(indices=idx, values=val, counts=vcnt,
                          row_ids=row_ids, real_counts=real_counts,
                          n_rows=n_rows)


def _pack_split_on_device(r, c, v, groups, *, n_rows: int, L: int,
                          n_vpad: int, n_virtual: int, n_rows_pad: int):
    import jax

    global _pack_split_jit
    if _pack_split_jit is None:
        import jax.numpy as jnp

        def pack(r, c, v, groups, n_rows, L, n_vpad, n_virtual,
                 n_rows_pad):
            nnz = r.shape[0]
            order = jnp.argsort(r, stable=True)
            rs, cs, vs = r[order], c[order], v[order]
            counts = jnp.bincount(rs, length=n_rows).astype(jnp.int32)
            starts = jnp.concatenate(
                [jnp.zeros(1, jnp.int32),
                 jnp.cumsum(counts, dtype=jnp.int32)])
            gstarts = jnp.concatenate(
                [jnp.zeros(1, jnp.int32),
                 jnp.cumsum(groups, dtype=jnp.int32)])
            pos = jnp.arange(nnz, dtype=jnp.int32) - starts[rs]
            vrow = gstarts[rs] + pos // L
            vpos = pos % L
            flat = vrow * jnp.int32(L) + vpos
            idx = jnp.zeros(n_vpad * L, jnp.int32).at[flat].set(
                cs, mode="drop")
            val = jnp.zeros(n_vpad * L, jnp.float32).at[flat].set(
                vs, mode="drop")
            owners = jnp.repeat(jnp.arange(n_rows, dtype=jnp.int32),
                                groups, total_repeat_length=n_virtual)
            row_ids = jnp.full(n_vpad, n_rows, jnp.int32) \
                .at[jnp.arange(n_virtual)].set(owners)
            k_within = jnp.arange(n_vpad, dtype=jnp.int32) \
                - gstarts[jnp.minimum(row_ids, n_rows - 1)]
            vcnt = jnp.where(
                row_ids < n_rows,
                jnp.minimum(counts[jnp.minimum(row_ids, n_rows - 1)]
                            - k_within * L, L), 0).astype(jnp.int32)
            real_counts = jnp.zeros(n_rows_pad, jnp.int32).at[:n_rows].set(
                counts)
            return (idx.reshape(n_vpad, L), val.reshape(n_vpad, L), vcnt,
                    row_ids, real_counts)

        _pack_split_jit = jax.jit(
            pack, static_argnames=("n_rows", "L", "n_vpad", "n_virtual",
                                   "n_rows_pad"))
    return _pack_split_jit(r, c, v, groups, n_rows=n_rows, L=L,
                           n_vpad=n_vpad, n_virtual=n_virtual,
                           n_rows_pad=n_rows_pad)


_pack_split_jit = None


@dataclass(frozen=True)
class HistoryBucket:
    """One length class of a :class:`BucketedHistories` layout: all rows
    whose history fits L (and not L/2). ``row_ids[j]`` is the real row
    that bucket-row j belongs to (``n_rows_padded`` sentinel on padding
    rows); each real row appears in AT MOST ONE bucket, so writing the
    per-bucket solve results back is a unique-index scatter — no
    duplicate-index scatter-add anywhere (TPU serializes those)."""

    length: int
    indices: np.ndarray   # [n_bk_pad, L] int32
    values: np.ndarray    # [n_bk_pad, L] float32
    counts: np.ndarray    # [n_bk_pad] int32 (true history length)
    row_ids: np.ndarray   # [n_bk_pad] int32

    @property
    def n_rows(self) -> int:
        return self.indices.shape[0]


@dataclass(frozen=True)
class BucketedHistories:
    """Drop-free dense layout for skewed histories: each row is padded to
    the next power of two of its own length (≤2× padding waste) instead
    of a single global ``max_len``. Besides never dropping entries (MLlib
    parity — ``ALSAlgorithm.scala:75-85``), per-bucket updates give every
    normal-equation einsum a contraction depth K = L_bucket, where the
    single-L split layout forced the small L that minimizes padding —
    and tiny K starves the MXU."""

    buckets: tuple          # of HistoryBucket, ascending length
    n_rows: int
    n_rows_padded: int

    @property
    def padded_entries(self) -> int:
        return sum(b.n_rows * b.length for b in self.buckets)

    @property
    def max_len(self) -> int:
        return max((b.length for b in self.buckets), default=1)


def bucket_layout(counts: np.ndarray, min_len: int = 8,
                  pad_rows_to: int = 1, max_len: Optional[int] = None):
    """Host-side bucket planning: per-row bucket length (next pow2 of the
    row's count, floored at ``min_len``, optionally capped at
    ``max_len`` — capped rows TRUNCATE like the pad layout), member rows
    per bucket, and the flat destination offset of every row's first
    slot."""
    n_rows = len(counts)
    if max_len is not None:
        counts = np.minimum(counts, max_len)
    lengths = np.maximum(min_len, 1 << np.int64(
        np.ceil(np.log2(np.maximum(counts, 1)))))
    lengths[counts == 0] = 0  # empty rows join no bucket
    plan = []
    row_base = np.zeros(n_rows, dtype=np.int64)
    off = 0
    for L in np.unique(lengths):
        if L == 0:
            continue
        rows_k = np.flatnonzero(lengths == L)
        n_bk = len(rows_k)
        n_bk_pad = max(-(-n_bk // pad_rows_to) * pad_rows_to, pad_rows_to)
        row_base[rows_k] = off + np.arange(n_bk, dtype=np.int64) * int(L)
        plan.append((int(L), rows_k, n_bk_pad, off))
        off += n_bk_pad * int(L)
    return plan, row_base, off  # off == total flat slots S


def pack_histories_bucketed_device(rows: np.ndarray, cols: np.ndarray,
                                   vals: np.ndarray, n_rows: int,
                                   pad_rows_to: int = 1,
                                   min_len: int = 8,
                                   max_len: Optional[int] = None,
                                   counts: Optional[np.ndarray] = None
                                   ) -> BucketedHistories:
    """Pack COO triples into the bucketed layout with ONE compiled
    scatter (host work is bincount + per-row offset arithmetic): sort by
    row on device, scatter each entry to ``row_base[row] + pos_in_row``
    in a flat buffer, then carve per-bucket views. ``max_len`` caps each
    row's history (truncating in input order, pad-layout semantics);
    without it the layout is drop-free."""
    import jax.numpy as jnp

    # single host landing for the layout math (see the split pack)
    rows = np.asarray(rows)  # ptpu: allow[host-sync-in-hot-path]
    if counts is None:  # callers that already histogrammed pass it in
        counts = np.bincount(rows, minlength=n_rows)
    if max_len is not None:
        counts = np.minimum(counts, int(max_len))
    plan, row_base, S = bucket_layout(counts, min_len, pad_rows_to)
    n_rows_pad = max(-(-n_rows // pad_rows_to) * pad_rows_to, pad_rows_to)
    if S == 0:
        return BucketedHistories(buckets=(), n_rows=n_rows,
                                 n_rows_padded=n_rows_pad)
    if S >= 2 ** 31:  # pragma: no cover — would need >1B ratings
        raise ValueError(f"bucketed layout needs {S} slots (> int32); "
                         "shard the dataset across hosts first")
    flat = _pack_flat_native(rows, cols, vals, row_base, counts,
                             n_rows, S)
    if flat is None:
        flat = _pack_flat_on_device(
            jnp.asarray(rows, dtype=jnp.int32),
            jnp.asarray(cols, dtype=jnp.int32),
            jnp.asarray(vals, dtype=jnp.float32),
            jnp.asarray(row_base, dtype=jnp.int32),
            jnp.asarray(counts, dtype=jnp.int32),  # post-cap budget
            n_rows=n_rows, S=S)
    # land the packed layout on HOST (see _host for why)
    flat_idx, flat_val = _host(flat[0], flat[1])
    buckets = []
    for L, rows_k, n_bk_pad, off in plan:
        n_bk = len(rows_k)
        # each padding row gets a DISTINCT out-of-range sentinel: the
        # result-writeback scatter promises unique_indices=True, and a
        # shared sentinel would make that promise false (UB per the JAX
        # scatter contract) even though the rows drop
        row_ids = (n_rows_pad
                   + np.arange(n_bk_pad, dtype=np.int64) - n_bk
                   ).astype(np.int32)
        row_ids[:n_bk] = rows_k
        cnt = np.zeros(n_bk_pad, dtype=np.int32)
        cnt[:n_bk] = counts[rows_k]
        buckets.append(HistoryBucket(
            length=L,
            indices=flat_idx[off:off + n_bk_pad * L].reshape(n_bk_pad, L),
            values=flat_val[off:off + n_bk_pad * L].reshape(n_bk_pad, L),
            counts=cnt, row_ids=row_ids))
    return BucketedHistories(buckets=tuple(buckets), n_rows=n_rows,
                             n_rows_padded=n_rows_pad)


def _pack_flat_native(rows, cols, vals, row_base, row_cap, n_rows: int,
                      S: int):
    """Host C++ counting-sort pack (``native/_codec.cpp pack_flat``), or
    None when the extension is unavailable. Same contract as
    :func:`_pack_flat_on_device` but the flat buffers are born on the
    host — which is where the bucket carving wants them anyway, so the
    device round-trip (~240MB H2D + ~320MB D2H at ML-20M scale through
    a remote tunnel, plus two program compiles) disappears."""
    from ..native import codec

    mod = codec()
    if mod is None or not hasattr(mod, "pack_flat"):
        return None
    r32 = _c_contig(rows, np.int32)
    c32 = _c_contig(cols, np.int32)
    v32 = _c_contig(vals, np.float32)
    b32 = _c_contig(row_base, np.int32)
    k32 = _c_contig(row_cap, np.int32)
    ib, vb = mod.pack_flat(r32, c32, v32, b32, k32, int(n_rows), int(S))
    return (np.frombuffer(ib, dtype=np.int32),
            np.frombuffer(vb, dtype=np.float32))


def _pack_flat_on_device(r, c, v, row_base, row_cap, *, n_rows: int,
                         S: int):
    import jax

    global _pack_flat_jit
    if _pack_flat_jit is None:
        import jax.numpy as jnp

        def pack(r, c, v, row_base, row_cap, n_rows, S):
            # int32 throughout: S and nnz stay < 2^31 (S ≤ ~2·nnz by the
            # ≤2× pow2-padding bound; the flat buffer is range-checked on
            # the host before this program is built)
            nnz = r.shape[0]
            order = jnp.argsort(r, stable=True)
            rs, cs, vs = r[order], c[order], v[order]
            counts = jnp.bincount(rs, length=n_rows).astype(jnp.int32)
            starts = jnp.concatenate(
                [jnp.zeros(1, jnp.int32),
                 jnp.cumsum(counts, dtype=jnp.int32)])
            pos = jnp.arange(nnz, dtype=jnp.int32) - starts[rs]
            # entries past a row's (possibly max_len-capped) budget drop;
            # without a cap pos < row_cap always holds
            dest = jnp.where(pos < row_cap[rs], row_base[rs] + pos,
                             jnp.int32(S))
            # no unique_indices promise: capped entries all alias the
            # OOB sentinel S (they drop, but the promise would be a lie)
            idx = jnp.zeros(S, jnp.int32).at[dest].set(cs, mode="drop")
            val = jnp.zeros(S, jnp.float32).at[dest].set(vs, mode="drop")
            return idx, val

        _pack_flat_jit = jax.jit(pack, static_argnames=("n_rows", "S"))
    return _pack_flat_jit(r, c, v, row_base, row_cap, n_rows=n_rows, S=S)


_pack_flat_jit = None


def resolve_max_len(counts: np.ndarray, n_rows: int,
                    max_len: Optional[int]) -> int:
    """Padded history length: the explicit cap, or the longest row with
    the 99.9th-percentile auto-cap (warning when entries get dropped)."""
    if max_len is not None:
        return max(int(max_len), 1)
    L = int(counts.max(initial=1))
    if n_rows * L > AUTO_CAP_ENTRIES:
        capped = int(np.quantile(counts, 0.999)) or 1
        capped = max(capped, AUTO_CAP_ENTRIES // max(n_rows, 1))
        if capped < L:
            dropped = int(np.maximum(counts - capped, 0).sum())
            log.warning(
                "pack_histories: capping history length %d → %d "
                "(99.9th pct; dense layout would be %d×%d); dropping "
                "%d/%d entries from the heaviest rows. Set max_len to "
                "override.", L, capped, n_rows, L, dropped,
                int(counts.sum()))
            L = capped
    return max(L, 1)


def pack_histories_device(rows: np.ndarray, cols: np.ndarray,
                          vals: np.ndarray, n_rows: int, max_len: int,
                          pad_rows_to: int = 1) -> PaddedHistories:
    """Device-side :func:`pack_histories`: one jitted sort + scatter.

    Packing 20M MovieLens-shaped entries takes ~10s of host numpy
    (argsort + fancy-index scatters) but milliseconds as a compiled XLA
    program, so the COO triples ship to the device raw and the padded
    layout is built there. Semantics match the host packer: stable
    within-row input order, entries beyond ``max_len`` dropped, rows
    padded to a ``pad_rows_to`` multiple.

    Returns the padded arrays as ``jax.Array``s still resident on device
    (duck-typed into ``PaddedHistories``) so the training loop can shard
    them without a host round-trip.
    """
    import jax.numpy as jnp

    L = max(int(max_len), 1)
    n_pad = ((n_rows + pad_rows_to - 1) // pad_rows_to) * pad_rows_to
    # single host landing for the layout math (see the split pack)
    rows = np.asarray(rows)  # ptpu: allow[host-sync-in-hot-path]
    # native host pack first (no device round-trip, no pack compile)
    base = np.arange(n_rows, dtype=np.int64) * L
    if n_pad * L < 2 ** 31:
        flat = _pack_flat_native(
            rows, cols, vals, base,
            np.full(n_rows, L, dtype=np.int32), n_rows, n_pad * L)
    else:  # pragma: no cover — >2^31 slots needs the device path
        flat = None
    if flat is not None:
        counts = np.bincount(rows, minlength=n_rows)
        cnt = np.zeros(n_pad, np.int32)
        cnt[:n_rows] = np.minimum(counts, L)
        return PaddedHistories(indices=flat[0].reshape(n_pad, L),
                               values=flat[1].reshape(n_pad, L),
                               counts=cnt)
    idx, val, cnt = _pack_on_device(
        jnp.asarray(rows, dtype=jnp.int32),
        jnp.asarray(cols, dtype=jnp.int32),
        jnp.asarray(vals, dtype=jnp.float32),
        n_rows=n_rows, L=L, n_pad=n_pad)
    # host-land (same reason as the bucketed/split packs; see _host)
    idx, val, cnt = _host(idx, val, cnt)
    return PaddedHistories(indices=idx, values=val, counts=cnt)


def _pack_on_device(r, c, v, *, n_rows: int, L: int, n_pad: int):
    import jax

    global _pack_jit
    if _pack_jit is None:
        import jax.numpy as jnp

        def pack(r, c, v, n_rows, L, n_pad):
            nnz = r.shape[0]
            order = jnp.argsort(r, stable=True)
            rs, cs, vs = r[order], c[order], v[order]
            counts = jnp.bincount(rs, length=n_rows).astype(jnp.int32)
            starts = jnp.concatenate(
                [jnp.zeros(1, jnp.int32),
                 jnp.cumsum(counts, dtype=jnp.int32)])
            pos = jnp.arange(nnz, dtype=jnp.int32) - starts[rs]
            flat = rs * jnp.int32(L) + pos
            oob = jnp.int32(n_pad * L)  # mode="drop" sentinel for pos >= L
            flat = jnp.where(pos < L, flat, oob)
            idx = jnp.zeros(n_pad * L, jnp.int32).at[flat].set(
                cs, mode="drop")
            val = jnp.zeros(n_pad * L, jnp.float32).at[flat].set(
                vs, mode="drop")
            cnt = jnp.zeros(n_pad, jnp.int32).at[:n_rows].set(
                jnp.minimum(counts, L))
            return idx.reshape(n_pad, L), val.reshape(n_pad, L), cnt

        _pack_jit = jax.jit(pack,
                            static_argnames=("n_rows", "L", "n_pad"))
    return _pack_jit(r, c, v, n_rows=n_rows, L=L, n_pad=n_pad)


_pack_jit = None
