"""Persistent, shape-keyed gram-mode selection (VERDICT r3 task 2).

``ALSParams(gram_mode="auto")`` needs a concrete realization (baseline
einsum vs. the pair-packed MXU tiling, ``ops/gram.py``) at trace time.
Round 3 raced the candidates at *bench* time only; this module makes the
choice persistent and shape-keyed so every trainer entry benefits:

resolution order for ``best_mode(rank, bf16)``:

1. the user cache file (``PIO_GRAM_AUTOTUNE_CACHE``, default
   ``~/.cache/predictionio_tpu/gram_autotune.json``) — written by
   ``record()`` whenever a measured race runs (bench.py's gram race,
   ``benchmarks/gram_profile.py --record``);
2. the packaged defaults (``gram_autotune_defaults.json`` next to this
   file) — the committed table measured on real hardware;
3. a hardware heuristic: on TPU, "pair" below rank 128 (two rank<128
   systems share one 128-wide MXU tile; a full-rank system doesn't),
   "einsum" otherwise and on every non-TPU backend.

Keys are ``<device family>|r<rank bucket>|<f32|bf16>`` — the L/B batch
axes move the absolute time but not the winner (measured: the winner is
set by how full the MXU tile is, i.e. by rank and dtype), so they are
deliberately not in the key.
"""

from __future__ import annotations

import json
import os
import re
import threading

_LOCK = threading.Lock()
_DEFAULTS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "gram_autotune_defaults.json")
_cache_mem: dict | None = None


def _cache_path() -> str:
    return os.environ.get(
        "PIO_GRAM_AUTOTUNE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "predictionio_tpu", "gram_autotune.json"))


def device_family(kind: str | None = None) -> str:
    """Coarse device family ("TPU v5 lite", "TPU v4", "cpu", ...) — fine
    enough to key tuning, coarse enough to survive kind-string noise."""
    if kind is None:
        try:
            import jax

            kind = jax.devices()[0].device_kind
        except Exception:  # noqa: BLE001 — no backend: untuned
            return "unknown"
    kind = str(kind)
    # "TPU v5 lite0" -> "TPU v5 lite"; "TPU v4" -> "TPU v4" (the version
    # digit is part of the family; only a trailing chip INDEX is noise)
    m = re.match(r"^(TPU v\d+[a-z]*(?: lite)?)", kind)
    if m:
        return m.group(1)
    if kind.lower().startswith("tpu"):
        return kind
    return kind.split(" ")[0].lower() or "unknown"


def _rank_bucket(rank: int) -> int:
    for b in (32, 64, 128):
        if rank <= b:
            return b
    return 256


def _key(family: str, rank: int, bf16: bool) -> str:
    return f"{family}|r{_rank_bucket(rank)}|{'bf16' if bf16 else 'f32'}"


def _load(path: str) -> dict:
    try:
        with open(path) as f:
            d = json.load(f)
        return d if isinstance(d, dict) else {}
    except (OSError, json.JSONDecodeError):
        return {}


def _table() -> dict:
    """defaults overlaid by the user cache (cache wins: it's measured on
    THIS machine)."""
    global _cache_mem
    with _LOCK:
        if _cache_mem is None:
            t = _load(_DEFAULTS_PATH)
            t.update(_load(_cache_path()))
            _cache_mem = t
        return dict(_cache_mem)


#: the gram realizations an autotune entry may name (``ops/gram.py``
#: einsum/pair on a materialized gather; ``ops/fused_gram.py`` for the
#: gather-fusing Pallas kernel)
MODES = ("einsum", "pair", "fused")


def _fused_lowers() -> bool:
    """Whether the fused Pallas kernel can actually lower on the
    attached backend — a tuning table measured on one machine may name
    "fused" on a host whose jax/Mosaic build can't compile it (or with
    no accelerator at all); resolution must DEGRADE, never raise."""
    try:
        from .fused_gram import fused_gram_supported

        return fused_gram_supported()
    except Exception:  # noqa: BLE001 — probe failure = unsupported
        return False


def best_mode(rank: int, bf16: bool = False,
              device_kind: str | None = None) -> str:
    """Concrete gram mode ("einsum" | "pair" | "fused") for
    ``gram_mode="auto"``. A table entry naming "fused" is honored only
    where the Pallas kernel lowers (:func:`_fused_lowers`); everywhere
    else it falls back to the baseline einsum instead of raising —
    the tuning table describes a *preference*, not a capability."""
    fam = device_family(device_kind)
    ent = _table().get(_key(fam, rank, bf16))
    if isinstance(ent, dict) and ent.get("mode") in MODES:
        mode = ent["mode"]
        if mode == "fused" and not _fused_lowers():
            return "einsum"
        return mode
    # heuristic: pair-packing helps exactly when two systems fit one
    # 128-wide MXU tile; CPUs/GPUs gain nothing from the extra flops
    if fam.startswith("TPU") and _rank_bucket(rank) < 128:
        return "pair"
    return "einsum"


# -- serving top-k mode table (ISSUE 13) -------------------------------------
#
# The serving batched lane has the same einsum-vs-fused choice training
# got in PR 7: the [B, I] score-matrix einsum (ops/… `_serve_topk`) vs
# the fused gather→score→top-k Pallas kernel (ops/fused_topk.py). Keys
# add a quant dimension — the wire dtype of the row-quantized serving
# tables moves the bandwidth math, and therefore the winner.

#: the serving top-k realizations a table entry may name
TOPK_MODES = ("einsum", "fused")

#: serving-table wire dtypes the key's quant field may carry
TOPK_QUANTS = ("f32", "bf16", "int8")


def _topk_key(family: str, rank: int, quant: str) -> str:
    return f"{family}|topk|r{_rank_bucket(rank)}|{quant}"


def _topk_lowers() -> bool:
    """Whether the fused serving kernel can lower on the attached
    backend — like :func:`_fused_lowers`, resolution must DEGRADE to
    the einsum lane, never raise mid-serve."""
    try:
        from .fused_topk import fused_topk_supported

        return fused_topk_supported()
    except Exception:  # noqa: BLE001 — probe failure = unsupported
        return False


def best_topk_mode(rank: int, quant: str = "f32",
                   device_kind: str | None = None) -> str:
    """Concrete serving top-k mode ("einsum" | "fused") for the
    batched lane, support-gated exactly like :func:`best_mode`: a
    table entry naming "fused" is honored only where the Pallas kernel
    lowers; everywhere else the einsum lane serves. The heuristic
    (no table entry) prefers the fused kernel wherever it lowers — it
    exists to beat the [B, I] HBM round trip — and einsum on every
    backend without it."""
    if quant not in TOPK_QUANTS:
        quant = "f32"
    fam = device_family(device_kind)
    ent = _table().get(_topk_key(fam, rank, quant))
    if isinstance(ent, dict) and ent.get("mode") in TOPK_MODES:
        mode = ent["mode"]
        if mode == "fused" and not _topk_lowers():
            return "einsum"
        return mode
    if fam.startswith("TPU") and _topk_lowers():
        return "fused"
    return "einsum"


def record_topk(rank: int, mode: str, quant: str = "f32",
                device_kind: str | None = None,
                measured: dict | None = None) -> bool:
    """Persist a measured serving top-k winner (serving_bench --quant
    races the lanes); same atomic merge-on-write + source-priority
    discipline as :func:`record`."""
    if mode not in TOPK_MODES or quant not in TOPK_QUANTS:
        return False
    fam = device_family(device_kind)
    if fam in ("unknown", "cpu"):
        return False
    ent = {"mode": mode}
    if measured:
        ent.update(measured)
    return _persist(_topk_key(fam, rank, quant), ent)


def _persist(key: str, ent: dict) -> bool:
    """Atomic merge-on-write of one table entry, honoring the
    measurement-source priority (shared by :func:`record` and
    :func:`record_topk`)."""
    path = _cache_path()
    global _cache_mem
    prio = {"bench_race": 2, "serving_bench": 2, "gram_profile": 1}
    with _LOCK:
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            cur = _load(path)
            old = cur.get(key)
            if (isinstance(old, dict)
                    and prio.get(old.get("source"), 0)
                    > prio.get(ent.get("source"), 0)):
                return False
            cur[key] = ent
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(cur, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            return False  # cache is advisory; never fail the caller
        _cache_mem = None  # re-overlay on next lookup
        return True


def record(rank: int, mode: str, bf16: bool = False,
           device_kind: str | None = None,
           measured: dict | None = None) -> bool:
    """Persist a measured winner (atomic write; merge-on-write so
    concurrent processes tuning different shapes don't clobber).
    Returns whether anything was persisted — callers reporting
    "recorded" must not claim success for a refused write."""
    if mode not in MODES:
        return False
    fam = device_family(device_kind)
    if fam in ("unknown", "cpu"):
        return False  # only persist real-accelerator measurements
    ent = {"mode": mode}
    if measured:
        ent.update(measured)
    # whole-training measurements (bench_race) beat single-op profile
    # measurements for the same key: the end-to-end number includes the
    # fusion context the op actually runs in (_persist's priority map)
    return _persist(_key(fam, rank, bf16), ent)


def reset_for_tests() -> None:
    global _cache_mem
    with _LOCK:
        _cache_mem = None
