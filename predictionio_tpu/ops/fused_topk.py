"""Fused gather→score→top-k Pallas kernel — the serving-side HBM attack.

The batched serving lane (``models/als.py::_serve_topk``) materializes
the full ``[B, I]`` score matrix in HBM before ``lax.top_k`` reduces it
to ``[B, k]`` — at ML-20M scale that is ~230 MB written and read back
per 2048-query dispatch for a result that is 3 orders of magnitude
smaller. This kernel is the serving twin of ``ops/fused_gram.py``
(PR 7): stream, don't materialize.

- per query block, the block's user indices hop from their VMEM block
  into an SMEM tile whose scalar reads drive per-row DMAs pulling user
  rows from the HBM-resident table straight into a ``[block_q, r]``
  VMEM tile (int8/bf16 on the wire for row-quantized serving tables —
  dequantized AFTER the DMA with f32 accumulation, the Tensor-Casting
  precision co-design, arXiv 2010.13100);
- the item table streams through a double-buffered ``[2, chunk, r]``
  VMEM tile — chunk c+1's DMA is in flight while the MXU contracts
  ``[block_q, r] × [r, chunk]`` for chunk c (the fused_gram idiom);
- each chunk's scores merge into an on-chip running top-k
  (``[block_q, k]`` carried through the chunk loop), so the only HBM
  writes are the final ``[B, k]`` ids+scores — the ``[B, I]`` score
  matrix never exists.

Per scored element the HBM traffic drops from ``r·4 + 8`` B (table read
plus score write+readback) to ``r·wire_bytes`` B — ~3× less on the f32
wire and ~12× on int8 rows, which is what moves the batched lane off
the HBM roof (``benchmarks/roofline_probe.py`` PROBE_SERVE measures
where the bound lands).

Entry points mirror fused_gram's contract:

- :func:`fused_topk` — the kernel (``interpret=True`` runs anywhere);
- :func:`fused_topk_dispatch` — compiled on TPU, interpret-mode kernel
  elsewhere (explicit ``serving topk="fused"`` on CPU is a debugging
  run), XLA reference on TPUs whose Mosaic can't lower it;
- :func:`fused_topk_reference` — the jnp mirror (fallback + oracle);
- :func:`fused_topk_supported` — one-shot lowering probe.

Routed through ``models/als.py::_device_topk`` (single + replicated
lanes + pinned hot tier) and ``_sharded_rank_fn`` (per-shard local
top-k with a global ``base`` id offset), picked by the
``gram_autotune.best_topk_mode`` table. See docs/kernels.md for the
VMEM budget math (audited statically by ``ptpu check`` vmem-overbudget
and asserted at trace time below).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover — pallas not in this jax build
    _HAVE_PALLAS = False

#: query rows scored per grid step — bounds the user tile and the
#: running top-k carry; the item-chunk sweep, not the block size, sets
#: the pipeline depth
_BLOCK_Q = 8

#: item rows per double-buffer fill. Bounds the VMEM working set at
#: ``2·chunk·r·wire_bytes`` (512 KiB at r=128 f32, 128 KiB on the int8
#: wire) however large the catalog grows.
_ITEM_CHUNK = 512

#: largest k the on-chip merge carries. Past this the einsum path wins
#: anyway (the [B, I] matrix amortizes over more extracted rows) and
#: the merge's [block_q, k+chunk] top_k stops being cheap — the
#: dispatcher falls back instead of scaling the carry.
TOPK_MAX_K = 128


def fused_topk_vmem_bytes(rank: int, k: int, wire_bytes: int = 4,
                          block_q: int = _BLOCK_Q,
                          chunk: int = _ITEM_CHUNK) -> int:
    """VMEM bytes the kernel holds live per core (docs/kernels.md):
    the double-buffered item tiles + scale rows, the user tile, the
    staged scale/index blocks, the running top-k carry and the merge
    temp, and the output tile."""
    item = 2 * chunk * rank * wire_bytes       # double-buffered chunks
    iscale = 2 * chunk * 4                     # per-chunk scale rows
    ubuf = block_q * rank * wire_bytes         # gathered user rows
    blocks = block_q * 4 * 2                   # idx + uscale blocks
    carry = block_q * k * (4 + 4)              # running top-k s+ids
    merge = block_q * (k + chunk) * (4 + 4)    # concat temp for top_k
    out = block_q * k * (4 + 4)                # output tile
    return item + iscale + ubuf + blocks + carry + merge + out


def _fused_topk_kernel(n_chunks: int, chunk: int, k: int, n_items: int,
                       has_scale: bool, *refs):
    """One ``[block_q]`` query block: gather the block's user rows by
    per-row DMA (indices staged VMEM→SMEM so scalar reads drive the
    copies), then sweep the item table chunk by chunk — chunk c+1's
    block DMA in flight while the MXU scores chunk c — merging each
    chunk's ``[block_q, chunk]`` scores into the on-chip running
    top-k. Only the final ``[block_q, k]`` ids+scores leave the
    core."""
    if has_scale:
        (idx_ref, us_ref, base_ref, utab_ref, itab_ref, isc_ref,
         outs_ref, outi_ref, ubuf, ibuf, vbuf, sbuf,
         usem, isem, vsems, ssems) = refs
    else:
        (idx_ref, base_ref, utab_ref, itab_ref,
         outs_ref, outi_ref, ubuf, ibuf, vbuf,
         usem, isem, vsems) = refs
        us_ref = isc_ref = sbuf = ssems = None
    block_q = ubuf.shape[0]

    def issue_chunk(c, slot):
        pltpu.make_async_copy(
            itab_ref.at[pl.ds(c * chunk, chunk), :],
            vbuf.at[slot], vsems.at[slot]).start()
        if has_scale:
            pltpu.make_async_copy(
                isc_ref.at[pl.ds(c, 1), :],
                sbuf.at[slot], ssems.at[slot]).start()

    def wait_chunk(slot):
        pltpu.make_async_copy(
            itab_ref.at[pl.ds(0, chunk), :],
            vbuf.at[slot], vsems.at[slot]).wait()
        if has_scale:
            pltpu.make_async_copy(
                isc_ref.at[pl.ds(0, 1), :],
                sbuf.at[slot], ssems.at[slot]).wait()

    # stage this block's indices into scalar memory: row DMAs need
    # scalar source addresses
    icopy = pltpu.make_async_copy(idx_ref.at[pl.ds(0, 1), :],
                                  ibuf.at[pl.ds(0, 1), :], isem)
    icopy.start()
    icopy.wait()

    # the user-row gather DMAs ride alongside the first item chunk's
    # block DMA — both in flight before anything waits
    issue_chunk(0, 0)

    def issue_row(q, c):
        pltpu.make_async_copy(
            utab_ref.at[pl.ds(ibuf[0, q], 1), :],
            ubuf.at[pl.ds(q, 1), :], usem).start()
        return c

    jax.lax.fori_loop(0, block_q, issue_row, 0, unroll=False)

    def wait_row(q, c):
        pltpu.make_async_copy(
            utab_ref.at[pl.ds(0, 1), :],
            ubuf.at[pl.ds(q, 1), :], usem).wait()
        return c

    jax.lax.fori_loop(0, block_q, wait_row, 0, unroll=False)

    # dequantize AFTER the wire: int8/bf16 rows upcast in VMEM and
    # every contraction accumulates f32 (preferred_element_type)
    q_rows = ubuf[:].astype(jnp.float32)                # [block_q, r]
    if has_scale:
        q_rows = q_rows * us_ref[0][:, None]
    base = base_ref[0, 0]

    neg = jnp.full((block_q, k), -jnp.inf, dtype=jnp.float32)
    zero_ids = jnp.zeros((block_q, k), dtype=jnp.int32)

    def step(c, carry):
        acc_s, acc_i = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < n_chunks)
        def _():
            issue_chunk(c + 1, jax.lax.rem(c + 1, 2))

        wait_chunk(slot)
        v = vbuf[slot].astype(jnp.float32)              # [chunk, r]
        s = jax.lax.dot_general(
            q_rows, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # [block_q, chunk]
        if has_scale:
            s = s * sbuf[slot][0][None, :]
        gid = (base + c * chunk
               + jax.lax.broadcasted_iota(jnp.int32, (block_q, chunk),
                                          1))
        s = jnp.where(gid < n_items, s, -jnp.inf)
        # streaming merge: earlier chunks sit first in the concat, so
        # lax.top_k's prefer-lower-position tie rule reproduces the
        # reference's prefer-lower-id semantics globally
        cat_s = jnp.concatenate([acc_s, s], axis=1)
        cat_i = jnp.concatenate([acc_i, gid], axis=1)
        top_s, pos = jax.lax.top_k(cat_s, k)
        top_i = jnp.take_along_axis(cat_i, pos, axis=1)
        return top_s, top_i

    acc_s, acc_i = jax.lax.fori_loop(0, n_chunks, step,
                                     (neg, zero_ids), unroll=False)
    outs_ref[:] = acc_s
    outi_ref[:] = acc_i


def _pad_rows_to(x: jax.Array, to: int, fill=0) -> jax.Array:
    n = x.shape[0]
    if n == to:
        return x
    pad = [(0, to - n)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad, constant_values=fill)


def _pow2_ceil(n: int, lo: int = 1) -> int:
    p = lo
    while p < n:
        p <<= 1
    return p


@functools.partial(jax.jit, static_argnames=("k", "n_items", "block_q",
                                             "chunk", "interpret"))
def fused_topk(user_table: jax.Array, idx: jax.Array,
               item_table: jax.Array,
               user_scale: Optional[jax.Array] = None,
               item_scale: Optional[jax.Array] = None,
               base: Optional[jax.Array] = None, *, k: int,
               n_items: int, block_q: int = _BLOCK_Q,
               chunk: Optional[int] = None,
               interpret: bool = False
               ) -> Tuple[jax.Array, jax.Array]:
    """Fused gather→score→top-k from HBM-resident tables: returns
    ``(scores [B, k] f32, ids [B, k] int32)`` for
    ``scores[b] = top_k((user_table[idx[b]]·u_scale) @
    (item_table·i_scale)ᵀ)`` with ids offset by ``base`` (the sharded
    ranker's global-id origin; padding items — global id ≥ n_items —
    are masked to -inf exactly like ``_serve_topk``).

    ``user_scale``/``item_scale`` are the per-row f32 scales of
    int8-quantized tables (both or neither — bf16/f32 tables carry
    none). B pads to the block multiple and the catalog to the chunk
    multiple internally; ragged tails are the normal case."""
    assert _HAVE_PALLAS, "pallas unavailable in this jax build"
    assert (user_scale is None) == (item_scale is None), \
        "int8 tables quantize both sides (scales come in pairs)"
    B = idx.shape[0]
    m, r = user_table.shape
    Ip = item_table.shape[0]
    assert 1 <= k <= TOPK_MAX_K, \
        f"fused_topk carries k <= {TOPK_MAX_K} on chip, got {k}"
    c = min(chunk or _ITEM_CHUNK, _pow2_ceil(max(Ip, 8)))
    c = max(c, k)  # the merge width k+chunk must cover k candidates
    Ipad = -(-Ip // c) * c
    n_chunks = Ipad // c
    Bp = max(-(-B // block_q) * block_q, block_q)
    wire = item_table.dtype.itemsize
    # `ptpu check` (vmem-overbudget) audits this statically; assert the
    # same bound at trace time so an exotic (rank, k, chunk) override
    # fails loudly on the host instead of OOMing VMEM mid-serve
    assert fused_topk_vmem_bytes(r, k, wire, block_q, c) \
        < 16 * 1024 * 1024, \
        f"fused_topk VMEM working set exceeds the ~16 MiB/core " \
        f"budget at rank {r}, k {k}, chunk {c} (docs/kernels.md)"

    idxp = _pad_rows_to(idx.astype(jnp.int32), Bp).reshape(
        Bp // block_q, block_q)
    itab = _pad_rows_to(item_table, Ipad)
    has_scale = item_scale is not None
    inputs = [idxp]
    in_specs = [pl.BlockSpec((1, block_q), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)]
    if has_scale:
        # the user-row scales ride as a pre-gathered [B]-sized block —
        # a [B] fetch from the [m, 1] scale vector, nothing like the
        # [m, r] table the row DMAs exist to avoid
        # ptpu: allow[materialized-gather] — [B]-bounded scale fetch
        us = user_scale.reshape(-1)[idxp.reshape(-1)].astype(
            jnp.float32)
        inputs.append(us.reshape(Bp // block_q, block_q))
        in_specs.append(pl.BlockSpec((1, block_q), lambda i: (i, 0),
                                     memory_space=pltpu.VMEM))
    # ptpu: allow[recompile-hazard] — `base is None` is pytree
    # STRUCTURE, not a traced value: jit already specializes on the
    # argument's presence, so this branch can never retrace per value
    if base is None:
        base_arr = jnp.zeros((1, 1), jnp.int32)
    else:
        base_arr = jnp.asarray(base).astype(jnp.int32).reshape(1, 1)
    inputs.append(base_arr)
    in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    # both factor tables STAY in HBM — user rows are DMA'd by index,
    # item chunks stream through the double buffer; a VMEM-resident
    # BlockSpec would cap the catalog at the ~16MB core budget
    inputs.append(user_table)
    in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
    inputs.append(itab)
    in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
    if has_scale:
        isc = _pad_rows_to(item_scale.reshape(-1).astype(jnp.float32),
                           Ipad, fill=1.0).reshape(n_chunks, c)
        inputs.append(isc)
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))

    scratch = [
        pltpu.VMEM((block_q, r), user_table.dtype),   # gathered rows
        pltpu.SMEM((1, block_q), jnp.int32),          # staged indices
        pltpu.VMEM((2, c, r), item_table.dtype),      # chunk dbl buffer
    ]
    if has_scale:
        scratch.append(pltpu.VMEM((2, 1, c), jnp.float32))
    scratch += [
        pltpu.SemaphoreType.DMA,                      # user rows
        pltpu.SemaphoreType.DMA,                      # index staging
        pltpu.SemaphoreType.DMA((2,)),                # item chunks
    ]
    if has_scale:
        scratch.append(pltpu.SemaphoreType.DMA((2,)))

    kernel = functools.partial(_fused_topk_kernel, n_chunks, c, k,
                               n_items, has_scale)
    scores, ids = pl.pallas_call(
        kernel,
        grid=(Bp // block_q,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((block_q, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_q, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Bp, k), jnp.float32),
            jax.ShapeDtypeStruct((Bp, k), jnp.int32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(*inputs)
    return scores[:B], ids[:B]


def fused_topk_reference(user_table: jax.Array, idx: jax.Array,
                         item_table: jax.Array,
                         user_scale: Optional[jax.Array] = None,
                         item_scale: Optional[jax.Array] = None,
                         base: Optional[jax.Array] = None, *, k: int,
                         n_items: int) -> Tuple[jax.Array, jax.Array]:
    """jnp mirror of the kernel (gather, dequantize, full [B, I] score
    matrix, top_k) — the fallback on TPUs whose Mosaic can't lower the
    kernel and the oracle for the parity tests. Materializes the score
    matrix: this is the baseline the kernel exists to beat."""
    # ptpu: allow[materialized-gather] — [B, r] serving row fetch
    # bounded by the dispatch batch, mirroring _serve_topk
    vecs = user_table[idx].astype(jnp.float32)
    if user_scale is not None:
        # ptpu: allow[materialized-gather] — [B]-bounded scale fetch
        vecs = vecs * user_scale.reshape(-1)[idx][:, None]
    items = item_table.astype(jnp.float32)
    scores = vecs @ items.T
    if item_scale is not None:
        scores = scores * item_scale.reshape(1, -1)
    Ip = item_table.shape[0]
    gid = jnp.arange(Ip, dtype=jnp.int32)
    if base is not None:
        gid = gid + jnp.asarray(base).astype(jnp.int32).reshape(())
    scores = jnp.where((gid < n_items)[None, :], scores, -jnp.inf)
    s, pos = jax.lax.top_k(scores, min(k, Ip))
    ids = jnp.take(gid, pos)
    if k > Ip:  # mirror the kernel's fixed [B, k] shape
        pad = k - Ip
        s = jnp.pad(s, ((0, 0), (0, pad)), constant_values=-jnp.inf)
        ids = jnp.pad(ids, ((0, 0), (0, pad)))
    return s, ids


#: compiled wrapper for the dispatch fallback lanes: without jit the
#: reference runs op-by-op and `item_table.astype(f32)` materializes a
#: full-width copy of the serving table in HBM — exactly the 4×
#: footprint the quantized tables exist to avoid. Compiled, the upcast
#: fuses into the score matmul.
_reference_compiled = jax.jit(fused_topk_reference,
                              static_argnames=("k", "n_items"))


def _tpu_attached() -> bool:
    try:
        dev = jax.devices()[0]
        return dev.platform == "tpu" or dev.device_kind.startswith("TPU")
    except Exception:  # noqa: BLE001 — no backend at all
        return False


_support: dict = {}


def fused_topk_supported() -> bool:
    """Probe ONCE whether the fused serving kernel lowers+compiles on
    the attached backend. True only on a TPU whose Mosaic build accepts
    it (dynamic-index row DMAs and the in-kernel top_k merge are both
    version-dependent); the autotune table uses this to degrade to the
    einsum lane instead of raising mid-serve."""
    if not _HAVE_PALLAS or not _tpu_attached():
        return False
    cached = _support.get("tpu")
    if cached is not None:
        return cached
    try:
        utab = jnp.zeros((256, 64), jnp.float32)
        itab = jnp.zeros((1024, 64), jnp.float32)
        idx = jnp.zeros((_BLOCK_Q,), jnp.int32)
        jax.jit(functools.partial(fused_topk, k=8, n_items=1000)
                ).lower(utab, idx, itab).compile()
        ok = True
    except Exception:  # noqa: BLE001 — lowering not supported
        ok = False
    _support["tpu"] = ok
    return ok


def reset_support_cache_for_tests() -> None:
    _support.clear()


def fused_topk_dispatch(user_table: jax.Array, idx: jax.Array,
                        item_table: jax.Array,
                        user_scale: Optional[jax.Array] = None,
                        item_scale: Optional[jax.Array] = None,
                        base: Optional[jax.Array] = None, *, k: int,
                        n_items: int) -> Tuple[jax.Array, jax.Array]:
    """Backend-aware fused entry (what ``models/als.py::_device_topk``
    calls when the serving top-k resolves to "fused"):

    - TPU with Mosaic support → the compiled kernel;
    - TPU without support → the XLA reference (graceful, not fatal);
    - no TPU → interpret-mode kernel: an explicit topk="fused" on CPU
      is a debugging run and should exercise the REAL kernel (this is
      what tier-1 covers without a TPU).
    """
    if not _HAVE_PALLAS:
        return _reference_compiled(user_table, idx, item_table,
                                   user_scale, item_scale, base,
                                   k=k, n_items=n_items)
    if _tpu_attached():
        if not fused_topk_supported():
            return _reference_compiled(user_table, idx, item_table,
                                       user_scale, item_scale, base,
                                       k=k, n_items=n_items)
        return fused_topk(user_table, idx, item_table, user_scale,
                          item_scale, base, k=k, n_items=n_items)
    return fused_topk(user_table, idx, item_table, user_scale,
                      item_scale, base, k=k, n_items=n_items,
                      interpret=True)
