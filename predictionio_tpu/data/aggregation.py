"""Property aggregation: replaying ``$set/$unset/$delete`` into entity state.

Behavior parity with the reference's two aggregators:

- the commutative ``EventOp`` monoid used for parallel aggregation
  (``data/.../storage/PEventAggregator.scala:30-151``: ``SetProp.++`` per-field
  latest-time merge, ``UnsetProp.++``, ``DeleteEntity.++``, ``EventOp.++``
  at :96-111 and ``toPropertyMap`` at :113-151), and
- the time-ordered fold used for local aggregation
  (``data/.../storage/LEventAggregator.scala:42-141``).

The monoid form is the important one for the TPU build: it is
order-insensitive and associative, so host-side shards of the event log can
be aggregated independently and merged — the same property that let the
reference run it under Spark's ``aggregateByKey``. The fold form is used on
the serving path for single-entity lookups.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from datetime import datetime
from typing import Any, Dict, Iterable, Optional, Tuple

from .datamap import DataMap, PropertyMap
from .event import Event, to_millis

#: Event names that drive property aggregation.
AGGREGATION_EVENTS = ("$set", "$unset", "$delete")


@dataclass(frozen=True)
class EventOp:
    """Commutative, associative summary of an entity's property events.

    ``set_fields`` maps field name → (value, set-time-millis); ``set_t`` is
    the latest ``$set`` time (a ``$set`` with no fields still moves it);
    ``unset_fields`` maps field name → latest unset-time; ``delete_t`` is the
    latest ``$delete`` time. ``merge`` is the monoid ``++``.
    """

    set_fields: Dict[str, Tuple[Any, int]] = field(default_factory=dict)
    set_t: Optional[int] = None
    unset_fields: Dict[str, int] = field(default_factory=dict)
    delete_t: Optional[int] = None
    first_updated: Optional[datetime] = None
    last_updated: Optional[datetime] = None

    @staticmethod
    def from_event(e: Event) -> "EventOp":
        return EventOp.from_parts(e.event, e.properties.to_dict(),
                                  e.event_time_millis, e.event_time)

    @staticmethod
    def from_parts(event: str, properties: Dict[str, Any], t: int,
                   event_time: datetime) -> "EventOp":
        """Build from raw parts — lets the columnar path skip ``Event``
        object construction entirely."""
        if event == "$set":
            return EventOp(
                set_fields={k: (v, t) for k, v in properties.items()},
                set_t=t, first_updated=event_time, last_updated=event_time)
        if event == "$unset":
            return EventOp(
                unset_fields={k: t for k in properties.keys()},
                first_updated=event_time, last_updated=event_time)
        if event == "$delete":
            return EventOp(
                delete_t=t, first_updated=event_time,
                last_updated=event_time)
        return EventOp()

    def merge(self, other: "EventOp") -> "EventOp":
        """Order-insensitive combine: per-field latest-write-wins."""
        set_fields = dict(self.set_fields)
        for k, (v, t) in other.set_fields.items():
            if k not in set_fields or t > set_fields[k][1]:
                set_fields[k] = (v, t)
        unset_fields = dict(self.unset_fields)
        for k, t in other.unset_fields.items():
            if k not in unset_fields or t > unset_fields[k]:
                unset_fields[k] = t
        return EventOp(
            set_fields=set_fields,
            set_t=_max_opt(self.set_t, other.set_t),
            unset_fields=unset_fields,
            delete_t=_max_opt(self.delete_t, other.delete_t),
            first_updated=_min_time(self.first_updated, other.first_updated),
            last_updated=_max_time(self.last_updated, other.last_updated),
        )

    def to_property_map(self) -> Optional[PropertyMap]:
        """Materialize current entity properties, or None if the entity does
        not exist (never ``$set``, or deleted after the last ``$set``).
        Matches ``EventOp.toPropertyMap`` (``PEventAggregator.scala:113-151``):
        a field survives unless unset at-or-after its set time, or the entity
        was deleted at-or-after the *latest* set time; fields set at-or-before
        a non-superseding delete are dropped.
        """
        if self.set_t is None:
            return None
        if self.delete_t is not None and self.delete_t >= self.set_t:
            return None
        fields = {}
        for k, (v, t) in self.set_fields.items():
            if k in self.unset_fields and self.unset_fields[k] >= t:
                continue
            if self.delete_t is not None and self.delete_t >= t:
                continue
            fields[k] = v
        assert self.first_updated is not None and self.last_updated is not None
        return PropertyMap(fields, self.first_updated, self.last_updated)


def _max_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


def _min_time(a: Optional[datetime], b: Optional[datetime]) -> Optional[datetime]:
    if a is None:
        return b
    if b is None:
        return a
    return b if to_millis(b) < to_millis(a) else a


def _max_time(a: Optional[datetime], b: Optional[datetime]) -> Optional[datetime]:
    if a is None:
        return b
    if b is None:
        return a
    return b if to_millis(b) > to_millis(a) else a


def aggregate_properties(events: Iterable[Event]) -> Dict[str, PropertyMap]:
    """Aggregate a stream of events into per-entity current properties using
    the commutative monoid (parallel semantics,
    ``PEventAggregator.aggregateProperties`` at :196-210). Shard-safe: callers
    may aggregate shards independently and combine with
    :func:`merge_aggregates`."""
    ops: Dict[str, EventOp] = {}
    for e in events:
        op = EventOp.from_event(e)
        prev = ops.get(e.entity_id)
        ops[e.entity_id] = prev.merge(op) if prev is not None else op
    out: Dict[str, PropertyMap] = {}
    for entity_id, op in ops.items():
        pm = op.to_property_map()
        if pm is not None:
            out[entity_id] = pm
    return out


def aggregate_from_columnar(batch) -> Dict[str, PropertyMap]:
    """Monoid aggregation over a columnar batch of ``$set/$unset/$delete``
    events (``PEventAggregator.scala:196-210`` without per-event objects):
    the caller pushes entity-type/time filters down as columnar masks; only
    the surviving special events pay Python-level JSON merges."""
    from .event import from_millis

    names = batch.dicts.event_names.values
    entity_values = batch.dicts.entity_ids.values
    ops: Dict[str, EventOp] = {}
    for i in range(batch.n):
        op = EventOp.from_parts(
            names[batch.event[i]], batch.props_json(i),
            int(batch.event_time[i]), from_millis(int(batch.event_time[i])))
        eid = entity_values[batch.entity_id[i]]
        prev = ops.get(eid)
        ops[eid] = prev.merge(op) if prev is not None else op
    out: Dict[str, PropertyMap] = {}
    for entity_id, op in ops.items():
        pm = op.to_property_map()
        if pm is not None:
            out[entity_id] = pm
    return out


def merge_aggregates(a: Dict[str, EventOp], b: Dict[str, EventOp]) -> Dict[str, EventOp]:
    """Combine per-shard partial aggregates (the ``combOp`` of the reference's
    ``aggregateByKey``)."""
    out = dict(a)
    for k, op in b.items():
        prev = out.get(k)
        out[k] = prev.merge(op) if prev is not None else op
    return out


def partial_aggregate(events: Iterable[Event]) -> Dict[str, EventOp]:
    """Per-shard partial aggregation (the ``seqOp`` side)."""
    ops: Dict[str, EventOp] = {}
    for e in events:
        op = EventOp.from_event(e)
        prev = ops.get(e.entity_id)
        ops[e.entity_id] = prev.merge(op) if prev is not None else op
    return ops


def aggregate_properties_single(events: Iterable[Event]) -> Optional[PropertyMap]:
    """Time-ordered fold for one entity (local semantics,
    ``LEventAggregator.aggregatePropertiesSingle`` at :73-91): ``$set`` merges
    right-biased, ``$unset`` drops keys, ``$delete`` resets existence; the
    entity exists only if the fold ends with a defined map."""
    dm: Optional[DataMap] = None
    first: Optional[datetime] = None
    last: Optional[datetime] = None
    for e in sorted(events, key=lambda ev: ev.event_time_millis):
        if e.event not in AGGREGATION_EVENTS:
            continue
        if e.event == "$set":
            dm = e.properties if dm is None else dm.union(e.properties)
        elif e.event == "$unset":
            dm = None if dm is None else dm.without(e.properties.keys())
        elif e.event == "$delete":
            dm = None
        first = _min_time(first, e.event_time)
        last = _max_time(last, e.event_time)
    if dm is None:
        return None
    assert first is not None and last is not None
    return PropertyMap(dm.to_dict(), first, last)


def aggregate_properties_ordered(events: Iterable[Event]) -> Dict[str, PropertyMap]:
    """Grouped time-ordered fold (``LEventAggregator.aggregateProperties`` at
    :42-60)."""
    by_entity: Dict[str, list] = {}
    for e in events:
        by_entity.setdefault(e.entity_id, []).append(e)
    out: Dict[str, PropertyMap] = {}
    for entity_id, evs in by_entity.items():
        pm = aggregate_properties_single(evs)
        if pm is not None:
            out[entity_id] = pm
    return out
