"""Batch views — deprecated pre-0.9 aggregation API, kept for compat.

Capability parity with ``data/.../view/{LBatchView,PBatchView,DataView}.
scala`` (SURVEY C22): an ``EventSeq`` wrapper with predicate filtering and
ordered per-entity aggregation, plus a ``BatchView`` that snapshots an
app's events once and answers filtered/aggregated queries. Deprecated in
the reference and here alike — new code should use
``EventStoreFacade.aggregate_properties`` (C16/C17).
"""

from __future__ import annotations

import warnings
from datetime import datetime
from typing import Callable, Dict, Iterable, List, Optional, TypeVar

from .datamap import DataMap
from .event import Event

T = TypeVar("T")


def _predicate(start_time: Optional[datetime] = None,
               until_time: Optional[datetime] = None,
               entity_type: Optional[str] = None,
               event: Optional[str] = None) -> Callable[[Event], bool]:
    """Compose the ViewPredicates (``LBatchView.scala:31-75``)."""

    def ok(e: Event) -> bool:
        if start_time is not None and e.event_time < start_time:
            return False
        if until_time is not None and not (e.event_time < until_time):
            return False
        if entity_type is not None and e.entity_type != entity_type:
            return False
        if event is not None and e.event != event:
            return False
        return True

    return ok


def data_map_aggregator():
    """The ``$set/$unset/$delete`` fold of ``ViewAggregators`` (:77-101):
    (Optional[DataMap], Event) → Optional[DataMap]."""

    def agg(acc: Optional[DataMap], e: Event) -> Optional[DataMap]:
        if e.event == "$set":
            base = acc.to_dict() if acc else {}
            base.update(e.properties.to_dict())
            return DataMap(base)
        if e.event == "$unset":
            base = acc.to_dict() if acc else {}
            for k in e.properties.to_dict():
                base.pop(k, None)
            return DataMap(base)
        if e.event == "$delete":
            return None
        return acc

    return agg


class EventSeq:
    """List-of-events wrapper (``EventSeq``, ``LBatchView.scala:103-142``)."""

    def __init__(self, events: Iterable[Event]):
        self.events: List[Event] = list(events)

    def filter(self, p: Optional[Callable[[Event], bool]] = None, *,
               start_time: Optional[datetime] = None,
               until_time: Optional[datetime] = None,
               entity_type: Optional[str] = None,
               event: Optional[str] = None) -> "EventSeq":
        pred = p if p is not None else _predicate(
            start_time, until_time, entity_type, event)
        return EventSeq([e for e in self.events if pred(e)])

    def aggregate_by_entity_ordered(
            self, init: T, op: Callable[[T, Event], T]) -> Dict[str, T]:
        """Fold events per entityId in event-time order (:134-141)."""
        grouped: Dict[str, List[Event]] = {}
        for e in sorted(self.events, key=lambda e: e.event_time):
            grouped.setdefault(e.entity_id, []).append(e)
        out: Dict[str, T] = {}
        for eid, evs in grouped.items():
            acc = init
            for e in evs:
                acc = op(acc, e)
            out[eid] = acc
        return out

    def __iter__(self):
        return iter(self.events)

    def __len__(self):
        return len(self.events)


class BatchView:
    """Snapshot view over one app's events (``LBatchView``/``PBatchView``
    role — the L/P split collapses here like everywhere else)."""

    def __init__(self, ctx, app_name: str,
                 start_time: Optional[datetime] = None,
                 until_time: Optional[datetime] = None):
        warnings.warn(
            "BatchView is deprecated (reference data/view/); use "
            "EventStoreFacade.aggregate_properties instead",
            DeprecationWarning, stacklevel=2)
        self.events = EventSeq(ctx.event_store.find(
            app_name, start_time=start_time, until_time=until_time))

    def aggregate_properties(self, entity_type: str) -> Dict[str, DataMap]:
        """Current properties per entity (``LBatchView.scala:168-…``)."""
        agg = data_map_aggregator()
        folded = self.events.filter(
            entity_type=entity_type).aggregate_by_entity_ordered(None, agg)
        return {k: v for k, v in folded.items() if v is not None}
