"""Columnar bulk event reads — the PEvents analogue.

The reference's entire training read path was parallel:
``data/src/main/scala/org/apache/predictionio/data/storage/PEvents.scala:38-189``
hands templates an ``RDD[Event]`` whose partitions Spark scans in
parallel (``storage/jdbc/.../JDBCPEvents.scala:49-89`` splits the SQL
scan by time range). A TPU-native framework has no executors to ship
closures to — what it needs from the data layer is **columns**: dense
integer codes and flat value arrays that turn straight into
``jax.Array`` shards. So the P-side contract here is
:class:`ColumnarBatch`: every event field dictionary-encoded into numpy
arrays, filters pushed down as vectorized masks, host-sharding for
multi-host feeding (``PEvents``' partition role) as array slicing.

Layout (one batch = one app/channel log projection):

- ``event``, ``entity_type``, ``target_entity_type``: int32 codes into
  per-log :class:`StringDict`\\ s (-1 where the target is absent)
- ``entity_id``, ``target_entity_id``: int32 codes into the entity/target
  id dicts — the ``BiMap.stringInt`` indexation
  (``data/.../storage/BiMap.scala:105``) precomputed at the storage layer
- ``event_time``: int64 epoch millis
- ``float_props[name]``: float64 with NaN for missing — numeric
  properties (e.g. ``rating``) extracted at encode time
- ``props_offsets``/``props_blob``: raw JSON property bytes, offset-
  indexed (empty slice ⇒ no properties) — feeds the ``$set`` aggregation
  path and full-event reconstruction

This is a *bulk-read projection*: per-event metadata that training never
touches (event ids, tags, prId, creation time) stays in the row store.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

try:
    import fcntl
except ImportError:  # non-POSIX
    fcntl = None  # type: ignore[assignment]

import numpy as np

from .event import Event, from_millis, to_millis
from .storage.base import ANY, EventFilter

try:  # pandas.factorize is ~10x numpy for bulk string->code encoding
    import pandas as _pd
except ImportError:  # pragma: no cover - pandas is baked into the image
    _pd = None


# -- pandas-optional bulk helpers (backends' encode paths) ------------------
# pandas is an ACCELERATOR here, never a dependency: every helper has a
# slower pure-numpy/stdlib fallback with identical semantics.

def bulk_factorize(values):
    """(codes int64 [n], uniques object ndarray) — None → code -1."""
    if _pd is not None:
        return _pd.factorize(np.asarray(values, dtype=object),
                             use_na_sentinel=True)
    index: Dict[object, int] = {}
    codes = np.empty(len(values), dtype=np.int64)
    uniques: List[object] = []
    for k, v in enumerate(values):
        if v is None:
            codes[k] = -1
            continue
        c = index.get(v)
        if c is None:
            c = index[v] = len(uniques)
            uniques.append(v)
        codes[k] = c
    return codes, np.asarray(uniques, dtype=object)


def bulk_to_float64(values, assume_numeric: bool = False) -> np.ndarray:
    """Numbers → float64, anything else (None/str/bool) → NaN.

    The strict path pays one isinstance pass so a numeric STRING like
    ``"4.5"`` stays NaN (pandas ``to_numeric`` would parse it, silently
    diverging from the lazy JSON-parse path's isinstance gate).
    ``assume_numeric=True`` skips that pass — only for callers whose
    upstream already type-gated (e.g. SQLite's ``json_type`` SQL)."""
    if _pd is not None:
        num = _pd.to_numeric(_pd.Series(list(values), dtype=object),
                             errors="coerce")
        out = num.to_numpy(dtype=np.float64, na_value=np.nan)
        if assume_numeric:
            return np.ascontiguousarray(out)
        # to_numpy may hand back a read-only view: never write in place
        good = np.fromiter(
            (isinstance(v, (int, float)) and not isinstance(v, bool)
             or v is None for v in values),
            dtype=bool, count=len(values))
        return np.where(good, out, np.nan)
    return np.array([v if isinstance(v, (int, float))
                     and not isinstance(v, bool) else np.nan
                     for v in values], dtype=np.float64)


def hash_impl() -> str:
    """Which :func:`bulk_hash64` implementation this process uses
    (``'pd'`` = pandas siphash, ``'blake2b'`` = stdlib fallback). The
    two are mutually incompatible, so sidecar manifests record the
    writer's implementation: a reader on a different stack must rebuild
    rather than run a dup check that can never match (and so silently
    fails open, appending duplicate rows on crash replay)."""
    return "pd" if _pd is not None else "blake2b"


def bulk_hash64(strings) -> np.ndarray:
    """Deterministic 64-bit hashes of strings (uint64) — stable across
    processes and hosts (pod hosts compare these on a shared fs), as
    long as every host runs the same stack: the pandas path (siphash,
    fixed key) and the fallback (blake2b) are each self-consistent but
    differ from each other (see :func:`hash_impl`)."""
    if _pd is not None:
        return _pd.util.hash_array(np.asarray(strings, dtype=object))
    import hashlib

    return np.fromiter(
        (int.from_bytes(hashlib.blake2b(
            s.encode("utf-8"), digest_size=8).digest(), "little")
         for s in strings), dtype=np.uint64, count=len(strings))


def bulk_iso_to_millis(strings) -> np.ndarray:
    """ISO-8601 timestamps → epoch millis int64.

    ``asi8``'s unit follows the DatetimeIndex RESOLUTION, which pandas
    infers (datetime64[us] for these strings — a raw ``// 1_000_000``
    would silently yield epoch SECONDS); convert to an explicit ms
    resolution first."""
    if _pd is not None:
        # format="ISO8601" itself requires pandas >= 2.0, which also has
        # as_unit — no older-pandas branch is reachable here
        return _pd.to_datetime(list(strings), utc=True,
                               format="ISO8601").as_unit("ms").asi8
    from datetime import datetime, timedelta, timezone

    from .event import parse_iso

    epoch = datetime(1970, 1, 1, tzinfo=timezone.utc)
    one_ms = timedelta(milliseconds=1)
    # timedelta floor-division FLOORS (exact integer math) — matching
    # pandas' as_unit truncation for pre-epoch sub-ms times, where
    # float timestamp()*1000 would truncate toward zero instead
    return np.fromiter(((parse_iso(s) - epoch) // one_ms
                        for s in strings),
                       dtype=np.int64, count=len(strings))

__all__ = [
    "StringDict",
    "ColumnarBatch",
    "ColumnarDicts",
    "SegmentLog",
    "columnar_from_events",
    "columnar_from_columns",
]


class StringDict:
    """Append-only string → dense int32 code dictionary.

    Codes are assigned in first-seen order and never change, so segments
    encoded at different times against the same dict concatenate without
    remapping (the property per-log dicts exist for).
    """

    __slots__ = ("values", "index", "_pdidx")

    def __init__(self, values: Optional[List[str]] = None):
        self.values: List[str] = list(values or [])
        self.index: Dict[str, int] = {v: i for i, v in enumerate(self.values)}
        self._pdidx = None  # lazy pandas Index for C-bulk lookups

    def __len__(self) -> int:
        return len(self.values)

    def encode_one(self, s: str) -> int:
        code = self.index.get(s)
        if code is None:
            code = len(self.values)
            self.index[s] = code
            self.values.append(s)
        return code

    def _bulk_lookup(self, uniques) -> np.ndarray:
        """Codes for a sequence of UNIQUE strings (appending unseen ones)
        — one C-level hash join instead of n dict lookups; the Python
        path only runs for genuinely-new values."""
        if _pd is None or len(uniques) < 1024:
            return np.fromiter((self.encode_one(u) for u in uniques),
                               dtype=np.int32, count=len(uniques))
        # the cached Index may be a STALE SNAPSHOT of values[:k] — codes
        # never change, so its hits stay correct; misses (new since the
        # snapshot, or genuinely new) take the dict path. Rebuild only
        # when the dict has outgrown the snapshot enough that misses
        # dominate — not on every append.
        if self._pdidx is None or len(self.values) > 2 * len(self._pdidx):
            self._pdidx = _pd.Index(self.values, dtype=object)
        codes = self._pdidx.get_indexer(uniques).astype(np.int32)
        for i in np.flatnonzero(codes < 0):
            codes[i] = self.encode_one(uniques[i])
        return codes

    def encode(self, strings: Sequence[Optional[str]],
               missing: int = -1) -> np.ndarray:
        """Bulk-encode (appending unseen strings); None → ``missing``.
        ``bytes`` values are accepted (UTF-8) — bulk readers fetch raw
        bytes so only the dictionary *uniques* pay a decode here."""
        n = len(strings)
        if n == 0:
            return np.empty(0, dtype=np.int32)
        if _pd is not None:
            codes, uniques = _pd.factorize(
                _pd.array(strings, dtype=object), use_na_sentinel=True)
            if len(uniques) == 0:  # every value None
                return np.full(n, missing, dtype=np.int32)
            # map the batch-local codes onto the persistent dict
            uniques = [u.decode("utf-8") if isinstance(u, bytes) else u
                       for u in uniques.tolist()]
            remap = self._bulk_lookup(uniques)
            out = np.where(codes >= 0, remap[np.maximum(codes, 0)],
                           np.int32(missing)).astype(np.int32)
            return out
        enc = self.encode_one
        return np.fromiter(
            (missing if s is None else
             enc(s.decode("utf-8") if isinstance(s, bytes) else s)
             for s in strings),
            dtype=np.int32, count=n)

    def decode(self, codes: np.ndarray) -> List[Optional[str]]:
        vals = self.values
        return [vals[c] if c >= 0 else None for c in codes]

    def as_array(self) -> np.ndarray:
        return np.asarray(self.values, dtype=object)


@dataclass
class ColumnarDicts:
    """The five per-log dictionaries all of a log's segments share."""

    event_names: StringDict = field(default_factory=StringDict)
    entity_types: StringDict = field(default_factory=StringDict)
    entity_ids: StringDict = field(default_factory=StringDict)
    target_types: StringDict = field(default_factory=StringDict)
    target_ids: StringDict = field(default_factory=StringDict)

    def counts(self) -> Dict[str, int]:
        return {k: len(getattr(self, k)) for k in (
            "event_names", "entity_types", "entity_ids",
            "target_types", "target_ids")}


_EMPTY_F64 = lambda n: np.full(n, np.nan, dtype=np.float64)  # noqa: E731


@dataclass
class ColumnarBatch:
    """A projection of one event log as dictionary-encoded columns."""

    event: np.ndarray          # int32 [n]
    entity_type: np.ndarray    # int32 [n]
    entity_id: np.ndarray      # int32 [n]
    target_type: np.ndarray    # int32 [n], -1 = None
    target_id: np.ndarray      # int32 [n], -1 = None
    event_time: np.ndarray     # int64 [n] epoch ms
    props_offsets: np.ndarray  # int64 [n+1]
    props_blob: np.ndarray     # uint8 [total]
    float_props: Dict[str, np.ndarray]  # name -> float64 [n], NaN missing
    dicts: ColumnarDicts

    def __len__(self) -> int:
        return len(self.event)

    @property
    def n(self) -> int:
        return len(self.event)

    # -- filter pushdown (vectorized EventFilter) --------------------------
    def mask(self, f: EventFilter) -> np.ndarray:
        m = np.ones(self.n, dtype=bool)
        if f.start_time is not None:
            m &= self.event_time >= to_millis(f.start_time)
        if f.until_time is not None:
            m &= self.event_time < to_millis(f.until_time)
        if f.event_names is not None:
            codes = [self.dicts.event_names.index.get(nm, -2)
                     for nm in f.event_names]
            m &= np.isin(self.event, np.asarray(codes, dtype=np.int32))
        if f.entity_type is not None:
            c = self.dicts.entity_types.index.get(f.entity_type, -2)
            m &= self.entity_type == c
        if f.entity_id is not None:
            c = self.dicts.entity_ids.index.get(f.entity_id, -2)
            m &= self.entity_id == c
        if f.target_entity_type is not ANY:
            if f.target_entity_type is None:
                m &= self.target_type == -1
            else:
                c = self.dicts.target_types.index.get(
                    f.target_entity_type, -2)
                m &= self.target_type == c
        if f.target_entity_id is not ANY:
            if f.target_entity_id is None:
                m &= self.target_id == -1
            else:
                c = self.dicts.target_ids.index.get(f.target_entity_id, -2)
                m &= self.target_id == c
        return m

    def take(self, idx: np.ndarray,
             with_props: bool = True) -> "ColumnarBatch":
        """Row subset (indices or bool mask). ``with_props=False`` skips
        the property-byte repack — the training read path extracts its
        numeric columns at encode time and never touches the raw JSON."""
        if idx.dtype == bool:
            idx = np.flatnonzero(idx)
        if with_props:
            lens = self.props_offsets[1:] - self.props_offsets[:-1]
            sel_lens = lens[idx]
            offs = np.zeros(len(idx) + 1, dtype=np.int64)
            np.cumsum(sel_lens, out=offs[1:])
            total = int(offs[-1])
            if total == 0:
                blob = np.empty(0, dtype=np.uint8)
            else:
                # vectorized gather: each output byte's source index is the
                # selected row's start plus the byte's offset within it
                ramp = np.arange(total, dtype=np.int64) \
                    - np.repeat(offs[:-1], sel_lens)
                src = np.repeat(self.props_offsets[:-1][idx],
                                sel_lens) + ramp
                blob = np.asarray(self.props_blob)[src]
        else:
            offs = np.zeros(len(idx) + 1, dtype=np.int64)
            blob = np.empty(0, dtype=np.uint8)
        return ColumnarBatch(
            event=self.event[idx], entity_type=self.entity_type[idx],
            entity_id=self.entity_id[idx], target_type=self.target_type[idx],
            target_id=self.target_id[idx], event_time=self.event_time[idx],
            props_offsets=offs, props_blob=blob,
            float_props={k: v[idx] for k, v in self.float_props.items()},
            dicts=self.dicts)

    def select(self, f: EventFilter, ordered: bool = True,
               with_props: bool = True) -> "ColumnarBatch":
        """Apply an :class:`EventFilter`. ``ordered=False`` skips the
        event-time sort (an O(n log n) argsort a bulk training read does
        not need); limit/reversed force ordering."""
        m = self.mask(f)
        need_order = ordered or f.reversed \
            or (f.limit is not None and f.limit >= 0)
        if not need_order and m.all():
            if with_props:
                return self
            # zero-copy view minus the property bytes — the bulk training
            # read's hot case (homogeneous rate/buy logs)
            return ColumnarBatch(
                event=self.event, entity_type=self.entity_type,
                entity_id=self.entity_id, target_type=self.target_type,
                target_id=self.target_id, event_time=self.event_time,
                props_offsets=np.zeros(self.n + 1, dtype=np.int64),
                props_blob=np.empty(0, dtype=np.uint8),
                float_props=self.float_props, dicts=self.dicts)
        idx = np.flatnonzero(m)
        if need_order:
            order = np.argsort(self.event_time[idx], kind="stable")
            if f.reversed:
                order = order[::-1]
            idx = idx[order]
        if f.limit is not None and f.limit >= 0:
            idx = idx[: f.limit]
        return self.take(idx, with_props=with_props)

    def slice_rows(self, lo: int, hi: int,
                   with_props: bool = True) -> "ColumnarBatch":
        """Zero-copy contiguous row range ``[lo, hi)``: basic numpy
        slicing, so mmap-backed columns touch no pages outside the
        range — the storage-level shard-pushdown primitive. The props
        blob stays a view too (offsets are rebased, an O(rows) int64
        copy, never an O(bytes) blob copy)."""
        if not 0 <= lo <= hi <= self.n:
            raise ValueError(f"slice [{lo}, {hi}) of {self.n} rows")
        if with_props:
            offs = self.props_offsets[lo:hi + 1] - self.props_offsets[lo]
            blob = self.props_blob[self.props_offsets[lo]:
                                   self.props_offsets[hi]]
        else:
            offs = np.zeros(hi - lo + 1, dtype=np.int64)
            blob = np.empty(0, dtype=np.uint8)
        return ColumnarBatch(
            event=self.event[lo:hi], entity_type=self.entity_type[lo:hi],
            entity_id=self.entity_id[lo:hi],
            target_type=self.target_type[lo:hi],
            target_id=self.target_id[lo:hi],
            event_time=self.event_time[lo:hi],
            props_offsets=offs, props_blob=blob,
            float_props={k: v[lo:hi]
                         for k, v in self.float_props.items()},
            dicts=self.dicts)

    @staticmethod
    def shard_bounds(n: int, count: int) -> np.ndarray:
        """The canonical ``count + 1`` split points every backend's
        ``shard=`` pushdown uses over ``n`` storage-order rows — shards
        computed by different backends/hosts must tile identically."""
        return np.linspace(0, n, count + 1).astype(np.int64)

    def shard(self, index: int, count: int,
              with_props: bool = True) -> "ColumnarBatch":
        """Contiguous host shard ``index`` of ``count`` — the role of
        ``PEvents``' RDD partitions for multi-host feeding. Zero-copy
        (see :meth:`slice_rows`)."""
        if not 0 <= index < count:
            raise ValueError(f"shard {index} of {count}")
        bounds = self.shard_bounds(self.n, count)
        sub = self.slice_rows(int(bounds[index]), int(bounds[index + 1]),
                              with_props=with_props)
        sub.shard_offset = int(bounds[index])
        sub.shard_total = self.n
        return sub

    # -- property access ---------------------------------------------------
    def props_json(self, i: int) -> dict:
        s, e = int(self.props_offsets[i]), int(self.props_offsets[i + 1])
        if e == s:
            return {}
        return json.loads(self.props_blob[s:e].tobytes().decode("utf-8"))

    def float_prop(self, name: str) -> np.ndarray:
        """Numeric property column; lazily parsed from the raw JSON bytes
        when it wasn't extracted at encode time."""
        col = self.float_props.get(name)
        if col is not None:
            return col
        out = _EMPTY_F64(self.n)
        offs = self.props_offsets
        nonempty = np.flatnonzero(offs[1:] > offs[:-1])
        for i in nonempty:
            v = self.props_json(int(i)).get(name)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[i] = float(v)
        self.float_props[name] = out
        return out

    # -- compat ------------------------------------------------------------
    def to_events(self) -> Iterator[Event]:
        """Reconstruct :class:`Event` objects (bulk-projection fields only:
        no event ids / tags / prId — see module docstring)."""
        d = self.dicts
        ev, et, ei = d.event_names.values, d.entity_types.values, \
            d.entity_ids.values
        tt, ti = d.target_types.values, d.target_ids.values
        for i in range(self.n):
            tc = int(self.target_type[i])
            yield Event(
                event=ev[self.event[i]],
                entity_type=et[self.entity_type[i]],
                entity_id=ei[self.entity_id[i]],
                target_entity_type=tt[tc] if tc >= 0 else None,
                target_entity_id=(ti[int(self.target_id[i])]
                                  if self.target_id[i] >= 0 else None),
                properties=self.props_json(i),
                event_time=from_millis(int(self.event_time[i])))

    @staticmethod
    def empty(dicts: Optional[ColumnarDicts] = None,
              float_props: Sequence[str] = ()) -> "ColumnarBatch":
        return ColumnarBatch(
            event=np.empty(0, np.int32), entity_type=np.empty(0, np.int32),
            entity_id=np.empty(0, np.int32),
            target_type=np.empty(0, np.int32),
            target_id=np.empty(0, np.int32),
            event_time=np.empty(0, np.int64),
            props_offsets=np.zeros(1, np.int64),
            props_blob=np.empty(0, np.uint8),
            float_props={k: _EMPTY_F64(0) for k in float_props},
            dicts=dicts or ColumnarDicts())

    @staticmethod
    def concat(batches: Sequence["ColumnarBatch"]) -> "ColumnarBatch":
        """Concatenate same-dict batches (segments of one log)."""
        batches = [b for b in batches if b.n > 0]
        if not batches:
            return ColumnarBatch.empty()
        if len(batches) == 1:
            return batches[0]
        d = batches[0].dicts
        prop_names = set()
        for b in batches:
            prop_names |= set(b.float_props)
        offs = [np.zeros(1, dtype=np.int64)]
        total = 0
        for b in batches:
            offs.append(b.props_offsets[1:] + total)
            total += int(b.props_offsets[-1])
        return ColumnarBatch(
            event=np.concatenate([b.event for b in batches]),
            entity_type=np.concatenate([b.entity_type for b in batches]),
            entity_id=np.concatenate([b.entity_id for b in batches]),
            target_type=np.concatenate([b.target_type for b in batches]),
            target_id=np.concatenate([b.target_id for b in batches]),
            event_time=np.concatenate([b.event_time for b in batches]),
            props_offsets=np.concatenate(offs),
            props_blob=np.concatenate([b.props_blob for b in batches]),
            float_props={k: np.concatenate([
                b.float_props.get(k, _EMPTY_F64(b.n)) for b in batches])
                for k in prop_names},
            dicts=d)


# ---------------------------------------------------------------------------
# Encoding
# ---------------------------------------------------------------------------

def columnar_from_columns(
        dicts: ColumnarDicts,
        event: Sequence[str],
        entity_type: Sequence[str],
        entity_id: Sequence[str],
        target_type: Sequence[Optional[str]],
        target_id: Sequence[Optional[str]],
        event_time_ms: np.ndarray,
        props_json: Optional[Sequence[Optional[str]]] = None,
        float_props: Sequence[str] = ("rating",),
        float_prop_values: Optional[Dict[str, np.ndarray]] = None,
) -> ColumnarBatch:
    """Encode already-columnar host data (the fast path backends use:
    one bulk dictionary-encode per column, no per-event Python objects).

    ``float_prop_values`` supplies pre-extracted numeric property columns
    (e.g. SQLite's ``json_extract`` pushdown); missing ones are parsed
    from ``props_json``.
    """
    n = len(event)
    times = np.ascontiguousarray(event_time_ms, dtype=np.int64)
    if props_json is None:
        offsets = np.zeros(n + 1, dtype=np.int64)
        blob = np.empty(0, dtype=np.uint8)
    else:
        # props may arrive as str or raw utf-8 bytes (bulk readers fetch
        # bytes to skip the per-row str decode)
        encoded = [(b"" if not p or p == "{}" or p == b"{}"
                    else p if isinstance(p, bytes)
                    else p.encode("utf-8")) for p in props_json]
        lens = np.fromiter((len(b) for b in encoded), dtype=np.int64,
                           count=n)
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        blob = (np.frombuffer(b"".join(encoded), dtype=np.uint8).copy()
                if int(offsets[-1]) else np.empty(0, dtype=np.uint8))
    fp: Dict[str, np.ndarray] = {}
    for name in float_props:
        if float_prop_values and name in float_prop_values:
            fp[name] = np.ascontiguousarray(float_prop_values[name],
                                            dtype=np.float64)
        else:
            fp[name] = None  # type: ignore[assignment]  # filled below
    batch = ColumnarBatch(
        event=dicts.event_names.encode(event),
        entity_type=dicts.entity_types.encode(entity_type),
        entity_id=dicts.entity_ids.encode(entity_id),
        target_type=dicts.target_types.encode(target_type),
        target_id=dicts.target_ids.encode(target_id),
        event_time=times, props_offsets=offsets, props_blob=blob,
        float_props={k: v for k, v in fp.items() if v is not None},
        dicts=dicts)
    for name in float_props:
        if name not in batch.float_props:
            batch.float_prop(name)  # parse from the blob once, cache
    return batch


def columnar_from_events(events: Iterable[Event],
                         dicts: Optional[ColumnarDicts] = None,
                         float_props: Sequence[str] = ("rating",),
                         ) -> ColumnarBatch:
    """Encode an event iterator (the correct-everywhere fallback path)."""
    dicts = dicts or ColumnarDicts()
    ev: List[str] = []
    et: List[str] = []
    ei: List[str] = []
    tt: List[Optional[str]] = []
    ti: List[Optional[str]] = []
    tms: List[int] = []
    pj: List[Optional[str]] = []
    for e in events:
        ev.append(e.event)
        et.append(e.entity_type)
        ei.append(e.entity_id)
        tt.append(e.target_entity_type)
        ti.append(e.target_entity_id)
        tms.append(e.event_time_millis)
        pj.append(e.properties.to_json() if len(e.properties) else None)
    return columnar_from_columns(
        dicts, ev, et, ei, tt, ti,
        np.asarray(tms, dtype=np.int64), pj, float_props=float_props)


# ---------------------------------------------------------------------------
# On-disk segment log (the persistent sidecar backends cache into)
# ---------------------------------------------------------------------------

_COLS = ("event", "entity_type", "entity_id", "target_type", "target_id",
         "event_time", "props_offsets", "props_blob")
_DICTS = ("event_names", "entity_types", "entity_ids", "target_types",
          "target_ids")


def batch_digest(batch: ColumnarBatch) -> str:
    """sha256 over every column's bytes — the per-delta term of the
    segment log's chained content stamp."""
    h = hashlib.sha256()
    h.update(str(batch.n).encode())
    cols = [batch.event, batch.entity_type, batch.entity_id,
            batch.target_type, batch.target_id, batch.event_time,
            batch.props_offsets, batch.props_blob]
    cols += [batch.float_props[k] for k in sorted(batch.float_props)]
    for arr in cols:
        a = np.asarray(arr, order="C")
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class SegmentLog:
    """Immutable columnar segments + manifest for one event log.

    ``FORMAT`` versions the ENCODED CONTENT: readers invalidate and
    re-encode sidecars written by older formats (v2: the event_time
    column of v1 segmentfs sidecars could carry epoch seconds — the
    pandas datetime64[us] ``asi8`` bug).

    Directory layout::

        <dir>/manifest.json        {"watermark": ..., "count": N,
                                    "float_props": [...], "segments": [...]}
        <dir>/dict_<name>.txt      newline-separated dictionary values
        <dir>/seg-<k>/<col>.npy    one numpy file per column (mmap-read)

    Appends are atomic: segment dir + dicts written first, the manifest
    (the commit point) replaced last. Readers mmap columns, so loading a
    20M-event log costs page-cache reads, not JSON parsing.
    """

    #: encoded-content format version (bump forces re-encode)
    FORMAT = 2

    def __init__(self, path: str):
        self.path = path

    def format_stale(self, manifest: Optional[dict]) -> bool:
        """True when ``manifest`` was written by an older format and
        must be invalidated + re-encoded."""
        return manifest is not None \
            and int(manifest.get("format", 1)) < self.FORMAT

    @contextlib.contextmanager
    def lock(self):
        """Cross-process exclusive lock over sidecar mutation (append /
        rebuild): two processes syncing the same delta must not interleave
        dict appends or claim the same segment name."""
        os.makedirs(self.path, exist_ok=True)
        if fcntl is None:
            yield
            return
        with open(os.path.join(self.path, ".lock"), "a") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)

    # -- manifest ----------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self.path, "manifest.json")

    def read_manifest(self) -> Optional[dict]:
        try:
            with open(self._manifest_path(), "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def _write_manifest(self, manifest: dict) -> None:
        tmp = self._manifest_path() + f".tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f)
        os.replace(tmp, self._manifest_path())

    # -- dicts -------------------------------------------------------------
    def _read_dicts(self) -> ColumnarDicts:
        d = ColumnarDicts()
        for name in _DICTS:
            p = os.path.join(self.path, f"dict_{name}.txt")
            if os.path.exists(p):
                with open(p, "r", encoding="utf-8") as f:
                    raw = f.read()
                values = raw.split("\n")[:-1] if raw else []
                # one JSON string per line: unambiguous for values
                # containing newlines/backslashes
                setattr(d, name, StringDict([json.loads(v)
                                             for v in values]))
        return d

    def _write_dicts(self, dicts: ColumnarDicts,
                     prev_counts: Dict[str, int]) -> None:
        """Append-only dict growth: only new values are written."""
        for name in _DICTS:
            sd: StringDict = getattr(dicts, name)
            start = prev_counts.get(name, 0)
            if len(sd) == start:
                continue
            p = os.path.join(self.path, f"dict_{name}.txt")
            with open(p, "a", encoding="utf-8") as f:
                for v in sd.values[start:]:
                    f.write(json.dumps(v) + "\n")

    # -- segments ----------------------------------------------------------
    def append(self, batch: ColumnarBatch, watermark,
               prev_dict_counts: Dict[str, int],
               seq_range: Optional[Tuple[int, int]] = None,
               has_props: bool = True,
               hash_impl: Optional[str] = None) -> None:
        """Write ``batch`` as a new segment and commit the manifest.

        ``has_props=False`` defers the property-byte columns: the
        training read never touches raw JSON, so the first encode can
        skip fetching/concatenating it entirely; a later props-needing
        reader upgrades the segment via :meth:`ensure_props` using the
        recorded ``seq_range`` (source-row half-open range ``(lo, hi]``
        in the backing store)."""
        os.makedirs(self.path, exist_ok=True)
        manifest = self.read_manifest() or {
            "count": 0, "segments": [], "float_props": [],
            "watermark": None, "format": self.FORMAT}
        # deliberately NO format backfill on existing manifests: blessing
        # a v1 manifest as current would permanently exempt its old
        # segments from the format_stale invalidation net — appends to a
        # stale-format sidecar stay stale and get rebuilt on next read
        # unique across GENERATIONS: after an invalidate with a grace
        # period, retired segment dirs coexist with the new generation's
        # (readers may still mmap them) — names must never collide
        seg_name = f"seg-{len(manifest['segments']):06d}-{uuid.uuid4().hex[:8]}"
        seg_dir = os.path.join(self.path, seg_name)
        os.makedirs(seg_dir, exist_ok=True)
        cols = _COLS if has_props else tuple(
            c for c in _COLS if not c.startswith("props_"))
        for col in cols:
            np.save(os.path.join(seg_dir, f"{col}.npy"),
                    getattr(batch, col), allow_pickle=False)
        for name, arr in batch.float_props.items():
            np.save(os.path.join(seg_dir, f"prop_{name}.npy"), arr,
                    allow_pickle=False)
        self._write_dicts(batch.dicts, prev_dict_counts)
        entry = {"name": seg_name, "n": batch.n, "props": bool(has_props)}
        if seq_range is not None:
            entry["seq"] = [int(seq_range[0]), int(seq_range[1])]
        manifest["segments"].append(entry)
        manifest["count"] += batch.n
        manifest["watermark"] = watermark
        # incremental content stamp: chain the delta digest onto the
        # previous stamp — O(delta) per append, so ETag computation never
        # re-hashes the full log (the former full-bytes sha256 made every
        # poll after every append an O(total) scan, quadratic over the
        # life of the log). Segments are immutable, so the chain value is
        # a faithful stand-in for the full-content hash.
        manifest["stamp"] = hashlib.sha256(
            (manifest.get("stamp", "") + batch_digest(batch))
            .encode()).hexdigest()[:32]
        manifest["float_props"] = sorted(
            set(manifest["float_props"]) | set(batch.float_props))
        if hash_impl is not None:
            # writers that store id-hash columns beside segments record
            # their bulk_hash64 implementation; readers on a different
            # stack rebuild instead of dup-checking against hashes that
            # can never match (segmentfs pod sidecars)
            manifest["hash_impl"] = hash_impl
        self._write_manifest(manifest)

    def ensure_props(self, fetch) -> None:
        """Upgrade props-deferred segments in place: ``fetch(lo, hi, n)``
        must return ``(props_offsets [n+1] int64, props_blob uint8)`` for
        the segment's recorded source range. Call under :meth:`lock`."""
        manifest = self.read_manifest()
        if manifest is None:
            return
        changed = False
        for seg in manifest["segments"]:
            if seg.get("props", True):
                continue
            lo, hi = seg["seq"]
            offs, blob = fetch(lo, hi, seg["n"])
            seg_dir = os.path.join(self.path, seg["name"])
            np.save(os.path.join(seg_dir, "props_offsets.npy"), offs,
                    allow_pickle=False)
            np.save(os.path.join(seg_dir, "props_blob.npy"), blob,
                    allow_pickle=False)
            seg["props"] = True
            changed = True
        if changed:
            self._write_manifest(manifest)

    #: canonical dtypes of the core columns — segment reads land on these
    #: regardless of what an older writer put on disk (dtype-stable
    #: decoding: a stray int64 code column cannot poison jax feeds)
    _CORE_DTYPES = (("event", np.int32), ("entity_type", np.int32),
                    ("entity_id", np.int32), ("target_type", np.int32),
                    ("target_id", np.int32), ("event_time", np.int64))

    def load(self, mmap: bool = True, with_props: bool = True
             ) -> Tuple[Optional[ColumnarBatch], Optional[dict]]:
        """(batch, manifest) — a single-segment log mmaps its files in
        place; a multi-segment log decodes into contiguous preallocated
        column buffers with segment ``k+1`` read by a prefetch thread
        while ``k`` lands (overlapping fetch with decode, the analyzed
        dataloader discipline of arXiv 2005.04680): one allocation per
        column at the final size instead of per-segment arrays plus an
        O(total) concat copy.

        ``with_props=False`` skips the property-byte columns (and is the
        only valid mode while any segment is still props-deferred —
        callers wanting props run :meth:`ensure_props` first)."""
        manifest = self.read_manifest()
        if manifest is None:
            return None, None
        dicts = self._read_dicts()
        segs = manifest["segments"]
        for seg in segs:
            if with_props and not seg.get("props", True):
                raise RuntimeError(
                    f"segment {seg['name']} is props-deferred; call "
                    f"ensure_props() before load(with_props=True)")
        if not segs:
            return ColumnarBatch.empty(dicts), manifest
        if len(segs) == 1:
            seg_dir = os.path.join(self.path, segs[0]["name"])
            mode = "r" if mmap else None

            def col(name: str) -> np.ndarray:
                return np.load(os.path.join(seg_dir, f"{name}.npy"),
                               mmap_mode=mode, allow_pickle=False)

            return ColumnarBatch(
                event=col("event"), entity_type=col("entity_type"),
                entity_id=col("entity_id"), target_type=col("target_type"),
                target_id=col("target_id"), event_time=col("event_time"),
                props_offsets=(col("props_offsets") if with_props
                               else np.zeros(segs[0]["n"] + 1, np.int64)),
                props_blob=(col("props_blob") if with_props
                            else np.empty(0, np.uint8)),
                float_props={name: col(f"prop_{name}")
                             for name in manifest["float_props"]
                             if os.path.exists(os.path.join(
                                 seg_dir, f"prop_{name}.npy"))},
                dicts=dicts), manifest
        return self._load_contiguous(manifest, dicts, with_props), manifest

    def _load_contiguous(self, manifest: dict, dicts: ColumnarDicts,
                         with_props: bool) -> ColumnarBatch:
        segs = manifest["segments"]
        fp_names = list(manifest["float_props"])
        total = int(sum(s["n"] for s in segs))
        dest = {name: np.empty(total, dt) for name, dt in self._CORE_DTYPES}
        if with_props:
            # per-segment blob sizes from the npy headers only: an mmap
            # open touches the header page, never the data pages
            blob_total = sum(
                int(np.load(os.path.join(self.path, s["name"],
                                         "props_blob.npy"),
                            mmap_mode="r", allow_pickle=False).shape[0])
                for s in segs)
            props_offsets = np.empty(total + 1, np.int64)
            props_offsets[0] = 0
            props_blob = np.empty(blob_total, np.uint8)
        else:
            props_offsets = np.zeros(total + 1, np.int64)
            props_blob = np.empty(0, np.uint8)
        fp = {k: _EMPTY_F64(total) for k in fp_names}

        def read_segment(seg: dict) -> dict:
            seg_dir = os.path.join(self.path, seg["name"])
            out = {name: np.load(os.path.join(seg_dir, f"{name}.npy"),
                                 allow_pickle=False)
                   for name, _ in self._CORE_DTYPES}
            if with_props:
                for name in ("props_offsets", "props_blob"):
                    out[name] = np.load(
                        os.path.join(seg_dir, f"{name}.npy"),
                        allow_pickle=False)
            for name in fp_names:
                p = os.path.join(seg_dir, f"prop_{name}.npy")
                if os.path.exists(p):
                    out[f"prop_{name}"] = np.load(p, allow_pickle=False)
            return out

        # maxsize=2 bounds read-ahead to segment k+1 while k decodes
        q: queue.Queue = queue.Queue(maxsize=2)

        def producer() -> None:
            try:
                for i, seg in enumerate(segs):
                    q.put((i, read_segment(seg)))
            except BaseException as e:  # surfaced on the consumer side
                q.put((-1, e))

        t = threading.Thread(target=producer, daemon=True,
                             name="segmentlog-prefetch")
        t.start()
        row = blob_base = 0
        for _ in range(len(segs)):
            i, arrs = q.get()
            if i < 0:
                raise arrs
            n = int(segs[i]["n"])
            for name, _ in self._CORE_DTYPES:
                dest[name][row:row + n] = arrs[name]
            if with_props:
                offs = arrs["props_offsets"]
                props_offsets[row:row + n + 1] = offs + blob_base
                blen = int(offs[-1])
                props_blob[blob_base:blob_base + blen] = arrs["props_blob"]
                blob_base += blen
            for name in fp_names:
                a = arrs.get(f"prop_{name}")
                if a is not None:
                    fp[name][row:row + n] = a
            row += n
        t.join()
        return ColumnarBatch(
            event=dest["event"], entity_type=dest["entity_type"],
            entity_id=dest["entity_id"], target_type=dest["target_type"],
            target_id=dest["target_id"], event_time=dest["event_time"],
            props_offsets=props_offsets, props_blob=props_blob,
            float_props=fp, dicts=dicts)

    def dicts_and_counts(self) -> Tuple[ColumnarDicts, Dict[str, int]]:
        d = self._read_dicts()
        return d, d.counts()

    def invalidate(self, grace_s: float = 0.0) -> None:
        """Drop the sidecar's contents (deletes/compaction changed
        history). The manifest — the commit point — goes first; the
        ``.lock`` file stays so waiters keep a valid inode.

        ``grace_s > 0`` RETIRES segment directories instead of deleting
        them: on a shared filesystem another host may still hold live
        mmaps of these files (NFS gives no unlink-keeps-inode guarantee),
        so they stay until :meth:`sweep` finds them idle past the grace
        window — the same reader-grace invariant the jsonl log keeps."""
        import shutil
        if not os.path.isdir(self.path):
            return
        with contextlib.suppress(OSError):
            os.remove(self._manifest_path())
        now = time.time()
        for name in os.listdir(self.path):
            if name == ".lock":
                continue
            p = os.path.join(self.path, name)
            if grace_s > 0 and name.startswith("seg-") \
                    and os.path.isdir(p):
                # restart the grace clock from retirement, not creation
                with contextlib.suppress(OSError):
                    os.utime(p, (now, now))
                continue
            with contextlib.suppress(OSError):
                shutil.rmtree(p) if os.path.isdir(p) else os.remove(p)

    def sweep(self, grace_s: float) -> int:
        """Delete retired (unreferenced) segment dirs idle ≥ ``grace_s``.
        Call under :meth:`lock`."""
        import shutil
        if not os.path.isdir(self.path):
            return 0
        manifest = self.read_manifest()
        referenced = {s["name"]
                      for s in (manifest or {}).get("segments", ())}
        n = 0
        now = time.time()
        for name in os.listdir(self.path):
            if not name.startswith("seg-") or name in referenced:
                continue
            p = os.path.join(self.path, name)
            try:
                if os.path.isdir(p) \
                        and now - os.path.getmtime(p) >= grace_s:
                    shutil.rmtree(p)
                    n += 1
            except OSError:
                pass
        return n
