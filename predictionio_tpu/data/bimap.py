"""Bidirectional maps for id indexation.

Capability parity with the reference's ``BiMap``
(``data/src/main/scala/org/apache/predictionio/data/storage/BiMap.scala:28,105-126``):
templates use ``BiMap.stringInt`` to index string entity ids into dense
integer ids before building matrices. On TPU this is the bridge from the
string-keyed event log to dense row indices of sharded factor matrices, so
``string_int`` here returns ids that are stable, dense, and 0-based —
exactly what a ``jax.Array`` row index needs.
"""

from __future__ import annotations

from typing import Dict, Generic, Iterable, Mapping, Sequence, TypeVar

import numpy as np

K = TypeVar("K")
V = TypeVar("V")


class BiMap(Generic[K, V]):
    """Immutable one-to-one mapping with O(1) forward and inverse lookup."""

    def __init__(self, forward: Mapping[K, V]):
        self._fwd: Dict[K, V] = dict(forward)
        if len(set(self._fwd.values())) != len(self._fwd):
            raise ValueError("BiMap values must be unique")
        self._rev: Dict[V, K] = {v: k for k, v in self._fwd.items()}

    def __getitem__(self, k: K) -> V:
        return self._fwd[k]

    def get(self, k: K, default=None):
        return self._fwd.get(k, default)

    def __contains__(self, k: K) -> bool:
        return k in self._fwd

    def __len__(self) -> int:
        return len(self._fwd)

    def __iter__(self):
        return iter(self._fwd)

    def items(self):
        return self._fwd.items()

    def keys(self):
        return self._fwd.keys()

    def values(self):
        return self._fwd.values()

    @property
    def inverse(self) -> "BiMap[V, K]":
        """The inverted map (reference ``BiMap.inverse``)."""
        inv = BiMap.__new__(BiMap)
        inv._fwd = self._rev
        inv._rev = self._fwd
        return inv

    def take(self, keys: Iterable[K]) -> "BiMap[K, V]":
        return BiMap({k: self._fwd[k] for k in keys if k in self._fwd})

    def to_dict(self) -> Dict[K, V]:
        return dict(self._fwd)

    # -- constructors (reference BiMap.stringInt / stringLong / stringDouble)
    @staticmethod
    def string_int(keys: Iterable[str]) -> "BiMap[str, int]":
        """Dense 0-based int ids in first-seen order over unique keys."""
        fwd: Dict[str, int] = {}
        for k in keys:
            if k not in fwd:
                fwd[k] = len(fwd)
        return BiMap(fwd)

    string_long = string_int

    def map_array(self, keys: Sequence[K], missing: int = -1) -> np.ndarray:
        """Vectorized lookup of many keys → int64 array; absent keys map to
        ``missing``. Host-side precursor to device transfer."""
        return np.fromiter((self._fwd.get(k, missing) for k in keys),
                           dtype=np.int64, count=len(keys))
