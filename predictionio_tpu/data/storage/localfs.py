"""LOCALFS storage backend: JSON-lines event logs + JSON metadata files.

The role of the reference's LocalFS/HDFS backends
(``storage/localfs/``, ``storage/hdfs/`` — model blobs on a filesystem)
extended to a full backend: the event log is an append-only JSONL file
per (app, channel) — the natural on-disk shape of PredictionIO's
append-only event model — metadata repositories are small JSON documents
rewritten atomically, and model blobs are plain files.

Suited to single-host dev/offline-training setups; the SQLite backend
remains the default for concurrent serving. Deletes append tombstone
records; ``remove`` drops the whole log. Readers replay the log (events
are immutable, so a replay is exact).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import uuid

try:
    import fcntl
except ImportError:  # non-POSIX: fall back to the per-process lock only
    fcntl = None  # type: ignore[assignment]


@contextlib.contextmanager
def _flock(path: str):
    """OS-level exclusive lock on ``path``'s sidecar lockfile, covering
    cross-process appenders (e.g. a separately running eventserver in the
    quickstart topology) that the per-process RLock cannot see."""
    if fcntl is None:
        yield
        return
    with open(f"{path}.lock", "a") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(lf, fcntl.LOCK_UN)
from datetime import datetime
from typing import Dict, Iterator, List, Optional, Sequence

from ..event import Event
from .base import (
    AccessKey,
    AccessKeysDAO,
    App,
    AppsDAO,
    Channel,
    ChannelsDAO,
    EngineInstance,
    EngineInstancesDAO,
    EvaluationInstance,
    EvaluationInstancesDAO,
    EventFilter,
    EventStore,
    Model,
    ModelsDAO,
)


def atomic_write(path: str, data, fsync: bool = True) -> None:
    """Write-temp + rename publish: readers (on any host) see either the
    old content or the new, never a torn file. ``data`` is str or bytes.
    The one copy of a pattern that had grown four hand-rolled variants."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    mode = "wb" if isinstance(data, (bytes, bytearray)) else "w"
    kwargs = {} if "b" in mode else {"encoding": "utf-8"}
    with open(tmp, mode, **kwargs) as f:
        f.write(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)


class LocalFSClient:
    """Owns the root directory + a process-wide mutation lock."""

    def __init__(self, path: str):
        self.root = path
        os.makedirs(path, exist_ok=True)
        os.makedirs(os.path.join(path, "models"), exist_ok=True)
        self.lock = threading.RLock()
        #: per-log replay cache: path → (file size at replay, live events,
        #: dead-record count). Size mismatch (another process appended)
        #: invalidates the entry.
        self.event_cache: Dict[str, tuple] = {}

    @staticmethod
    def from_config(cfg: dict) -> "LocalFSClient":
        path = cfg.get("PATH") or os.path.join(
            os.environ.get("PIO_HOME", "."), "localfs")
        return LocalFSClient(path)

    def close(self) -> None:
        pass

    # -- small-document helpers (metadata repositories) --------------------
    def doc_path(self, name: str) -> str:
        return os.path.join(self.root, f"{name}.json")

    def read_doc(self, name: str, default):
        path = self.doc_path(name)
        if not os.path.exists(path):
            return default
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)

    def write_doc(self, name: str, value) -> None:
        atomic_write(self.doc_path(name), json.dumps(value))

    def next_seq(self, name: str) -> int:
        """Monotonic id sequence per entity kind — deleted rows never free
        their ids (matches the memory/sqlite backends; prevents a new app
        inheriting a dead app's event log)."""
        doc = f"{name}_seq"
        n = int(self.read_doc(doc, 0)) + 1
        self.write_doc(doc, n)
        return n


def _log_name(app_id: int, channel_id: Optional[int]) -> str:
    suffix = f"_{channel_id}" if channel_id is not None else ""
    return f"events_{app_id}{suffix}.jsonl"


class LocalFSEventStore(EventStore):
    def __init__(self, client: LocalFSClient):
        self.c = client

    def _path(self, app_id: int, channel_id: Optional[int]) -> str:
        return os.path.join(self.c.root, _log_name(app_id, channel_id))

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self.c.lock:
            path = self._path(app_id, channel_id)
            if not os.path.exists(path):
                open(path, "a", encoding="utf-8").close()
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self.c.lock:
            path = self._path(app_id, channel_id)
            self.c.event_cache.pop(path, None)
            if os.path.exists(path):
                # the .lock sidecar is deliberately left in place: unlinking
                # it would let a process blocked on the old inode and a new
                # process that re-creates the file both hold an "exclusive"
                # lock at once
                with _flock(path):
                    os.remove(path)
                return True
        return False

    def close(self) -> None:
        pass

    def _append(self, path: str, records: List[dict],
                expected_size: Optional[int] = None) -> Optional[int]:
        """Append records under the cross-process lock. When
        ``expected_size`` is given (the size our replay cache is based on)
        and another process appended in between, returns None — the caller
        must invalidate its cache instead of publishing a live-set that
        silently misses the other process's events.

        The whole payload goes through ONE ``write`` call: a crashed
        writer leaves at most one torn trailing line (which replay
        detects and truncates), never a valid prefix of a multi-record
        append."""
        with _flock(path):
            clean = True
            if expected_size is not None:
                current = os.path.getsize(path) if os.path.exists(path) \
                    else -1
                if current < 0:
                    current = 0  # about to be created by the append
                clean = current == max(expected_size, 0)
            with open(path, "a", encoding="utf-8") as f:
                f.write("".join(json.dumps(r) + "\n" for r in records))
                f.flush()
                return f.tell() if clean else None

    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(self, events: Sequence[Event], app_id: int,
                     channel_id: Optional[int] = None) -> List[str]:
        with self.c.lock:
            path = self._path(app_id, channel_id)
            live, dead = self._state(path)
            cached = self.c.event_cache.get(path)
            prior_size = cached[0] if cached is not None else -1
            ids, stored_events = [], []
            for e in events:
                eid = e.event_id or uuid.uuid4().hex
                stored = e.copy(event_id=eid)
                stored_events.append(stored)
                ids.append(eid)
            # ONE "putb" record per batch = one log line = one write
            # call: a process killed mid-insert leaves the batch fully
            # present or (as a truncated torn tail) fully absent —
            # never a committed prefix of fresh ids (the all-or-nothing
            # insert_batch contract under crashes, not just exceptions)
            records = [{"op": "putb",
                        "events": [s.to_json() for s in stored_events]}] \
                if len(stored_events) > 1 else \
                [{"op": "put", "event": stored_events[0].to_json()}] \
                if stored_events else []
            # disk first: a failed append must not leave ghost events in
            # the cache
            size = self._append(path, records, expected_size=prior_size)
            if size is None:
                # another process appended between our replay and this
                # append: drop the cache so the next read replays the file
                # instead of serving a live-set missing their events
                self.c.event_cache.pop(path, None)
            else:
                for stored in stored_events:
                    live[stored.event_id] = stored
                self.c.event_cache[path] = (size, live, dead)
            return ids

    def _state(self, path: str, deadline: Optional[float] = None):
        """(live events by id, dead-record count), replayed at most once
        per on-disk file state. Compacts the log when tombstoned/overwritten
        records outnumber live ones. ``deadline`` (monotonic) bounds a
        serving-time replay; insert/delete paths never pass one."""
        cached = self.c.event_cache.get(path)
        size = os.path.getsize(path) if os.path.exists(path) else -1
        if cached is not None and cached[0] == size:
            return cached[1], cached[2]
        import time as _time
        out: Dict[str, Event] = {}
        dead = 0

        def apply(rec: dict) -> int:
            """Replay one record; returns dead-record delta."""
            d = 0
            if rec["op"] == "put":
                e = Event.from_json(rec["event"])
                if e.event_id in out:
                    d += 1
                out[e.event_id] = e
            elif rec["op"] == "putb":  # atomic batch (one line)
                for doc in rec["events"]:
                    e = Event.from_json(doc)
                    if e.event_id in out:
                        d += 1
                    out[e.event_id] = e
            elif rec["op"] == "del":
                if out.pop(rec["eventId"], None) is not None:
                    d += 2  # the put and the tombstone
                else:
                    d += 1
            return d

        if size >= 0:
            # flock against cross-process writers: without it a reader can
            # see a torn trailing record mid-flush and crash on json.loads
            with _flock(path), open(path, "rb") as f:
                size = os.path.getsize(path)  # re-stat now that we hold it
                offset = 0
                truncate_to = None
                needs_newline = False
                ln = 0
                while True:
                    line = f.readline()  # streamed, never the whole file
                    if not line:
                        break
                    ln += 1
                    if deadline is not None and ln % 4096 == 0 \
                            and _time.monotonic() > deadline:
                        raise TimeoutError(
                            "event-log replay exceeded its deadline")
                    has_nl = line.endswith(b"\n")
                    s = line.strip()
                    if s:
                        try:
                            rec = json.loads(s)
                        except (json.JSONDecodeError,
                                UnicodeDecodeError):
                            # UnicodeDecodeError: the tear landed inside
                            # a multi-byte UTF-8 character — same torn-
                            # writer residue, different exception
                            if not has_nl:
                                # newline-less torn trailing line — the
                                # residue of a writer killed mid-append
                                # (the newline is the LAST byte of every
                                # committed append, so a record whose
                                # newline landed can never be torn-
                                # writer residue). Drop it AND truncate,
                                # or the next append would concatenate
                                # onto the partial line and corrupt the
                                # log permanently.
                                truncate_to = offset
                                break
                            raise  # committed-line corruption: surface
                        dead += apply(rec)
                        if not has_nl:
                            # parsed fine but the newline never landed:
                            # patch it so the next append starts fresh
                            needs_newline = True
                    offset += len(line)
                if truncate_to is not None:
                    with open(path, "r+b") as wf:
                        wf.truncate(truncate_to)
                    size = truncate_to
                elif needs_newline:
                    with open(path, "ab") as wf:
                        wf.write(b"\n")
                    size += 1
        if dead > max(len(out), 16):
            compacted = self._compact(path, out, size)
            if compacted is not None:
                size, dead = compacted
        self.c.event_cache[path] = (size, out, dead)
        return out, dead

    def _compact(self, path: str, live: Dict[str, Event],
                 replayed_size: int) -> Optional[tuple]:
        """Rewrite the log with only live records (atomic replace). Holds
        the cross-process lock and re-stats the log first: if another
        process appended since our replay, skip — replacing from a stale
        snapshot would silently drop their events."""
        with _flock(path):
            current = os.path.getsize(path) if os.path.exists(path) else -1
            if current != replayed_size:
                return None
            tmp = f"{path}.compact.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                for e in live.values():
                    f.write(json.dumps({"op": "put", "event": e.to_json()})
                            + "\n")
                f.flush()
                size = f.tell()
            os.replace(tmp, path)
            return size, 0

    def _replay(self, app_id: int, channel_id: Optional[int],
                deadline: Optional[float] = None) -> Dict[str, Event]:
        return self._state(self._path(app_id, channel_id), deadline)[0]

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        with self.c.lock:
            return self._replay(app_id, channel_id).get(event_id)

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        with self.c.lock:
            path = self._path(app_id, channel_id)
            live, dead = self._state(path)
            if event_id not in live:
                return False
            cached = self.c.event_cache.get(path)
            prior_size = cached[0] if cached is not None else -1
            size = self._append(path, [{"op": "del", "eventId": event_id}],
                                expected_size=prior_size)
            if size is None:
                self.c.event_cache.pop(path, None)
            else:
                live.pop(event_id)
                self.c.event_cache[path] = (size, live, dead + 2)
            return True

    def find(self, app_id: int, channel_id: Optional[int] = None,
             filter: EventFilter = EventFilter()) -> Iterator[Event]:
        with self.c.lock:
            events = list(self._replay(app_id, channel_id,
                                       filter.deadline).values())
        events = list(filter.apply(events))
        events.sort(key=lambda e: e.event_time_millis,
                    reverse=filter.reversed)
        if filter.limit is not None and filter.limit >= 0:
            events = events[: filter.limit]
        return iter(events)


class LocalFSApps(AppsDAO):
    DOC = "apps"

    def __init__(self, client: LocalFSClient):
        self.c = client

    def _load(self) -> List[App]:
        return [App(**a) for a in self.c.read_doc(self.DOC, [])]

    def _store(self, apps: List[App]) -> None:
        self.c.write_doc(self.DOC, [
            {"id": a.id, "name": a.name, "description": a.description}
            for a in apps])

    def insert(self, app: App) -> Optional[int]:
        with self.c.lock:
            apps = self._load()
            if any(a.name == app.name for a in apps):
                return None
            app_id = app.id
            if app_id == 0:
                app_id = self.c.next_seq("apps")
            elif any(a.id == app_id for a in apps):
                return None
            apps.append(App(id=app_id, name=app.name,
                            description=app.description))
            self._store(apps)
            return app_id

    def get(self, app_id: int) -> Optional[App]:
        return next((a for a in self._load() if a.id == app_id), None)

    def get_by_name(self, name: str) -> Optional[App]:
        return next((a for a in self._load() if a.name == name), None)

    def get_all(self) -> List[App]:
        return self._load()

    def update(self, app: App) -> None:
        with self.c.lock:
            self._store([app if a.id == app.id else a
                         for a in self._load()])

    def delete(self, app_id: int) -> None:
        with self.c.lock:
            self._store([a for a in self._load() if a.id != app_id])


class LocalFSAccessKeys(AccessKeysDAO):
    DOC = "access_keys"

    def __init__(self, client: LocalFSClient):
        self.c = client

    def _load(self) -> List[AccessKey]:
        return [AccessKey(key=k["key"], app_id=k["appId"],
                          events=tuple(k["events"]))
                for k in self.c.read_doc(self.DOC, [])]

    def _store(self, keys: List[AccessKey]) -> None:
        self.c.write_doc(self.DOC, [
            {"key": k.key, "appId": k.app_id, "events": list(k.events)}
            for k in keys])

    def insert(self, access_key: AccessKey) -> Optional[str]:
        with self.c.lock:
            keys = self._load()
            key = access_key.key or self.generate_key()
            if any(k.key == key for k in keys):
                return None
            keys.append(AccessKey(key=key, app_id=access_key.app_id,
                                  events=tuple(access_key.events)))
            self._store(keys)
            return key

    def get(self, key: str) -> Optional[AccessKey]:
        return next((k for k in self._load() if k.key == key), None)

    def get_all(self) -> List[AccessKey]:
        return self._load()

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [k for k in self._load() if k.app_id == app_id]

    def update(self, access_key: AccessKey) -> None:
        with self.c.lock:
            self._store([access_key if k.key == access_key.key else k
                         for k in self._load()])

    def delete(self, key: str) -> None:
        with self.c.lock:
            self._store([k for k in self._load() if k.key != key])


class LocalFSChannels(ChannelsDAO):
    DOC = "channels"

    def __init__(self, client: LocalFSClient):
        self.c = client

    def _load(self) -> List[Channel]:
        return [Channel(id=ch["id"], name=ch["name"], app_id=ch["appId"])
                for ch in self.c.read_doc(self.DOC, [])]

    def _store(self, chans: List[Channel]) -> None:
        self.c.write_doc(self.DOC, [
            {"id": ch.id, "name": ch.name, "appId": ch.app_id}
            for ch in chans])

    def insert(self, channel: Channel) -> Optional[int]:
        if not Channel.is_valid_name(channel.name):
            return None
        with self.c.lock:
            chans = self._load()
            cid = channel.id or self.c.next_seq("channels")
            if any(c.id == cid for c in chans):
                return None
            chans.append(Channel(id=cid, name=channel.name,
                                 app_id=channel.app_id))
            self._store(chans)
            return cid

    def get(self, channel_id: int) -> Optional[Channel]:
        return next((c for c in self._load() if c.id == channel_id), None)

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        return [c for c in self._load() if c.app_id == app_id]

    def delete(self, channel_id: int) -> None:
        with self.c.lock:
            self._store([c for c in self._load() if c.id != channel_id])


def _dt(s: str) -> datetime:
    return datetime.fromisoformat(s)


class LocalFSEngineInstances(EngineInstancesDAO):
    DOC = "engine_instances"

    def __init__(self, client: LocalFSClient):
        self.c = client

    def _load(self) -> List[EngineInstance]:
        out = []
        for d in self.c.read_doc(self.DOC, []):
            d = dict(d)
            d["start_time"] = _dt(d["start_time"])
            d["end_time"] = _dt(d["end_time"])
            out.append(EngineInstance(**d))
        return out

    def _store(self, instances: List[EngineInstance]) -> None:
        docs = []
        for i in instances:
            d = {
                "id": i.id, "status": i.status,
                "start_time": i.start_time.isoformat(),
                "end_time": i.end_time.isoformat(),
                "engine_id": i.engine_id,
                "engine_version": i.engine_version,
                "engine_variant": i.engine_variant,
                "engine_factory": i.engine_factory, "batch": i.batch,
                "env": dict(i.env), "spark_conf": dict(i.spark_conf),
                "data_source_params": i.data_source_params,
                "preparator_params": i.preparator_params,
                "algorithms_params": i.algorithms_params,
                "serving_params": i.serving_params,
            }
            docs.append(d)
        self.c.write_doc(self.DOC, docs)

    def insert(self, instance: EngineInstance) -> str:
        with self.c.lock:
            instances = self._load()
            iid = instance.id or uuid.uuid4().hex
            instances.append(instance.copy(id=iid))
            self._store(instances)
            return iid

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        return next((i for i in self._load() if i.id == instance_id), None)

    def get_all(self) -> List[EngineInstance]:
        return self._load()

    def get_completed(self, engine_id: str, engine_version: str,
                      engine_variant: str) -> List[EngineInstance]:
        from .base import STATUS_COMPLETED
        return sorted(
            (i for i in self._load()
             if i.status == STATUS_COMPLETED and i.engine_id == engine_id
             and i.engine_version == engine_version
             and i.engine_variant == engine_variant),
            key=lambda i: i.start_time, reverse=True)

    def update(self, instance: EngineInstance) -> None:
        with self.c.lock:
            self._store([instance if i.id == instance.id else i
                         for i in self._load()])

    def delete(self, instance_id: str) -> None:
        with self.c.lock:
            self._store([i for i in self._load() if i.id != instance_id])


class LocalFSEvaluationInstances(EvaluationInstancesDAO):
    DOC = "evaluation_instances"

    def __init__(self, client: LocalFSClient):
        self.c = client

    def _load(self) -> List[EvaluationInstance]:
        out = []
        for d in self.c.read_doc(self.DOC, []):
            d = dict(d)
            d["start_time"] = _dt(d["start_time"])
            d["end_time"] = _dt(d["end_time"])
            out.append(EvaluationInstance(**d))
        return out

    def _store(self, instances: List[EvaluationInstance]) -> None:
        self.c.write_doc(self.DOC, [
            {"id": i.id, "status": i.status,
             "start_time": i.start_time.isoformat(),
             "end_time": i.end_time.isoformat(),
             "evaluation_class": i.evaluation_class,
             "engine_params_generator_class":
                 i.engine_params_generator_class,
             "batch": i.batch, "env": dict(i.env),
             "spark_conf": dict(i.spark_conf),
             "evaluator_results": i.evaluator_results,
             "evaluator_results_html": i.evaluator_results_html,
             "evaluator_results_json": i.evaluator_results_json}
            for i in instances])

    def insert(self, instance: EvaluationInstance) -> str:
        with self.c.lock:
            instances = self._load()
            iid = instance.id or uuid.uuid4().hex
            instances.append(instance.copy(id=iid))
            self._store(instances)
            return iid

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        return next((i for i in self._load() if i.id == instance_id), None)

    def get_all(self) -> List[EvaluationInstance]:
        return self._load()

    def get_completed(self) -> List[EvaluationInstance]:
        from .base import STATUS_EVALCOMPLETED
        return sorted((i for i in self._load()
                       if i.status == STATUS_EVALCOMPLETED),
                      key=lambda i: i.start_time, reverse=True)

    def update(self, instance: EvaluationInstance) -> None:
        with self.c.lock:
            self._store([instance if i.id == instance.id else i
                         for i in self._load()])

    def delete(self, instance_id: str) -> None:
        with self.c.lock:
            self._store([i for i in self._load() if i.id != instance_id])


class LocalFSModels(ModelsDAO):
    def __init__(self, client: LocalFSClient):
        self.c = client

    def _path(self, model_id: str) -> str:
        return os.path.join(self.c.root, "models", f"{model_id}.bin")

    def insert(self, model: Model) -> None:
        with self.c.lock:
            # a reader on another host/process must never see a
            # truncated model blob mid-write
            atomic_write(self._path(model.id), model.models)

    def get(self, model_id: str) -> Optional[Model]:
        path = self._path(model_id)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return Model(id=model_id, models=f.read())

    def delete(self, model_id: str) -> None:
        with self.c.lock:
            path = self._path(model_id)
            if os.path.exists(path):
                os.remove(path)
