"""Storage registry: env-var-driven backend bootstrap.

Capability parity with the reference's ``Storage`` object
(``data/.../storage/Storage.scala:146-466``): configuration comes from
``PIO_STORAGE_SOURCES_<NAME>_TYPE`` (+ per-source keys) and
``PIO_STORAGE_REPOSITORIES_{METADATA,EVENTDATA,MODELDATA}_{NAME,SOURCE}``
(env parse at :158-228), with accessors per repository and a
``verify_all_data_objects`` smoke check (:372-394) used by ``pio status``.

Where the reference discovered backends reflectively by classname
convention (:310-337), this registry is an explicit type→factory table —
same pluggability (register_backend), no classpath scanning.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Mapping, Optional

from .base import (
    AccessKeysDAO,
    AppsDAO,
    ChannelsDAO,
    EngineInstancesDAO,
    EvaluationInstancesDAO,
    EventStore,
    ModelsDAO,
    StorageError,
)

REPOSITORIES = ("METADATA", "EVENTDATA", "MODELDATA")

_DAO_NAMES = ("events", "apps", "access_keys", "channels",
              "engine_instances", "evaluation_instances", "models")


@dataclass
class Backend:
    """Factory bundle for one storage source type."""

    make_client: Callable[[dict], object]
    daos: Dict[str, Callable[[object], object]] = field(default_factory=dict)
    close: Callable[[object], None] = lambda c: None


_BACKENDS: Dict[str, Backend] = {}


def register_backend(type_name: str, backend: Backend) -> None:
    _BACKENDS[type_name.upper()] = backend


def _register_builtins() -> None:
    from . import memory, sqlite

    register_backend("MEMORY", Backend(
        make_client=lambda cfg: memory,
        daos={
            "events": lambda c: memory.MemoryEventStore(),
            "apps": lambda c: memory.MemoryApps(),
            "access_keys": lambda c: memory.MemoryAccessKeys(),
            "channels": lambda c: memory.MemoryChannels(),
            "engine_instances": lambda c: memory.MemoryEngineInstances(),
            "evaluation_instances": lambda c: memory.MemoryEvaluationInstances(),
            "models": lambda c: memory.MemoryModels(),
        }))

    register_backend("SQLITE", Backend(
        make_client=lambda cfg: sqlite.SQLiteClient.from_config(cfg),
        daos={
            "events": lambda c: sqlite.SQLiteEventStore(c),
            "apps": lambda c: sqlite.SQLiteApps(c),
            "access_keys": lambda c: sqlite.SQLiteAccessKeys(c),
            "channels": lambda c: sqlite.SQLiteChannels(c),
            "engine_instances": lambda c: sqlite.SQLiteEngineInstances(c),
            "evaluation_instances": lambda c: sqlite.SQLiteEvaluationInstances(c),
            "models": lambda c: sqlite.SQLiteModels(c),
        },
        close=lambda c: c.close()))

    from . import localfs

    from . import segmentfs

    register_backend("SEGMENTFS", Backend(
        make_client=lambda cfg: segmentfs.SegmentFSClient.from_config(cfg),
        daos={
            "events": lambda c: segmentfs.SegmentFSEventStore(c),
            "apps": lambda c: segmentfs.SegmentFSApps(c),
            "access_keys": lambda c: segmentfs.SegmentFSAccessKeys(c),
            "channels": lambda c: segmentfs.SegmentFSChannels(c),
            "engine_instances":
                lambda c: segmentfs.SegmentFSEngineInstances(c),
            "evaluation_instances":
                lambda c: segmentfs.SegmentFSEvaluationInstances(c),
            "models": lambda c: segmentfs.SegmentFSModels(c),
        },
        close=lambda c: c.close()))

    from . import remote

    register_backend("REMOTE", Backend(
        make_client=lambda cfg: remote.RemoteClient.from_config(cfg),
        daos={
            "events": lambda c: remote.RemoteEventStore(c),
            "apps": lambda c: remote.RemoteApps(c),
            "access_keys": lambda c: remote.RemoteAccessKeys(c),
            "channels": lambda c: remote.RemoteChannels(c),
            "engine_instances": lambda c: remote.RemoteEngineInstances(c),
            "evaluation_instances":
                lambda c: remote.RemoteEvaluationInstances(c),
            "models": lambda c: remote.RemoteModels(c),
        },
        close=lambda c: c.close()))

    register_backend("LOCALFS", Backend(
        make_client=lambda cfg: localfs.LocalFSClient.from_config(cfg),
        daos={
            "events": lambda c: localfs.LocalFSEventStore(c),
            "apps": lambda c: localfs.LocalFSApps(c),
            "access_keys": lambda c: localfs.LocalFSAccessKeys(c),
            "channels": lambda c: localfs.LocalFSChannels(c),
            "engine_instances": lambda c: localfs.LocalFSEngineInstances(c),
            "evaluation_instances":
                lambda c: localfs.LocalFSEvaluationInstances(c),
            "models": lambda c: localfs.LocalFSModels(c),
        },
        close=lambda c: c.close()))

    from . import objectstore

    # "s3" and "gcs" are one backend: both stores speak the same REST
    # subset (the GCS XML API is S3-compatible); reference roles:
    # storage/s3/.../S3Models.scala, storage/hdfs/.../HDFSModels.scala
    for _name in ("S3", "GCS", "OBJECTSTORE"):
        register_backend(_name, Backend(
            make_client=lambda cfg:
                objectstore.ObjectStoreClient.from_config(cfg),
            daos={
                "events": lambda c: objectstore.ObjectStoreEventStore(c),
                "apps": lambda c: objectstore.ObjectStoreApps(c),
                "access_keys":
                    lambda c: objectstore.ObjectStoreAccessKeys(c),
                "channels": lambda c: objectstore.ObjectStoreChannels(c),
                "engine_instances":
                    lambda c: objectstore.ObjectStoreEngineInstances(c),
                "evaluation_instances":
                    lambda c: objectstore.ObjectStoreEvaluationInstances(c),
                "models": lambda c: objectstore.ObjectStoreModels(c),
            },
            close=lambda c: c.close()))


_register_builtins()


@dataclass
class SourceConfig:
    name: str
    type: str
    properties: Dict[str, str] = field(default_factory=dict)


class Storage:
    """One configured storage environment: sources + repository bindings.

    The default configuration (no env vars) is a SQLite file at
    ``$PIO_HOME/pio.db`` (or ``./pio_data/pio.db``) for all three
    repositories — the role PGSQL played in the reference's default
    ``pio-env.sh``. With multiple sources configured, unbound
    repositories fall back to the alphabetically-first source name
    (deterministic across processes).
    """

    def __init__(self, env: Optional[Mapping[str, str]] = None):
        self.env = dict(env if env is not None else os.environ)
        self._sources: Dict[str, SourceConfig] = {}
        self._repos: Dict[str, str] = {}
        self._clients: Dict[str, object] = {}
        self._dao_cache: Dict[tuple, object] = {}
        self._lock = threading.RLock()
        self._parse_env()

    # -- configuration -----------------------------------------------------
    def _parse_env(self) -> None:
        prefix = "PIO_STORAGE_SOURCES_"
        names = sorted({k[len(prefix):-len("_TYPE")] for k in self.env
                        if k.startswith(prefix) and k.endswith("_TYPE")})
        for name in names:
            props = {}
            p = f"{prefix}{name}_"
            for k, v in self.env.items():
                if k.startswith(p) and k != f"{p}TYPE":
                    props[k[len(p):]] = v
            self._sources[name] = SourceConfig(
                name=name, type=self.env[f"{p}TYPE"].upper(), properties=props)

        for repo in REPOSITORIES:
            src = self.env.get(f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE")
            if src is not None:
                if src not in self._sources:
                    raise StorageError(
                        f"repository {repo} references undefined source {src}")
                self._repos[repo] = src

        if not self._sources:
            # dev default: one SQLite file for everything
            home = self.env.get("PIO_HOME", os.path.join(os.getcwd(), "pio_data"))
            path = self.env.get("PIO_SQLITE_PATH",
                                os.path.join(home, "pio.db"))
            self._sources["DEFAULT"] = SourceConfig(
                name="DEFAULT", type="SQLITE", properties={"PATH": path})
        default = next(iter(self._sources))
        for repo in REPOSITORIES:
            self._repos.setdefault(repo, default)

    # -- accessors ---------------------------------------------------------
    def _client(self, source_name: str) -> object:
        with self._lock:
            if source_name not in self._clients:
                cfg = self._sources[source_name]
                backend = _BACKENDS.get(cfg.type)
                if backend is None:
                    raise StorageError(f"unknown storage type {cfg.type!r} "
                                       f"(registered: {sorted(_BACKENDS)})")
                self._clients[source_name] = backend.make_client(cfg.properties)
            return self._clients[source_name]

    def _dao(self, repo: str, dao: str):
        source_name = self._repos[repo]
        key = (source_name, dao)
        with self._lock:
            if key not in self._dao_cache:
                cfg = self._sources[source_name]
                backend = _BACKENDS[cfg.type]
                if dao not in backend.daos:
                    raise StorageError(
                        f"storage type {cfg.type!r} has no {dao!r} DAO")
                self._dao_cache[key] = backend.daos[dao](self._client(source_name))
            return self._dao_cache[key]

    def events(self) -> EventStore:
        return self._dao("EVENTDATA", "events")

    def apps(self) -> AppsDAO:
        return self._dao("METADATA", "apps")

    def access_keys(self) -> AccessKeysDAO:
        return self._dao("METADATA", "access_keys")

    def channels(self) -> ChannelsDAO:
        return self._dao("METADATA", "channels")

    def engine_instances(self) -> EngineInstancesDAO:
        return self._dao("METADATA", "engine_instances")

    def evaluation_instances(self) -> EvaluationInstancesDAO:
        return self._dao("METADATA", "evaluation_instances")

    def models(self) -> ModelsDAO:
        return self._dao("MODELDATA", "models")

    # -- ops ---------------------------------------------------------------
    def verify_all_data_objects(self) -> None:
        """Instantiate every repository DAO and smoke-test the event store
        (``Storage.verifyAllDataObjects``, ``Storage.scala:372-394``)."""
        for dao in _DAO_NAMES:
            repo = ("EVENTDATA" if dao == "events"
                    else "MODELDATA" if dao == "models" else "METADATA")
            self._dao(repo, dao)
        ev = self.events()
        ev.init(0)
        ev.remove(0)

    def close(self) -> None:
        with self._lock:
            for name, client in self._clients.items():
                _BACKENDS[self._sources[name].type].close(client)
            self._clients.clear()
            self._dao_cache.clear()


_global: Optional[Storage] = None
_global_lock = threading.Lock()


def get_storage(refresh: bool = False) -> Storage:
    """Process-wide storage environment (lazily built from os.environ)."""
    global _global
    with _global_lock:
        if _global is None or refresh:
            _global = Storage()
        return _global


def set_storage(storage: Optional[Storage]) -> None:
    """Override the process-wide storage (tests, embedded use)."""
    global _global
    with _global_lock:
        _global = storage
