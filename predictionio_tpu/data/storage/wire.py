"""Wire serialization shared by the storage server and the REMOTE
client backend (the client-server storage role of the reference's JDBC /
Elasticsearch / HBase sources — ``JDBCLEvents.scala:109-247``,
``ESLEvents.scala:106-150``: every host reaches the event store over the
network, no shared filesystem required).

Three formats:

- metadata entities ↔ JSON docs (datetimes as ISO strings)
- :class:`EventFilter` ↔ JSON (the ``ANY`` tri-state sentinel encoded
  explicitly — ``{"any": true}`` vs ``{"value": ...}`` — matching the
  reference's ``Option[Option[String]]`` trick)
- :class:`ColumnarBatch` ↔ one ``.npz`` payload (columns + dictionary
  value arrays, no pickling) for the bulk training read
"""

from __future__ import annotations

import io
from datetime import datetime
from typing import Any, Dict, Optional

import numpy as np

from .base import (
    ANY,
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    EventFilter,
)

# -- metadata entities ------------------------------------------------------

_DT_FIELDS = ("start_time", "end_time")


def entity_to_doc(e) -> dict:
    import dataclasses

    d = dataclasses.asdict(e)
    for k in _DT_FIELDS:
        if isinstance(d.get(k), datetime):
            d[k] = d[k].isoformat()
    if "events" in d:
        d["events"] = list(d["events"])
    return d


_ENTITY_TYPES = {
    "apps": App,
    "access_keys": AccessKey,
    "channels": Channel,
    "engine_instances": EngineInstance,
    "evaluation_instances": EvaluationInstance,
}


def entity_from_doc(dao: str, d: dict):
    cls = _ENTITY_TYPES[dao]
    d = dict(d)
    for k in _DT_FIELDS:
        if isinstance(d.get(k), str):
            d[k] = datetime.fromisoformat(d[k])
    if "events" in d and d["events"] is not None:
        d["events"] = tuple(d["events"])
    return cls(**d)


# -- EventFilter ------------------------------------------------------------

def filter_to_doc(f: EventFilter) -> dict:
    def tri(v) -> Dict[str, Any]:
        return {"any": True} if v is ANY else {"value": v}

    return {
        "start_time": f.start_time.isoformat() if f.start_time else None,
        "until_time": f.until_time.isoformat() if f.until_time else None,
        "entity_type": f.entity_type,
        "entity_id": f.entity_id,
        "event_names": (list(f.event_names)
                        if f.event_names is not None else None),
        "target_entity_type": tri(f.target_entity_type),
        "target_entity_id": tri(f.target_entity_id),
        "limit": f.limit,
        "reversed": f.reversed,
        # deadline is a LOCAL monotonic clock value — it cannot cross the
        # wire; the client maps it to an HTTP timeout instead
    }


def filter_from_doc(d: Optional[dict]) -> EventFilter:
    if not d:
        return EventFilter()

    def tri(v):
        if not isinstance(v, dict) or v.get("any"):
            return ANY
        return v.get("value")

    def dt(s):
        return datetime.fromisoformat(s) if s else None

    return EventFilter(
        start_time=dt(d.get("start_time")),
        until_time=dt(d.get("until_time")),
        entity_type=d.get("entity_type"),
        entity_id=d.get("entity_id"),
        event_names=d.get("event_names"),
        target_entity_type=tri(d.get("target_entity_type", {"any": True})),
        target_entity_id=tri(d.get("target_entity_id", {"any": True})),
        limit=d.get("limit"),
        reversed=bool(d.get("reversed")),
    )


# -- ColumnarBatch ----------------------------------------------------------

_BATCH_COLS = ("event", "entity_type", "entity_id", "target_type",
               "target_id", "event_time", "props_offsets", "props_blob")
_DICT_NAMES = ("event_names", "entity_types", "entity_ids",
               "target_types", "target_ids")


def batch_to_npz(batch) -> bytes:
    """Serialize a ColumnarBatch (pickle-free: dictionary values go as
    numpy unicode arrays)."""
    arrays = {c: np.asarray(getattr(batch, c)) for c in _BATCH_COLS}
    for name in _DICT_NAMES:
        vals = getattr(batch.dicts, name).values
        arrays[f"dict_{name}"] = np.asarray(vals, dtype="U") if vals \
            else np.empty(0, dtype="U1")
    for name, arr in batch.float_props.items():
        arrays[f"prop_{name}"] = np.asarray(arr)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def batch_from_npz(data: bytes):
    from ..columnar import ColumnarBatch, ColumnarDicts, StringDict

    with np.load(io.BytesIO(data), allow_pickle=False) as z:
        dicts = ColumnarDicts(**{
            name: StringDict([str(v) for v in z[f"dict_{name}"]])
            for name in _DICT_NAMES})
        return ColumnarBatch(
            **{c: z[c] for c in _BATCH_COLS},
            float_props={k[len("prop_"):]: z[k] for k in z.files
                         if k.startswith("prop_")},
            dicts=dicts)
