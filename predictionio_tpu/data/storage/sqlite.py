"""SQLite storage backend — the durable dev default.

Plays the role the reference's JDBC backend played
(``storage/jdbc/src/main/scala/.../JDBCLEvents.scala`` event tables
``events_<appId>[_<channelId>]``, ``JDBCApps/JDBCAccessKeys/...`` metadata
tables), on Python's built-in sqlite3: one database file holds the event
log, metadata, and model blobs. WAL mode + a process-wide write lock give
safe concurrent access from server executor threads.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
from typing import Iterator, List, Optional

from ...faults import fire
from ..datamap import DataMap
from ..event import Event, from_millis, new_event_id, to_millis, utcnow
from .base import (
    ANY,
    AccessKey,
    AccessKeysDAO,
    App,
    AppsDAO,
    Channel,
    ChannelsDAO,
    EngineInstance,
    EngineInstancesDAO,
    EvaluationInstance,
    EvaluationInstancesDAO,
    EventFilter,
    EventStore,
    Model,
    ModelsDAO,
    STATUS_COMPLETED,
    STATUS_EVALCOMPLETED,
)


class SQLiteClient:
    """Shared connection + write lock for one database file."""

    def __init__(self, path: str = ":memory:"):
        self.path = path
        if path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.conn = sqlite3.connect(path, check_same_thread=False)
        self.conn.execute("PRAGMA journal_mode=WAL")
        # WAL + NORMAL: commits are durable against app crashes and only
        # lose the tail on OS/power failure — the standard WAL trade, and
        # ~10× fewer fsyncs on the per-event REST ingest path
        self.conn.execute("PRAGMA synchronous=NORMAL")
        self.lock = threading.RLock()
        #: in-process columnar sidecar cache: table → (batch, watermark,
        #: count) — revalidated against the row store on every bulk read
        self.columnar_cache: dict = {}

    def close(self) -> None:
        with self.lock:
            self.conn.close()

    @staticmethod
    def from_config(config: Optional[dict]) -> "SQLiteClient":
        path = (config or {}).get("PATH", ":memory:")
        return SQLiteClient(path)


def _table(app_id: int, channel_id: Optional[int]) -> str:
    # `is not None`, never falsy: channel 0 must not alias the default
    # channel (memory/localfs/segmentfs already keep it distinct)
    return f"events_{app_id}" + (f"_{channel_id}"
                                 if channel_id is not None else "")


def _fork_context():
    """Fork multiprocessing context, or None where unavailable. Fork —
    not spawn — so encode workers skip the ~1s numpy/pandas re-import;
    they touch only their own fresh sqlite connection plus
    numpy/pandas, so inherited state is inert."""
    import multiprocessing

    try:
        return multiprocessing.get_context("fork")
    except ValueError:  # pragma: no cover — non-POSIX
        return None


def _encode_range_worker(path: str, sql: str, rng: tuple,
                         n_props: int) -> Optional[dict]:
    """One seq-range of the columnar first-encode, self-contained so it
    can run in a worker process: fetch (bytes ``text_factory`` — only
    dictionary uniques are ever UTF-8-decoded), per-column local
    factorize, numeric-prop column build. The raw property JSON is
    deliberately NOT part of the range SQL (props-deferred segments).
    Returns plain numpy payloads; the parent remaps local dictionary
    codes onto the persistent per-log dictionaries."""
    import numpy as np

    from ..columnar import bulk_factorize, bulk_to_float64

    conn = sqlite3.connect(path)
    conn.text_factory = bytes
    try:
        rows = conn.execute(sql, rng).fetchall()
    finally:
        conn.close()
    if not rows:
        return None
    cols = list(zip(*rows))
    n = len(rows)
    codes_out = {}
    uniq_out = {}
    for name, j in (("event", 0), ("entity_type", 1), ("entity_id", 2),
                    ("target_type", 3), ("target_id", 4)):
        # bulk_factorize hands uniques back as an object ndarray whose
        # .tolist() is C-speed (pandas ExtensionArray iteration would
        # box every element through __getitem__)
        codes, uniques = bulk_factorize(cols[j])
        codes_out[name] = codes.astype(np.int32)
        uniq_out[name] = [u.decode("utf-8") if isinstance(u, bytes)
                          else u for u in uniques.tolist()]
    # json_extract yields float/int/None only (json_type gated in SQL),
    # so the strict isinstance pass is skippable
    fpv = [bulk_to_float64(cols[6 + j], assume_numeric=True)
           for j in range(n_props)]
    return dict(codes=codes_out, uniq=uniq_out,
                times=np.asarray(cols[5], dtype=np.int64), fpv=fpv,
                lo=int(rng[0]), last_seq=int(rng[1]), n=n)


class SQLiteEventStore(EventStore):
    def __init__(self, client: SQLiteClient):
        self.client = client

    @property
    def _conn(self) -> sqlite3.Connection:
        return self.client.conn

    #: the event columns in canonical order (queries never SELECT * — the
    #: leading ``seq`` column is bookkeeping, not event data)
    EVENT_COLS = ("id, event, entity_type, entity_id, target_entity_type, "
                  "target_entity_id, properties, event_time, tags, pr_id, "
                  "creation_time")

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self.client.lock:
            table = _table(app_id, channel_id)
            self._migrate_legacy(table)
            self._conn.execute(f"""
                CREATE TABLE IF NOT EXISTS {table} (
                    seq INTEGER PRIMARY KEY AUTOINCREMENT,
                    id TEXT UNIQUE NOT NULL,
                    event TEXT NOT NULL,
                    entity_type TEXT NOT NULL,
                    entity_id TEXT NOT NULL,
                    target_entity_type TEXT,
                    target_entity_id TEXT,
                    properties TEXT,
                    event_time INTEGER NOT NULL,
                    tags TEXT,
                    pr_id TEXT,
                    creation_time INTEGER NOT NULL
                )""")
            self._conn.execute(
                f"CREATE INDEX IF NOT EXISTS idx_{table}_t "
                f"ON {table} (event_time)")
            self._conn.commit()
        return True

    def _migrate_legacy(self, table: str) -> None:
        """Round-1 tables used the implicit rowid, which SQLite *reuses*
        after deletes — that falsifies the columnar sidecar's monotonic
        watermark (a reused rowid can make a changed prefix look
        unchanged). Rebuild such tables around an AUTOINCREMENT ``seq``,
        which is guaranteed never to be reused."""
        tmp = f"{table}_legacy"
        names = {r[0] for r in self._conn.execute(
            "SELECT name FROM sqlite_master WHERE type='table' "
            "AND name IN (?, ?)", (table, tmp))}
        if not names:
            return
        cols = [r[1] for r in
                self._conn.execute(f"PRAGMA table_info({table})")] \
            if table in names else []
        if "seq" in cols and tmp not in names:
            return  # already migrated
        # one explicit transaction: SQLite DDL is transactional, and the
        # Python driver autocommits DDL otherwise — a crash mid-migration
        # must never strand events in the _legacy table
        self._conn.commit()
        self._conn.execute("BEGIN IMMEDIATE")
        try:
            if table in names and "seq" not in cols:
                self._conn.execute(f"ALTER TABLE {table} RENAME TO {tmp}")
            # (re)create the new-schema table; on crash recovery
            # (tmp left over by a pre-atomic version) it may exist already
            self._conn.execute(f"""
                CREATE TABLE IF NOT EXISTS {table} (
                    seq INTEGER PRIMARY KEY AUTOINCREMENT,
                    id TEXT UNIQUE NOT NULL,
                    event TEXT NOT NULL,
                    entity_type TEXT NOT NULL,
                    entity_id TEXT NOT NULL,
                    target_entity_type TEXT,
                    target_entity_id TEXT,
                    properties TEXT,
                    event_time INTEGER NOT NULL,
                    tags TEXT,
                    pr_id TEXT,
                    creation_time INTEGER NOT NULL
                )""")
            self._conn.execute(
                f"INSERT OR IGNORE INTO {table} ({self.EVENT_COLS}) "
                f"SELECT {self.EVENT_COLS} FROM {tmp} ORDER BY rowid")
            self._conn.execute(f"DROP TABLE {tmp}")
            self._conn.execute(
                f"CREATE INDEX IF NOT EXISTS idx_{table}_t "
                f"ON {table} (event_time)")
            self._conn.commit()
        except BaseException:
            self._conn.rollback()
            raise

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self.client.lock:
            self._conn.execute(
                f"DROP TABLE IF EXISTS {_table(app_id, channel_id)}")
            self._conn.commit()
            for wp in (False, True):
                self.client.columnar_cache.pop(
                    (_table(app_id, channel_id), wp), None)
        d = self._columnar_dir(app_id, channel_id)
        if d is not None:
            from ..columnar import SegmentLog
            log = SegmentLog(d)
            with log.lock():
                log.invalidate()
        return True

    def close(self) -> None:
        pass  # client is shared; closed by the registry

    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(self, events, app_id: int,
                     channel_id: Optional[int] = None) -> List[str]:
        fire("storage.io", op="insert", backend="sqlite")
        rows, ids = [], []
        for e in events:
            eid = e.event_id or new_event_id()
            ids.append(eid)
            rows.append((
                eid, e.event, e.entity_type, e.entity_id,
                e.target_entity_type, e.target_entity_id,
                e.properties.to_json(), to_millis(e.event_time),
                json.dumps(list(e.tags)), e.pr_id,
                to_millis(e.creation_time)))
        sql = (f"INSERT OR REPLACE INTO {_table(app_id, channel_id)} "
               f"({self.EVENT_COLS}) VALUES (?,?,?,?,?,?,?,?,?,?,?)")
        with self.client.lock:
            try:
                try:
                    self._conn.executemany(sql, rows)
                except sqlite3.OperationalError as e:
                    if "no such table" not in str(e):
                        raise
                    self.init(app_id, channel_id)
                    self._conn.executemany(sql, rows)
                self._conn.commit()
            except BaseException:
                # a failed executemany may have applied a prefix of the
                # rows; roll it back so a caller's per-event retry (the
                # event server's poison-batch fallback) cannot commit
                # those rows alongside fresh duplicates
                self._conn.rollback()
                raise
        return ids

    def insert_columnar(self, batch, app_id: int,
                        channel_id: Optional[int] = None) -> int:
        """Vectorized block write: each dictionary-coded column is decoded
        once (five list lookups total, no per-event ``Event`` objects) and
        the rows go down in a single ``executemany`` transaction — the
        zero-copy counterpart of :meth:`insert_batch` for the
        ``/columnar`` ingest route."""
        fire("storage.io", op="insert_columnar", backend="sqlite")
        n = batch.n
        if n == 0:
            return 0
        d = batch.dicts
        ev = d.event_names.decode(batch.event)
        et = d.entity_types.decode(batch.entity_type)
        ei = d.entity_ids.decode(batch.entity_id)
        tt = d.target_types.decode(batch.target_type)
        ti = d.target_ids.decode(batch.target_id)
        offs = batch.props_offsets
        blob = batch.props_blob.tobytes()
        times = batch.event_time.tolist()
        now_ms = to_millis(utcnow())
        rows = []
        for i in range(n):
            s, e = int(offs[i]), int(offs[i + 1])
            props = blob[s:e].decode("utf-8") if e > s else "{}"
            rows.append((new_event_id(), ev[i], et[i], ei[i], tt[i], ti[i],
                         props, times[i], "[]", None, now_ms))
        sql = (f"INSERT OR REPLACE INTO {_table(app_id, channel_id)} "
               f"({self.EVENT_COLS}) VALUES (?,?,?,?,?,?,?,?,?,?,?)")
        with self.client.lock:
            try:
                try:
                    self._conn.executemany(sql, rows)
                except sqlite3.OperationalError as e:
                    if "no such table" not in str(e):
                        raise
                    self.init(app_id, channel_id)
                    self._conn.executemany(sql, rows)
                self._conn.commit()
            except BaseException:
                self._conn.rollback()
                raise
        return n

    # -- columnar bulk reads (PEvents role) --------------------------------
    #: rows per columnar segment during sidecar sync
    COLUMNAR_CHUNK = 2_000_000

    def _columnar_dir(self, app_id: int,
                      channel_id: Optional[int]) -> Optional[str]:
        if self.client.path == ":memory:":
            return None
        return os.path.join(f"{self.client.path}.columnar",
                            _table(app_id, channel_id))

    def _scalar(self, sql: str, *params) -> Optional[int]:
        with self.client.lock:
            try:
                row = self._conn.execute(sql, params).fetchone()
            except sqlite3.OperationalError as e:
                if "no such table" in str(e):
                    return None
                raise
        return row[0] if row else None

    def warm_columnar(self, app_id: int,
                      channel_id: Optional[int] = None) -> bool:
        d = self._columnar_dir(app_id, channel_id)
        if d is None:  # :memory: database — nothing persistent to warm
            return False
        self._sync_columnar(d, app_id, channel_id, ("rating",),
                            want_props=False)
        return True

    def find_columnar(self, app_id: int, channel_id: Optional[int] = None,
                      filter: EventFilter = EventFilter(),
                      float_props=("rating",),
                      ordered: bool = True, with_props: bool = True,
                      shard=None):
        """Columnar bulk read backed by a persistent segment sidecar
        (``<db>.columnar/<table>/``): the row store stays authoritative;
        immutable numpy segments are synced forward by rowid watermark and
        mmap-loaded, so training-scale scans run at memory bandwidth
        instead of per-row Python (the ``JDBCPEvents.scala:49-89``
        partitioned-scan role). ``shard=(i, n)`` slices the mmap'd
        projection by row range — pages outside the shard stay
        untouched (the rowid-range scan, done at the page-cache level)."""
        d = self._columnar_dir(app_id, channel_id)
        if d is None:  # :memory: database — encode per call
            return super().find_columnar(app_id, channel_id, filter,
                                         float_props, ordered=ordered,
                                         with_props=with_props,
                                         shard=shard)
        batch = self._sync_columnar(d, app_id, channel_id,
                                    tuple(float_props),
                                    want_props=with_props)
        if shard is not None:
            out = self._shard_and_select(batch, shard, filter,
                                         ordered=ordered,
                                         with_props=with_props)
        else:
            out = batch.select(filter, ordered=ordered,
                               with_props=with_props)
        # views are deterministic projections of the log, so the parent's
        # chained content stamp remains a valid ETag for them
        out.content_stamp = getattr(batch, "content_stamp", None)
        return out

    def _change_stamp(self) -> tuple:
        """(data_version, total_changes): moves whenever this connection —
        or any other process — writes the database. Stable stamp ⇒ the
        cached columnar view is provably current without paying the O(n)
        prefix-count validity query per read."""
        with self.client.lock:
            dv = self._conn.execute("PRAGMA data_version").fetchone()[0]
            return dv, self._conn.total_changes

    def _sync_columnar(self, sidecar_dir: str, app_id: int,
                       channel_id: Optional[int], float_props: tuple,
                       want_props: bool = True):
        from ..columnar import ColumnarBatch, SegmentLog

        table = _table(app_id, channel_id)
        stamp = self._change_stamp()
        ck = (table, bool(want_props))
        cached = self.client.columnar_cache.get(ck)
        if cached is not None and cached[2] == stamp:
            return cached[1]
        with self.client.lock:
            self._migrate_legacy(table)  # watermark needs AUTOINCREMENT seq
        log = SegmentLog(sidecar_dir)
        with log.lock():
            manifest = log.read_manifest()
            if log.format_stale(manifest):
                if int(manifest.get("format", 1)) == 1:
                    # v1→v2 changed only how ISO strings became millis —
                    # the SQLite encoder reads INTEGER millis straight
                    # from SQL and never touched that path, so v1
                    # sqlite sidecars are byte-identical to v2: stamp in
                    # place instead of re-encoding millions of rows
                    manifest["format"] = 2
                    log._write_manifest(manifest)
                if log.format_stale(manifest):
                    log.invalidate()
                    manifest = None
            wm = int((manifest or {}).get("watermark") or 0)
            count = int((manifest or {}).get("count") or 0)
            if manifest is not None:
                # deletes / REPLACEd rows below the watermark falsify the
                # segments; rebuild from scratch when the prefix changed
                # (seq is AUTOINCREMENT: never reused, so this check is
                # sound against delete-then-reinsert races)
                prefix = self._scalar(
                    f"SELECT COUNT(*) FROM {table} WHERE seq<=?", wm)
                if prefix != count:
                    log.invalidate()
                    manifest, wm, count = None, 0, 0
            max_seq = self._scalar(
                f"SELECT COALESCE(MAX(seq),0) FROM {table}")
            if max_seq is None:  # table never created
                return ColumnarBatch.empty()
            if max_seq > wm:
                self._encode_delta(log, table, wm, float_props)
            if want_props:
                try:
                    log.ensure_props(self._fetch_props_range(table))
                except RuntimeError:
                    # a delete raced the sync inside a deferred segment's
                    # range: self-heal in-call instead of surfacing a
                    # transient error to the reader
                    log.invalidate()
                    self._encode_delta(log, table, 0, float_props)
                    log.ensure_props(self._fetch_props_range(table))
                    cached = None
            manifest = log.read_manifest()
            key = ((manifest or {}).get("watermark"),
                   (manifest or {}).get("count"),
                   len((manifest or {}).get("segments") or ()))
            # stamp taken BEFORE the validity queries: a write racing the
            # sync makes the stamp stale, forcing revalidation next call
            if cached is not None and cached[0] == key:
                batch = cached[1]
            else:
                batch, _ = log.load(with_props=want_props)
                if batch is None:
                    batch = ColumnarBatch.empty()
            # chained per-segment content stamp (maintained O(delta) at
            # append) rides on the batch so the storage server's ETag
            # never re-hashes the full column bytes
            batch.content_stamp = (manifest or {}).get("stamp")
            self.client.columnar_cache[ck] = (key, batch, stamp)
            return batch

    def _fetch_props_range(self, table: str):
        """Fetch-callback factory for :meth:`SegmentLog.ensure_props`:
        builds one segment's ``(props_offsets, props_blob)`` from the
        row store by seq range."""
        import numpy as np

        def fetch(lo: int, hi: int, n: int):
            with self.client.lock:
                rows = self._conn.execute(
                    f"SELECT CAST(properties AS BLOB) FROM {table} "
                    f"WHERE seq>? AND seq<=? ORDER BY seq",
                    (lo, hi)).fetchall()
            if len(rows) != n:
                # a delete raced the sync inside this range; the prefix
                # check will invalidate and rebuild on the next call
                raise RuntimeError(
                    f"props upgrade: {table} range ({lo},{hi}] has "
                    f"{len(rows)} rows, segment expects {n}")
            encoded = [b"" if not p or p == b"{}" else p
                       for (p,) in rows]
            lens = np.fromiter(map(len, encoded), dtype=np.int64,
                               count=n)
            offs = np.zeros(n + 1, dtype=np.int64)
            np.cumsum(lens, out=offs[1:])
            blob = (np.frombuffer(b"".join(encoded), dtype=np.uint8)
                    .copy() if int(offs[-1]) else
                    np.empty(0, dtype=np.uint8))
            return offs, blob

        return fetch

    #: worker processes for the parallel first-encode fetch
    ENCODE_PROCS = 4
    #: rows per pipelined fetch unit (several make up one segment)
    ENCODE_SUBCHUNK = 250_000
    #: deltas below this many (estimated) rows encode in-process —
    #: pool spin-up would dominate tests' tiny logs
    ENCODE_PARALLEL_MIN = 600_000

    def _chunk_bounds(self, table: str, watermark: int,
                      step: int) -> List[int]:
        """Ascending seq upper bounds splitting ``seq > watermark`` into
        ~``step``-row ranges. ``seq`` aliases the rowid, so each OFFSET
        probe is an index-only B-tree walk (C speed)."""
        bounds: List[int] = []
        lo = watermark
        while True:
            with self.client.lock:
                row = self._conn.execute(
                    f"SELECT MAX(seq) FROM (SELECT seq FROM {table} "
                    f"WHERE seq>? ORDER BY seq LIMIT ?)",
                    (lo, step)).fetchone()
            if row is None or row[0] is None or row[0] <= lo:
                return bounds
            bounds.append(int(row[0]))
            lo = int(row[0])

    def _encode_delta(self, log, table: str, watermark: int,
                      float_props: tuple) -> None:
        """Encode rows above ``watermark`` into new segments. Numeric
        property extraction is pushed into SQL (``json_extract``). Large
        deltas are range-partitioned over ``ENCODE_PROCS`` worker
        *processes* (fork): each worker fetches its seq range on its own
        connection and does the per-row work — tuple building, local
        dictionary factorize, property-blob concat — with real
        parallelism (threads serialize on the GIL for exactly that
        work; measured ~1.3x where processes give ~3x). The parent
        only remaps each worker's
        *uniques* onto the persistent dictionaries and stitches
        segments, so per-row Python in the parent is zero."""
        from collections import deque
        from concurrent.futures import ProcessPoolExecutor

        import numpy as np

        safe_props = [p for p in float_props
                      if p.replace("_", "").isalnum()]
        # json_type gate: only real JSON numbers become ratings — a string
        # "N/A" or a bool must come back NULL (matching the lazy-parse
        # path's isinstance check), never be CAST-coerced to 0.0/1.0
        prop_sql = "".join(
            f", CASE WHEN json_type(properties, '$.{p}') IN "
            f"('integer','real') THEN "
            f"json_extract(properties, '$.{p}') END"
            for p in safe_props)
        # properties JSON is NOT fetched — the training read never touches
        # it; props-needing readers upgrade segments via ensure_props().
        # seq itself isn't fetched either: the range bounds are actual
        # seq values, so each range's last seq is its upper bound.
        sql = (f"SELECT event, entity_type, entity_id, "
               f"target_entity_type, target_entity_id, "
               f"event_time{prop_sql} FROM {table} "
               f"WHERE seq>? AND seq<=? ORDER BY seq")
        bounds = self._chunk_bounds(table, watermark,
                                    self.ENCODE_SUBCHUNK)
        if not bounds:
            return
        dicts, prev_counts = log.dicts_and_counts()
        ranges = list(zip([watermark] + bounds[:-1], bounds))
        path = os.path.abspath(self.client.path)
        n_props = len(safe_props)
        per_seg = max(1, self.COLUMNAR_CHUNK // self.ENCODE_SUBCHUNK)

        def emit(parts: list) -> None:
            """Remap worker-local dictionary codes onto the persistent
            dicts (uniques only — C-bulk via StringDict.encode) and
            commit one segment."""
            nonlocal prev_counts
            from ..columnar import ColumnarBatch
            cols = {}
            for name, sd in (("event", dicts.event_names),
                             ("entity_type", dicts.entity_types),
                             ("entity_id", dicts.entity_ids),
                             ("target_type", dicts.target_types),
                             ("target_id", dicts.target_ids)):
                chunks = []
                for p in parts:
                    codes = p["codes"][name]
                    uniq = p["uniq"][name]
                    if len(uniq) == 0:
                        chunks.append(np.full(p["n"], -1, np.int32))
                        continue
                    # worker uniques are already unique: skip encode()'s
                    # re-factorize, go straight to the C-bulk lookup
                    remap = sd._bulk_lookup(uniq)
                    chunks.append(np.where(
                        codes >= 0, remap[np.maximum(codes, 0)],
                        np.int32(-1)).astype(np.int32))
                cols[name] = np.concatenate(chunks)
            n_seg = sum(p["n"] for p in parts)
            batch = ColumnarBatch(
                event=cols["event"], entity_type=cols["entity_type"],
                entity_id=cols["entity_id"],
                target_type=cols["target_type"],
                target_id=cols["target_id"],
                event_time=np.concatenate([p["times"] for p in parts]),
                props_offsets=np.zeros(n_seg + 1, np.int64),
                props_blob=np.empty(0, np.uint8),
                float_props={nm: np.concatenate(
                    [p["fpv"][j] for p in parts])
                    for j, nm in enumerate(safe_props)},
                dicts=dicts)
            log.append(batch, watermark=int(parts[-1]["last_seq"]),
                       prev_dict_counts=prev_counts,
                       seq_range=(int(parts[0]["lo"]),
                                  int(parts[-1]["last_seq"])),
                       has_props=False)
            prev_counts = dicts.counts()

        est_rows = len(ranges) * self.ENCODE_SUBCHUNK
        n_cpu = os.cpu_count() or 1
        # fork is only safe single-threaded: a forked child of a
        # multithreaded parent can inherit a held lock and deadlock
        # (server executor threads are a supported caller here)
        ctx = _fork_context() if threading.active_count() == 1 else None
        if len(ranges) == 1 or est_rows < self.ENCODE_PARALLEL_MIN \
                or n_cpu == 1 or ctx is None:
            # small delta / single-core / no safe fork: in-process
            for seg_start in range(0, len(ranges), per_seg):
                parts = [p for rng in ranges[seg_start:seg_start + per_seg]
                         if (p := _encode_range_worker(
                             path, sql, rng, n_props)) is not None]
                if parts:
                    emit(parts)
            return
        workers = max(1, min(self.ENCODE_PROCS, n_cpu, len(ranges)))
        with ProcessPoolExecutor(max_workers=workers,
                                 mp_context=ctx) as pool:
            futs: deque = deque()
            ri = 0
            pending: list = []
            while ri < len(ranges) or futs:
                while ri < len(ranges) and len(futs) < workers + 2:
                    futs.append(pool.submit(
                        _encode_range_worker, path, sql, ranges[ri],
                        n_props))
                    ri += 1
                p = futs.popleft().result()
                if p is not None:
                    pending.append(p)
                if len(pending) >= per_seg or (not futs
                                               and ri >= len(ranges)):
                    if pending:
                        emit(pending)
                        pending = []

    def aggregate_properties(self, app_id: int,
                             channel_id: Optional[int] = None, *,
                             entity_type: str, start_time=None,
                             until_time=None, required=None):
        """Columnar aggregation: filter pushdown runs as vectorized masks
        over the sidecar; only surviving ``$set/$unset/$delete`` rows pay
        Python-level merges (``PEventAggregator.scala:196-210`` role)."""
        d = self._columnar_dir(app_id, channel_id)
        if d is None:
            return super().aggregate_properties(
                app_id, channel_id, entity_type=entity_type,
                start_time=start_time, until_time=until_time,
                required=required)
        from ..aggregation import AGGREGATION_EVENTS, aggregate_from_columnar
        batch = self._sync_columnar(d, app_id, channel_id, ("rating",),
                                    want_props=True)
        sub = batch.select(EventFilter(
            entity_type=entity_type, start_time=start_time,
            until_time=until_time,
            event_names=list(AGGREGATION_EVENTS)), ordered=False)
        result = aggregate_from_columnar(sub)
        if required:
            req = set(required)
            result = {k: v for k, v in result.items()
                      if req <= set(v.keys())}
        return result

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        with self.client.lock:
            try:
                cur = self._conn.execute(
                    f"SELECT {self.EVENT_COLS} FROM "
                    f"{_table(app_id, channel_id)} WHERE id=?",
                    (event_id,))
                row = cur.fetchone()
            except sqlite3.OperationalError as e:
                if "no such table" in str(e):
                    return None
                raise
        return _row_to_event(row) if row else None

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        with self.client.lock:
            try:
                cur = self._conn.execute(
                    f"DELETE FROM {_table(app_id, channel_id)} WHERE id=?",
                    (event_id,))
            except sqlite3.OperationalError as e:
                if "no such table" in str(e):
                    return False
                raise
            self._conn.commit()
            return cur.rowcount > 0

    def find(self, app_id: int, channel_id: Optional[int] = None,
             filter: EventFilter = EventFilter()) -> Iterator[Event]:
        fire("storage.io", op="find", backend="sqlite")
        clauses, params = [], []
        if filter.start_time is not None:
            clauses.append("event_time >= ?")
            params.append(to_millis(filter.start_time))
        if filter.until_time is not None:
            clauses.append("event_time < ?")
            params.append(to_millis(filter.until_time))
        if filter.entity_type is not None:
            clauses.append("entity_type = ?")
            params.append(filter.entity_type)
        if filter.entity_id is not None:
            clauses.append("entity_id = ?")
            params.append(filter.entity_id)
        if filter.event_names is not None:
            qs = ",".join("?" * len(filter.event_names))
            clauses.append(f"event IN ({qs})")
            params.extend(filter.event_names)
        for col, val in (("target_entity_type", filter.target_entity_type),
                         ("target_entity_id", filter.target_entity_id)):
            if val is ANY:
                continue
            if val is None:
                clauses.append(f"{col} IS NULL")
            else:
                clauses.append(f"{col} = ?")
                params.append(val)
        where = (" WHERE " + " AND ".join(clauses)) if clauses else ""
        order = " ORDER BY event_time " + ("DESC" if filter.reversed else "ASC")
        lim = ""
        if filter.limit is not None and filter.limit >= 0:
            lim = " LIMIT ?"
            params.append(filter.limit)
        sql = (f"SELECT {self.EVENT_COLS} FROM "
               f"{_table(app_id, channel_id)}{where}{order}{lim}")
        with self.client.lock:
            try:
                cur = self._conn.execute(sql, params)
                rows: list = []
                while True:
                    # chunked fetch so a heavy scan honors filter.deadline
                    # instead of materializing everything first
                    filter.check_deadline()
                    chunk = cur.fetchmany(4096)
                    if not chunk:
                        break
                    rows.extend(chunk)
            except sqlite3.OperationalError as e:
                if "no such table" in str(e):
                    return iter(())
                raise
        return (_row_to_event(r) for r in rows)


def _row_to_event(row) -> Event:
    (eid, event, etype, eidd, tetype, teid, props, t, tags, pr_id, ct) = row
    return Event(
        event=event, entity_type=etype, entity_id=eidd,
        target_entity_type=tetype, target_entity_id=teid,
        properties=DataMap.from_json(props) if props else DataMap(),
        event_time=from_millis(t), tags=tuple(json.loads(tags or "[]")),
        pr_id=pr_id, creation_time=from_millis(ct), event_id=eid)


class _SQLiteMeta:
    """Shared setup for metadata DAOs."""

    DDL = """
        CREATE TABLE IF NOT EXISTS apps (
            id INTEGER PRIMARY KEY AUTOINCREMENT, name TEXT UNIQUE NOT NULL,
            description TEXT);
        CREATE TABLE IF NOT EXISTS access_keys (
            key TEXT PRIMARY KEY, app_id INTEGER NOT NULL, events TEXT);
        CREATE TABLE IF NOT EXISTS channels (
            id INTEGER PRIMARY KEY AUTOINCREMENT, name TEXT NOT NULL,
            app_id INTEGER NOT NULL);
        CREATE TABLE IF NOT EXISTS engine_instances (
            id TEXT PRIMARY KEY, status TEXT, start_time INT,
            end_time INT, engine_id TEXT, engine_version TEXT,
            engine_variant TEXT, engine_factory TEXT, batch TEXT,
            env TEXT, spark_conf TEXT, data_source_params TEXT,
            preparator_params TEXT, algorithms_params TEXT,
            serving_params TEXT);
        CREATE TABLE IF NOT EXISTS evaluation_instances (
            id TEXT PRIMARY KEY, status TEXT, start_time INT,
            end_time INT, evaluation_class TEXT,
            engine_params_generator_class TEXT, batch TEXT, env TEXT,
            spark_conf TEXT, evaluator_results TEXT,
            evaluator_results_html TEXT, evaluator_results_json TEXT);
        CREATE TABLE IF NOT EXISTS models (
            id TEXT PRIMARY KEY, models BLOB NOT NULL);
    """

    def __init__(self, client: SQLiteClient):
        self.client = client
        with client.lock:
            client.conn.executescript(self.DDL)
            client.conn.commit()

    def _exec(self, sql, params=()):
        with self.client.lock:
            cur = self.client.conn.execute(sql, params)
            self.client.conn.commit()
            return cur

    def _query(self, sql, params=()):
        with self.client.lock:
            return self.client.conn.execute(sql, params).fetchall()


class SQLiteApps(_SQLiteMeta, AppsDAO):
    def insert(self, app: App) -> Optional[int]:
        try:
            if app.id > 0:
                cur = self._exec(
                    "INSERT INTO apps (id, name, description) VALUES (?,?,?)",
                    (app.id, app.name, app.description))
            else:
                cur = self._exec(
                    "INSERT INTO apps (name, description) VALUES (?,?)",
                    (app.name, app.description))
            return cur.lastrowid
        except sqlite3.IntegrityError:
            return None

    def get(self, app_id: int) -> Optional[App]:
        rows = self._query("SELECT id,name,description FROM apps WHERE id=?",
                           (app_id,))
        return App(*rows[0]) if rows else None

    def get_by_name(self, name: str) -> Optional[App]:
        rows = self._query("SELECT id,name,description FROM apps WHERE name=?",
                           (name,))
        return App(*rows[0]) if rows else None

    def get_all(self) -> List[App]:
        return [App(*r) for r in
                self._query("SELECT id,name,description FROM apps ORDER BY id")]

    def update(self, app: App) -> None:
        self._exec("UPDATE apps SET name=?, description=? WHERE id=?",
                   (app.name, app.description, app.id))

    def delete(self, app_id: int) -> None:
        self._exec("DELETE FROM apps WHERE id=?", (app_id,))


class SQLiteAccessKeys(_SQLiteMeta, AccessKeysDAO):
    def insert(self, access_key: AccessKey) -> Optional[str]:
        key = access_key.key or self.generate_key()
        try:
            self._exec("INSERT INTO access_keys VALUES (?,?,?)",
                       (key, access_key.app_id,
                        json.dumps(list(access_key.events))))
            return key
        except sqlite3.IntegrityError:
            return None

    def get(self, key: str) -> Optional[AccessKey]:
        rows = self._query("SELECT * FROM access_keys WHERE key=?", (key,))
        if not rows:
            return None
        k, app_id, events = rows[0]
        return AccessKey(k, app_id, tuple(json.loads(events or "[]")))

    def get_all(self) -> List[AccessKey]:
        return [AccessKey(k, a, tuple(json.loads(ev or "[]")))
                for k, a, ev in self._query("SELECT * FROM access_keys")]

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [AccessKey(k, a, tuple(json.loads(ev or "[]")))
                for k, a, ev in self._query(
                    "SELECT * FROM access_keys WHERE app_id=?", (app_id,))]

    def update(self, access_key: AccessKey) -> None:
        self._exec("UPDATE access_keys SET app_id=?, events=? WHERE key=?",
                   (access_key.app_id, json.dumps(list(access_key.events)),
                    access_key.key))

    def delete(self, key: str) -> None:
        self._exec("DELETE FROM access_keys WHERE key=?", (key,))


class SQLiteChannels(_SQLiteMeta, ChannelsDAO):
    def insert(self, channel: Channel) -> Optional[int]:
        if not Channel.is_valid_name(channel.name):
            return None
        cur = self._exec("INSERT INTO channels (name, app_id) VALUES (?,?)",
                         (channel.name, channel.app_id))
        return cur.lastrowid

    def get(self, channel_id: int) -> Optional[Channel]:
        rows = self._query("SELECT id,name,app_id FROM channels WHERE id=?",
                           (channel_id,))
        return Channel(*rows[0]) if rows else None

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        return [Channel(*r) for r in self._query(
            "SELECT id,name,app_id FROM channels WHERE app_id=?", (app_id,))]

    def delete(self, channel_id: int) -> None:
        self._exec("DELETE FROM channels WHERE id=?", (channel_id,))


_EI_COLS = ("id,status,start_time,end_time,engine_id,engine_version,"
            "engine_variant,engine_factory,batch,env,spark_conf,"
            "data_source_params,preparator_params,algorithms_params,"
            "serving_params")


def _ei_from_row(r) -> EngineInstance:
    return EngineInstance(
        id=str(r[0]), status=r[1], start_time=from_millis(r[2]),
        end_time=from_millis(r[3]), engine_id=r[4], engine_version=r[5],
        engine_variant=r[6], engine_factory=r[7], batch=r[8],
        env=json.loads(r[9] or "{}"), spark_conf=json.loads(r[10] or "{}"),
        data_source_params=r[11], preparator_params=r[12],
        algorithms_params=r[13], serving_params=r[14])


class SQLiteEngineInstances(_SQLiteMeta, EngineInstancesDAO):
    def insert(self, i: EngineInstance) -> str:
        iid = i.id or new_event_id()
        self._exec(
            f"INSERT INTO engine_instances ({_EI_COLS}) "
            "VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
            (iid, i.status, to_millis(i.start_time), to_millis(i.end_time),
             i.engine_id, i.engine_version, i.engine_variant,
             i.engine_factory, i.batch, json.dumps(i.env),
             json.dumps(i.spark_conf), i.data_source_params,
             i.preparator_params, i.algorithms_params, i.serving_params))
        return iid

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        rows = self._query(
            f"SELECT {_EI_COLS} FROM engine_instances WHERE id=?",
            (instance_id,))
        return _ei_from_row(rows[0]) if rows else None

    def get_all(self) -> List[EngineInstance]:
        return [_ei_from_row(r) for r in
                self._query(f"SELECT {_EI_COLS} FROM engine_instances")]

    def get_completed(self, engine_id, engine_version, engine_variant):
        rows = self._query(
            f"SELECT {_EI_COLS} FROM engine_instances WHERE status=? AND "
            "engine_id=? AND engine_version=? AND engine_variant=? "
            "ORDER BY start_time DESC",
            (STATUS_COMPLETED, engine_id, engine_version, engine_variant))
        return [_ei_from_row(r) for r in rows]

    def update(self, i: EngineInstance) -> None:
        self._exec(
            "UPDATE engine_instances SET status=?, start_time=?, end_time=?, "
            "engine_id=?, engine_version=?, engine_variant=?, "
            "engine_factory=?, batch=?, env=?, spark_conf=?, "
            "data_source_params=?, preparator_params=?, algorithms_params=?, "
            "serving_params=? WHERE id=?",
            (i.status, to_millis(i.start_time), to_millis(i.end_time),
             i.engine_id, i.engine_version, i.engine_variant,
             i.engine_factory, i.batch, json.dumps(i.env),
             json.dumps(i.spark_conf), i.data_source_params,
             i.preparator_params, i.algorithms_params, i.serving_params,
             i.id))

    def delete(self, instance_id: str) -> None:
        self._exec("DELETE FROM engine_instances WHERE id=?", (instance_id,))


_EV_COLS = ("id,status,start_time,end_time,evaluation_class,"
            "engine_params_generator_class,batch,env,spark_conf,"
            "evaluator_results,evaluator_results_html,evaluator_results_json")


def _ev_from_row(r) -> EvaluationInstance:
    return EvaluationInstance(
        id=str(r[0]), status=r[1], start_time=from_millis(r[2]),
        end_time=from_millis(r[3]), evaluation_class=r[4],
        engine_params_generator_class=r[5], batch=r[6],
        env=json.loads(r[7] or "{}"), spark_conf=json.loads(r[8] or "{}"),
        evaluator_results=r[9], evaluator_results_html=r[10],
        evaluator_results_json=r[11])


class SQLiteEvaluationInstances(_SQLiteMeta, EvaluationInstancesDAO):
    def insert(self, i: EvaluationInstance) -> str:
        iid = i.id or new_event_id()
        self._exec(
            f"INSERT INTO evaluation_instances ({_EV_COLS}) "
            "VALUES (?,?,?,?,?,?,?,?,?,?,?,?)",
            (iid, i.status, to_millis(i.start_time), to_millis(i.end_time),
             i.evaluation_class, i.engine_params_generator_class, i.batch,
             json.dumps(i.env), json.dumps(i.spark_conf),
             i.evaluator_results, i.evaluator_results_html,
             i.evaluator_results_json))
        return iid

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        rows = self._query(
            f"SELECT {_EV_COLS} FROM evaluation_instances WHERE id=?",
            (instance_id,))
        return _ev_from_row(rows[0]) if rows else None

    def get_all(self) -> List[EvaluationInstance]:
        return [_ev_from_row(r) for r in
                self._query(f"SELECT {_EV_COLS} FROM evaluation_instances")]

    def get_completed(self) -> List[EvaluationInstance]:
        rows = self._query(
            f"SELECT {_EV_COLS} FROM evaluation_instances WHERE status=? "
            "ORDER BY start_time DESC", (STATUS_EVALCOMPLETED,))
        return [_ev_from_row(r) for r in rows]

    def update(self, i: EvaluationInstance) -> None:
        self._exec(
            "UPDATE evaluation_instances SET status=?, start_time=?, "
            "end_time=?, evaluation_class=?, engine_params_generator_class=?, "
            "batch=?, env=?, spark_conf=?, evaluator_results=?, "
            "evaluator_results_html=?, evaluator_results_json=? WHERE id=?",
            (i.status, to_millis(i.start_time), to_millis(i.end_time),
             i.evaluation_class, i.engine_params_generator_class, i.batch,
             json.dumps(i.env), json.dumps(i.spark_conf),
             i.evaluator_results, i.evaluator_results_html,
             i.evaluator_results_json, i.id))

    def delete(self, instance_id: str) -> None:
        self._exec("DELETE FROM evaluation_instances WHERE id=?",
                   (instance_id,))


class SQLiteModels(_SQLiteMeta, ModelsDAO):
    def insert(self, model: Model) -> None:
        self._exec("INSERT OR REPLACE INTO models VALUES (?,?)",
                   (model.id, model.models))

    def get(self, model_id: str) -> Optional[Model]:
        rows = self._query("SELECT id, models FROM models WHERE id=?",
                           (model_id,))
        return Model(rows[0][0], bytes(rows[0][1])) if rows else None

    def delete(self, model_id: str) -> None:
        self._exec("DELETE FROM models WHERE id=?", (model_id,))
