"""Storage contracts: event-log DAO and metadata DAOs.

Capability parity with the reference's storage layer:

- ``EventStore`` is the event-log DAO contract, the analogue of ``LEvents``
  (``data/.../storage/LEvents.scala:40-513``: init/remove/close, insert,
  batch insert, get, delete, find with the full filter set, aggregate).
  The reference also had a Spark-RDD flavor (``PEvents.scala:38-189``);
  here a single contract serves both roles — bulk training reads go through
  :meth:`EventStore.find` into columnar host shards (see
  ``predictionio_tpu.data.columnar``) instead of RDD partitions.
- Metadata entities/DAOs mirror ``Apps.scala:32``, ``AccessKeys.scala:35``,
  ``Channels.scala:32``, ``EngineInstances.scala:46``,
  ``EvaluationInstances.scala`` and ``Models.scala:33``.

The reference made every event call async (Scala Futures) because JVM
threads were cheap and storage remote; here the core contract is synchronous
and the REST servers wrap calls in executor threads — simpler, and the hot
training path reads in bulk anyway.
"""

from __future__ import annotations

import abc
import base64
import uuid
from dataclasses import dataclass, field, replace
from datetime import datetime
from typing import Any, Dict, Iterable, Iterator, List, Optional, \
    Sequence, Tuple

from ..datamap import PropertyMap
from ..event import Event

#: Sentinel for "no filter" on nullable fields, distinguishing "match any"
#: from "match None" (the reference's Option[Option[String]] trick,
#: ``LEvents.scala:188``).
ANY: Any = ...


@dataclass(frozen=True)
class EventFilter:
    """Filter set of ``LEvents.futureFind`` (``LEvents.scala:188-214``)."""

    start_time: Optional[datetime] = None
    until_time: Optional[datetime] = None
    entity_type: Optional[str] = None
    entity_id: Optional[str] = None
    event_names: Optional[Sequence[str]] = None
    target_entity_type: Any = ANY  # ANY | None | str
    target_entity_id: Any = ANY
    limit: Optional[int] = None
    reversed: bool = False
    #: Optional ``time.monotonic()`` deadline. Backends check it *inside*
    #: their scan loops and raise :class:`TimeoutError` — the role of the
    #: reference's bounded ``Await.result(..., timeout)``
    #: (``LEventStore.scala:76-120``); serving-time filters must degrade
    #: within their latency budget, not after materializing a heavy scan.
    deadline: Optional[float] = None

    def check_deadline(self) -> None:
        import time
        if self.deadline is not None and time.monotonic() > self.deadline:
            raise TimeoutError("event scan exceeded its deadline")

    def apply(self, events: Iterable[Event]) -> Iterator[Event]:
        """Yield matching events, checking the deadline every 4096 — the
        one scan loop every in-process backend shares."""
        for i, e in enumerate(events):
            if i % 4096 == 0:
                self.check_deadline()
            if self.matches(e):
                yield e

    def matches(self, e: Event) -> bool:
        if self.start_time is not None and e.event_time < self.start_time:
            return False
        if self.until_time is not None and e.event_time >= self.until_time:
            return False
        if self.entity_type is not None and e.entity_type != self.entity_type:
            return False
        if self.entity_id is not None and e.entity_id != self.entity_id:
            return False
        if self.event_names is not None and e.event not in self.event_names:
            return False
        if self.target_entity_type is not ANY \
                and e.target_entity_type != self.target_entity_type:
            return False
        if self.target_entity_id is not ANY \
                and e.target_entity_id != self.target_entity_id:
            return False
        return True


class StorageError(RuntimeError):
    pass


def _open_jsonl(source) -> Any:
    """``import_jsonl`` source normalization: a path opens binary (a
    missing file raises a clean OSError *before* any try/wrap), bytes
    become an in-memory stream (the storage server's forwarded
    blocks)."""
    import io
    if isinstance(source, (bytes, bytearray)):
        return io.BytesIO(bytes(source))
    return open(source, "rb")


def iter_jsonl_blocks(f, block_size: int) -> Iterator[Tuple[bytes, int]]:
    """Split a binary stream into blocks of WHOLE lines (the bulk
    import lanes' shared reader): yields ``(buf, nlines)`` where buf
    ends at a line boundary and nlines counts the lines consumed —
    including blank ones, so callers' durable-prefix line accounting
    matches the file. A line longer than ``block_size`` is carried
    until its newline arrives; a final unterminated line still counts
    as one."""
    carry = b""
    while True:
        block = f.read(block_size)
        if not block and not carry:
            return
        buf = carry + block
        if block:
            cut = buf.rfind(b"\n")
            if cut < 0:  # a line longer than the block
                carry = buf
                continue
            buf, carry = buf[:cut + 1], buf[cut + 1:]
        else:
            carry = b""
        yield buf, (buf.count(b"\n") or 1)


class JsonlImportError(Exception):
    """A bulk JSONL import failed partway. ``lineno`` is where it
    failed, ``committed_lines``/``committed_events`` how far the
    durable prefix reaches (re-importing the whole file would
    duplicate that prefix under fresh ids)."""

    def __init__(self, lineno: int, committed_lines: int,
                 committed_events: int, cause: BaseException):
        super().__init__(
            f"import failed near line {lineno}: {cause}")
        self.lineno = lineno
        self.committed_lines = committed_lines
        self.committed_events = committed_events
        self.cause = cause


class EventStore(abc.ABC):
    """Append-only event log, partitioned by (app_id, channel_id)."""

    @abc.abstractmethod
    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        """Initialize storage for an app/channel (create tables etc.)."""

    @abc.abstractmethod
    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        """Remove all events of an app/channel."""

    @abc.abstractmethod
    def close(self) -> None:
        """Release client resources."""

    @abc.abstractmethod
    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        """Insert one event, returning its event id."""

    def insert_batch(self, events: Sequence[Event], app_id: int,
                     channel_id: Optional[int] = None) -> List[str]:
        """Insert many events (``LEvents.futureInsertBatch``); backends may
        override with a faster bulk path.

        Contract: **all-or-nothing.** The event server's poison-batch
        fallback retries per event after a failed batch, so a partial
        commit would duplicate the committed prefix under fresh ids.
        Transactional backends get this from their transaction; this
        default compensates before re-raising: fresh inserts are
        deleted, and an insert that REPLACED an existing event (same
        explicit event_id) gets its prior version re-inserted — the
        store must look as if the batch never happened."""
        done: list = []
        priors: dict = {}
        try:
            for e in events:
                if e.event_id and e.event_id not in priors:
                    priors[e.event_id] = self.get(e.event_id, app_id,
                                                  channel_id)
                done.append(self.insert(e, app_id, channel_id))
        except Exception:
            for eid in reversed(done):
                try:
                    prior = priors.get(eid)
                    if prior is not None:
                        self.insert(prior, app_id, channel_id)
                    else:
                        self.delete(eid, app_id, channel_id)
                except Exception:  # noqa: BLE001 — best-effort rollback
                    pass
            raise
        return done

    def insert_columnar(self, batch, app_id: int,
                        channel_id: Optional[int] = None) -> int:
        """Bulk-write an arrow-style column block (ISSUE 19,
        docs/streaming.md): ``batch`` is a
        :class:`~predictionio_tpu.data.columnar.ColumnarBatch` — the
        zero-copy ingest wire format — landed in one shot instead of a
        per-event object stream. Returns the rows written.

        Contract matches :meth:`insert_batch`: **all-or-nothing**, and
        rows with no explicit event id get fresh ids. This default
        decodes to :class:`Event` objects and rides ``insert_batch`` —
        correct (and equally durable) on every backend; columnar
        backends override with a vectorized path that never
        materializes the per-event objects."""
        events = list(batch.to_events())
        self.insert_batch(events, app_id, channel_id)
        return len(events)

    def import_jsonl(self, source, app_id: int,
                     channel_id: Optional[int] = None,
                     chunk: int = 100_000) -> int:
        """Bulk-load API-format JSON lines (``pio import``,
        ``tools/imprt/FileToEvents.scala``) from a file path or a
        bytes block, committing every ``chunk`` events via
        :meth:`insert_batch` (all-or-nothing per chunk). Returns the
        number of events imported; on failure raises
        :class:`JsonlImportError` carrying how far the durable prefix
        reaches so the caller can print a resume recipe. Backends with
        a bulk encode lane (segmentfs + the native codec) override
        this."""
        import json as _json

        total = 0
        lineno = 0
        committed = 0  # last LINE NUMBER fully committed
        events: List[Event] = []
        f = _open_jsonl(source)
        try:
            with f:
                for raw in f:
                    lineno += 1
                    line = raw.decode("utf-8").strip()
                    if line:
                        events.append(Event.from_json(_json.loads(line)))
                    if len(events) >= chunk:
                        self.insert_batch(events, app_id, channel_id)
                        total += len(events)
                        committed = lineno
                        events = []
            if events:
                self.insert_batch(events, app_id, channel_id)
                total += len(events)
        except Exception as e:  # noqa: BLE001 — report durable progress
            raise JsonlImportError(lineno, committed, total, e) from e
        return total

    @abc.abstractmethod
    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        """Get an event by id."""

    @abc.abstractmethod
    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        """Delete an event by id; True if it existed."""

    @abc.abstractmethod
    def find(self, app_id: int, channel_id: Optional[int] = None,
             filter: EventFilter = EventFilter()) -> Iterator[Event]:
        """Stream events matching the filter, in event-time order
        (reversed when ``filter.reversed``)."""

    def warm_columnar(self, app_id: int,
                      channel_id: Optional[int] = None) -> bool:
        """Build/refresh this log's persistent columnar sidecar NOW,
        so the first training read doesn't pay the one-time encode
        (measured: 176s of a 299s first ``ptpu train`` at ML-20M was
        the sidecar build — an ingest-time cost that belongs to
        ``pio import``, which already parsed every byte). Returns True
        when a persistent sidecar was (re)synced; the default no-op
        returns False for backends whose columnar reads have no
        persistent form to warm."""
        return False

    def find_columnar(self, app_id: int, channel_id: Optional[int] = None,
                      filter: EventFilter = EventFilter(),
                      float_props: Sequence[str] = ("rating",),
                      ordered: bool = True, with_props: bool = True,
                      shard: Optional[Tuple[int, int]] = None):
        """Bulk columnar read — the ``PEvents`` role
        (``data/.../storage/PEvents.scala:38-189``): the whole matching log
        as dictionary-encoded numpy columns ready for device transfer,
        instead of a per-event Python object stream. Backends with a
        persistent columnar sidecar (SQLite) override this; the default
        encodes from :meth:`find`, which is correct everywhere.

        ``shard=(i, n)`` is the partitioned-scan contract
        (``JDBCPEvents.scala:49-89``'s time-range split, done by row
        range): the UNFILTERED storage-order projection is tiled into
        ``n`` contiguous ranges by ``ColumnarBatch.shard_bounds`` and
        only range ``i`` is returned — filter/ordering then apply WITHIN
        the shard, so the union over all shards of a filtered read
        equals the unsharded filtered read. Backends push the range
        down (mmap page ranges, SQL row ranges, an HTTP row-range
        request); this default slices after a full local encode, which
        is correct but saves no IO. The returned batch carries
        ``shard_offset`` (global storage-row index of its first row)
        and ``shard_total`` (global unfiltered row count) so callers
        can reconstruct global row positions."""
        from ..columnar import columnar_from_events
        batch = columnar_from_events(
            self.find(app_id, channel_id,
                      EventFilter() if shard is not None else filter),
            float_props=float_props)
        if shard is None:
            return batch
        return self._shard_and_select(batch, shard, filter,
                                      ordered=ordered,
                                      with_props=with_props)

    @staticmethod
    def _shard_and_select(batch, shard: Tuple[int, int],
                          filter: EventFilter, *,
                          ordered: bool, with_props: bool):
        """Shared tail of every backend's ``shard=`` path: slice shard
        ``i`` of ``n`` off the full unfiltered projection (zero-copy),
        apply the filter within it, and stamp ``shard_offset`` /
        ``shard_total`` (global row position bookkeeping for the
        multihost feeding layer; positions are meaningful for the
        unordered training read — an ``ordered=True`` select reorders
        rows within the shard)."""
        from ..columnar import ColumnarBatch
        i, n = shard
        if not 0 <= i < n:
            raise ValueError(f"shard {i} of {n}")
        bounds = ColumnarBatch.shard_bounds(batch.n, n)
        lo, hi = int(bounds[i]), int(bounds[i + 1])
        sub = batch.slice_rows(lo, hi, with_props=with_props)
        sub = sub.select(filter, ordered=ordered, with_props=with_props)
        sub.shard_offset = lo
        sub.shard_total = batch.n
        return sub

    def aggregate_properties(
            self, app_id: int, channel_id: Optional[int] = None,
            *, entity_type: str, start_time: Optional[datetime] = None,
            until_time: Optional[datetime] = None,
            required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        """Replay ``$set/$unset/$delete`` into current per-entity properties
        (``LEvents.futureAggregateProperties``, ``LEvents.scala:215-278``)."""
        from ..aggregation import AGGREGATION_EVENTS, aggregate_properties
        events = self.find(app_id, channel_id, EventFilter(
            start_time=start_time, until_time=until_time,
            entity_type=entity_type, event_names=list(AGGREGATION_EVENTS)))
        result = aggregate_properties(events)
        if required:
            req = set(required)
            result = {k: v for k, v in result.items() if req <= set(v.keys())}
        return result

    def write(self, events: Iterable[Event], app_id: int,
              channel_id: Optional[int] = None) -> None:
        """Bulk write (the ``PEvents.write`` role, ``PEvents.scala:172-185``)."""
        batch: List[Event] = []
        for e in events:
            batch.append(e)
            if len(batch) >= 1000:
                self.insert_batch(batch, app_id, channel_id)
                batch = []
        if batch:
            self.insert_batch(batch, app_id, channel_id)


# ---------------------------------------------------------------------------
# Metadata entities
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class App:
    """``data/.../storage/Apps.scala:32``"""
    id: int
    name: str
    description: Optional[str] = None


@dataclass(frozen=True)
class AccessKey:
    """``data/.../storage/AccessKeys.scala:35``; empty ``events`` means all
    event names are allowed."""
    key: str
    app_id: int
    events: Sequence[str] = ()


@dataclass(frozen=True)
class Channel:
    """``data/.../storage/Channels.scala:32``; name validity: 1-16 chars,
    alphanumeric and dashes (``Channels.scala:70``)."""
    id: int
    name: str
    app_id: int

    @staticmethod
    def is_valid_name(s: str) -> bool:
        import re
        return bool(re.fullmatch(r"[a-zA-Z0-9-]{1,16}", s))


#: EngineInstance / EvaluationInstance lifecycle states
#: (``EngineInstances.scala``: INIT → COMPLETED; eval: EVALCOMPLETED).
STATUS_INIT = "INIT"
STATUS_COMPLETED = "COMPLETED"
STATUS_EVALCOMPLETED = "EVALCOMPLETED"


@dataclass(frozen=True)
class EngineInstance:
    """A training run (``data/.../storage/EngineInstances.scala:46-66``)."""
    id: str
    status: str
    start_time: datetime
    end_time: datetime
    engine_id: str
    engine_version: str
    engine_variant: str
    engine_factory: str
    batch: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    spark_conf: Dict[str, str] = field(default_factory=dict)
    data_source_params: str = ""
    preparator_params: str = ""
    algorithms_params: str = ""
    serving_params: str = ""

    def copy(self, **changes: Any) -> "EngineInstance":
        return replace(self, **changes)


@dataclass(frozen=True)
class EvaluationInstance:
    """An evaluation run (``data/.../storage/EvaluationInstances.scala``)."""
    id: str
    status: str
    start_time: datetime
    end_time: datetime
    evaluation_class: str = ""
    engine_params_generator_class: str = ""
    batch: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    spark_conf: Dict[str, str] = field(default_factory=dict)
    evaluator_results: str = ""
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""

    def copy(self, **changes: Any) -> "EvaluationInstance":
        return replace(self, **changes)


#: Model-blob ids starting with this prefix are RESERVED for framework
#: metadata riding the MODELDATA repository — today the release
#: registry's state documents (``predictionio_tpu.rollout.registry``).
#: Engine-instance ids (uuids / DAO-assigned integers) never collide
#: with it, and tooling that enumerates or garbage-collects model
#: blobs must skip reserved keys.
RESERVED_MODEL_KEY_PREFIX = "__release__"


@dataclass(frozen=True)
class Model:
    """A persisted model blob keyed by engine-instance id
    (``data/.../storage/Models.scala:33``); ids under
    :data:`RESERVED_MODEL_KEY_PREFIX` carry framework metadata instead
    of model bytes."""
    id: str
    models: bytes


# ---------------------------------------------------------------------------
# Metadata DAO contracts
# ---------------------------------------------------------------------------

class AppsDAO(abc.ABC):
    @abc.abstractmethod
    def insert(self, app: App) -> Optional[int]: ...
    @abc.abstractmethod
    def get(self, app_id: int) -> Optional[App]: ...
    @abc.abstractmethod
    def get_by_name(self, name: str) -> Optional[App]: ...
    @abc.abstractmethod
    def get_all(self) -> List[App]: ...
    @abc.abstractmethod
    def update(self, app: App) -> None: ...
    @abc.abstractmethod
    def delete(self, app_id: int) -> None: ...


class AccessKeysDAO(abc.ABC):
    @abc.abstractmethod
    def insert(self, access_key: AccessKey) -> Optional[str]:
        """Insert; if ``key`` is empty, generate one (reference generates
        url-safe base64 of a UUID, ``AccessKeys.scala:46``)."""
    @abc.abstractmethod
    def get(self, key: str) -> Optional[AccessKey]: ...
    @abc.abstractmethod
    def get_all(self) -> List[AccessKey]: ...
    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> List[AccessKey]: ...
    @abc.abstractmethod
    def update(self, access_key: AccessKey) -> None: ...
    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    @staticmethod
    def generate_key() -> str:
        return base64.urlsafe_b64encode(uuid.uuid4().bytes).decode().rstrip("=")


class ChannelsDAO(abc.ABC):
    @abc.abstractmethod
    def insert(self, channel: Channel) -> Optional[int]: ...
    @abc.abstractmethod
    def get(self, channel_id: int) -> Optional[Channel]: ...
    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> List[Channel]: ...
    @abc.abstractmethod
    def delete(self, channel_id: int) -> None: ...


class EngineInstancesDAO(abc.ABC):
    @abc.abstractmethod
    def insert(self, instance: EngineInstance) -> str: ...
    @abc.abstractmethod
    def get(self, instance_id: str) -> Optional[EngineInstance]: ...
    @abc.abstractmethod
    def get_all(self) -> List[EngineInstance]: ...
    @abc.abstractmethod
    def update(self, instance: EngineInstance) -> None: ...
    @abc.abstractmethod
    def delete(self, instance_id: str) -> None: ...

    @abc.abstractmethod
    def get_completed(self, engine_id: str, engine_version: str,
                      engine_variant: str) -> List[EngineInstance]:
        """COMPLETED instances, latest start-time first
        (``EngineInstances.scala:74-81``)."""

    def get_latest_completed(self, engine_id: str, engine_version: str,
                             engine_variant: str) -> Optional[EngineInstance]:
        """``EngineInstances.getLatestCompleted`` (:83-91)."""
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None


class EvaluationInstancesDAO(abc.ABC):
    @abc.abstractmethod
    def insert(self, instance: EvaluationInstance) -> str: ...
    @abc.abstractmethod
    def get(self, instance_id: str) -> Optional[EvaluationInstance]: ...
    @abc.abstractmethod
    def get_all(self) -> List[EvaluationInstance]: ...
    @abc.abstractmethod
    def get_completed(self) -> List[EvaluationInstance]: ...
    @abc.abstractmethod
    def update(self, instance: EvaluationInstance) -> None: ...
    @abc.abstractmethod
    def delete(self, instance_id: str) -> None: ...


class ModelsDAO(abc.ABC):
    @abc.abstractmethod
    def insert(self, model: Model) -> None: ...
    @abc.abstractmethod
    def get(self, model_id: str) -> Optional[Model]: ...
    @abc.abstractmethod
    def delete(self, model_id: str) -> None: ...
