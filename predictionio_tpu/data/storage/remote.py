"""REMOTE storage backend: EventStore + metadata DAOs over HTTP.

The client half of the network-capable storage story (server:
``server/storageserver.py``) — the role of the reference's JDBC /
Elasticsearch / HBase sources (``JDBCLEvents.scala:109-247``,
``ESLEvents.scala:106-150``): a TPU pod host with no shared filesystem
reaches the event store over the network. Configure via the standard
env scheme::

    PIO_STORAGE_SOURCES_NET_TYPE=remote
    PIO_STORAGE_SOURCES_NET_URL=http://storage-host:7077
    PIO_STORAGE_SOURCES_NET_SECRET=...            # optional
    PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE=NET

The bulk training read (:meth:`RemoteEventStore.find_columnar`) pulls
the server's columnar sidecar as ONE ``.npz`` payload and caches it by
``ETag`` — steady-state reads cost a single 304 round-trip, and filter
pushdown then runs locally over the cached columns (same vectorized
``ColumnarBatch.select`` every other backend uses).
"""

from __future__ import annotations

import base64
import json
import os
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Iterator, List, Optional, Sequence

from ...faults import declare, fire
from ...utils.retrying import RetryPolicy, retry_call
from ..event import Event
from .base import (
    AccessKeysDAO,
    AppsDAO,
    ChannelsDAO,
    EngineInstancesDAO,
    EvaluationInstancesDAO,
    EventFilter,
    EventStore,
    Model,
    ModelsDAO,
    StorageError,
)
from .wire import (
    batch_from_npz,
    entity_from_doc,
    entity_to_doc,
    filter_to_doc,
)


F_REMOTE = declare("storage.remote",
                   "one HTTP round trip of the remote-storage client "
                   "(op=/path= label the request)")


class _Transient(Exception):
    """Internal retry marker wrapping a retryable StorageError."""

    def __init__(self, error: StorageError):
        super().__init__(str(error))
        self.error = error


class RemoteClient:
    """One storage-server endpoint + connection policy (shared by the
    DAOs of a source)."""

    def __init__(self, url: str, secret: Optional[str] = None,
                 timeout: float = 60.0, retries: int = 2):
        self.url = url.rstrip("/")
        self.secret = secret
        self.timeout = timeout
        self.retries = retries
        #: (app_id, channel, props, float_props) → (etag, batch)
        self.columnar_cache: dict = {}
        self.lock = threading.Lock()

    @staticmethod
    def from_config(cfg: dict) -> "RemoteClient":
        url = cfg.get("URL") or cfg.get("url")
        if not url:
            raise ValueError("REMOTE source needs a URL property "
                             "(PIO_STORAGE_SOURCES_<NAME>_URL)")
        return RemoteClient(
            url, secret=cfg.get("SECRET"),
            timeout=float(cfg.get("TIMEOUT", 60.0)))

    def request(self, method: str, path: str, body: Optional[bytes] = None,
                headers: Optional[dict] = None,
                timeout: Optional[float] = None,
                idempotent: bool = True):
        """(status, headers, body). Connection errors retry with
        bounded exponential backoff (:mod:`~...utils.retrying`) ONLY
        for ``idempotent`` requests — a lost RESPONSE means the server
        may have committed, so a blind replay of a non-idempotent call
        (e.g. a metadata insert that auto-assigns ids) would duplicate
        it. Event inserts stay retryable because the client assigns
        event ids up front (replays become id-keyed upserts). A 503
        from the server (its backing store down, ISSUE 11) is retryable
        the same way — the server told us to come back."""
        fire(F_REMOTE, op=method, path=path)
        hdrs = {"Content-Type": "application/json"}
        if self.secret:
            hdrs["X-PIO-Storage-Secret"] = self.secret
        hdrs.update(headers or {})

        def attempt():
            req = urllib.request.Request(
                self.url + path, data=body, method=method, headers=hdrs)
            try:
                with urllib.request.urlopen(
                        req, timeout=timeout or self.timeout) as resp:
                    return resp.status, dict(resp.headers), resp.read()
            except urllib.error.HTTPError as e:
                if e.code == 304:
                    return 304, dict(e.headers), b""
                detail = ""
                try:
                    detail = json.loads(e.read().decode()).get("message", "")
                except Exception:  # noqa: BLE001
                    pass
                err = StorageError(
                    f"storage server {e.code} on {path}: {detail}")
                err.status = e.code  # callers branch on 404 (version skew)
                if e.code == 503 and idempotent:
                    raise _Transient(err) from e
                raise err from e
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                raise _Transient(StorageError(
                    f"storage server unreachable at {self.url}: {e}")) \
                    from e

        policy = RetryPolicy(
            max_attempts=(self.retries + 1) if idempotent else 1,
            base_ms=200.0, cap_ms=2000.0)
        try:
            return retry_call(attempt, policy=policy,
                              retry_on=(_Transient,))
        except _Transient as t:
            raise t.error from t

    def rpc(self, path: str, doc: Optional[dict] = None,
            idempotent: bool = True) -> dict:
        _, _, body = self.request(
            "POST", path, json.dumps(doc or {}).encode(),
            idempotent=idempotent)
        return json.loads(body.decode()) if body else {}

    def close(self) -> None:
        pass


class RemoteEventStore(EventStore):
    def __init__(self, client: RemoteClient):
        self.c = client

    def _base(self, app_id: int,
              channel_id: Optional[int]) -> "tuple[str, str]":
        # `is not None`: channel 0 must reach the server, not alias the
        # default channel
        q = (f"?channel={channel_id}" if channel_id is not None else "")
        return f"/v1/events/{app_id}", q

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        base, q = self._base(app_id, channel_id)
        return bool(self.c.rpc(f"{base}/init{q}").get("ok"))

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        base, q = self._base(app_id, channel_id)
        ok = bool(self.c.rpc(f"{base}/remove{q}").get("ok"))
        with self.c.lock:
            self.c.columnar_cache = {
                k: v for k, v in self.c.columnar_cache.items()
                if k[0] != app_id or k[1] != channel_id}
        return ok

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(self, events: Sequence[Event], app_id: int,
                     channel_id: Optional[int] = None) -> List[str]:
        from ..event import new_event_id

        base, q = self._base(app_id, channel_id)
        # assign event ids CLIENT-side: a retried batch whose first
        # attempt committed but lost its response then replays as an
        # id-keyed upsert instead of duplicating every event
        events = [e if e.event_id else e.copy(event_id=new_event_id())
                  for e in events]
        doc = [e.to_json() for e in events]
        return self.c.rpc(f"{base}/batch{q}", doc).get("ids", [])

    def insert_columnar(self, batch, app_id: int,
                        channel_id: Optional[int] = None) -> int:
        """Block ingest: ship the batch as one npz POST — the server's
        backend writes it in a single transaction (all-or-nothing per
        POST). NOT auto-retried: block rows get server-assigned event
        ids, so a replay after a lost response would duplicate the
        block — callers own the redelivery decision."""
        from .wire import batch_to_npz

        base, q = self._base(app_id, channel_id)
        _, _, body = self.c.request(
            "POST", f"{base}/columnar{q}", batch_to_npz(batch),
            headers={"Content-Type": "application/octet-stream"},
            idempotent=False)
        return int(json.loads(body.decode()).get("accepted", 0))

    def import_jsonl(self, source, app_id: int,
                     channel_id: Optional[int] = None,
                     chunk: int = 100_000) -> int:
        """Bulk import by forwarding raw JSONL blocks to the storage
        server (one ``import_jsonl`` POST per ~8 MB of whole lines),
        where the backing store's native lane does the parse/encode —
        instead of per-event JSON marshalling over ``/batch``. The
        server commits each POST all-or-nothing, so the durable prefix
        is exactly the acknowledged blocks.

        Idempotency follows insert_batch's client-assigned-id rule:
        every object line gets an ``eventId`` spliced in FIRST position
        (a duplicate key in JSON parses last-wins, so a line's own
        eventId still takes precedence) — a retried block whose first
        attempt committed but lost its response replays as id-keyed
        upserts, never duplicates. Residual window: if the server
        commits, the response is lost, AND the server stays down past
        the transport retries, the durable prefix over-counts by at
        most one block; a manual resume then duplicates that block
        (fresh splice ids). The error's cause names the transport
        failure so an operator can check the server before resuming."""
        from .base import JsonlImportError, _open_jsonl, \
            iter_jsonl_blocks
        from ..event import new_event_id

        base, q = self._base(app_id, channel_id)
        block_size = int(os.environ.get("PIO_IMPORT_BLOCK",
                                        str(8 << 20)))
        total = 0
        lineno = 0  # lines fully consumed == committed (block commits)
        f = _open_jsonl(source)  # missing file: clean OSError
        try:
            with f:
                for buf, nlines in iter_jsonl_blocks(f, block_size):
                    spliced = bytearray()
                    # split on \n ONLY: splitlines() also cuts on
                    # \x0b/\x0c/\x1c..., which would diverge from the
                    # local lanes' line accounting (and silently split
                    # one malformed physical line into two events).
                    # Interior blank lines stay as newlines so server-
                    # side error linenos remain block-relative.
                    pieces = buf.split(b"\n")
                    if pieces and pieces[-1] == b"":
                        pieces.pop()  # trailing \n, not a blank line
                    for raw in pieces:
                        s = raw.strip()
                        if s.startswith(b"{"):
                            if b'"eventId"' in s:
                                # an explicit "eventId": null would
                                # override the spliced id (duplicate-
                                # key last-wins) and make the server
                                # mint fresh random ids on every
                                # transport replay — drop the null key
                                # so the splice governs (ADVICE r4);
                                # only lines carrying the substring pay
                                # the parse
                                try:
                                    obj = json.loads(s)
                                    if isinstance(obj, dict) and \
                                            obj.get("eventId",
                                                    "") is None:
                                        del obj["eventId"]
                                        s = json.dumps(
                                            obj, ensure_ascii=False
                                        ).encode("utf-8")
                                except ValueError:
                                    pass  # malformed: server reports
                            rest = s[1:].lstrip()
                            eid = new_event_id().encode()
                            sep = b'"' if rest.startswith(b"}") \
                                else b'", '
                            spliced += (b'{"eventId": "' + eid + sep +
                                        s[1:])
                        else:
                            spliced += s
                        spliced += b"\n"
                    try:
                        _, _, body = self.c.request(
                            "POST", f"{base}/import_jsonl{q}",
                            bytes(spliced),
                            headers={"Content-Type":
                                     "application/x-ndjson"})
                    except StorageError as se:
                        if getattr(se, "status", None) == 404 \
                                and lineno == 0:
                            # older storage server without the bulk
                            # endpoint: nothing committed yet, so the
                            # inherited per-event lane can run the
                            # whole file from the top
                            return super().import_jsonl(
                                source, app_id, channel_id, chunk)
                        raise
                    doc = json.loads(body.decode())
                    err = doc.get("error")
                    if err is not None:
                        raise JsonlImportError(
                            lineno + err["lineno"],
                            lineno + err["committed_lines"],
                            total + err["committed_events"],
                            StorageError(err["message"]))
                    total += doc["imported"]
                    lineno += nlines
        except JsonlImportError:
            raise
        except Exception as e:  # noqa: BLE001 — durable-prefix report
            # (request() already replayed transport retries with the
            # SAME spliced ids, so the prefix really is `lineno` lines)
            raise JsonlImportError(lineno, lineno, total, e) from e
        return total

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        base, q = self._base(app_id, channel_id)
        sep = "&" if q else "?"
        _, _, body = self.c.request(
            "GET", f"{base}/get{q}{sep}id={urllib.parse.quote(event_id)}")
        d = json.loads(body.decode()).get("event")
        return Event.from_json(d) if d else None

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        base, q = self._base(app_id, channel_id)
        return bool(self.c.rpc(f"{base}/delete{q}",
                               {"id": event_id}).get("ok"))

    def find(self, app_id: int, channel_id: Optional[int] = None,
             filter: EventFilter = EventFilter()) -> Iterator[Event]:
        base, q = self._base(app_id, channel_id)
        timeout = None
        if filter.deadline is not None:
            timeout = max(filter.deadline - time.monotonic(), 0.001)
        _, _, body = self.c.request(
            "POST", f"{base}/find{q}",
            json.dumps(filter_to_doc(filter)).encode(), timeout=timeout)
        return iter([Event.from_json(d)
                     for d in json.loads(body.decode())["events"]])

    def find_columnar(self, app_id: int, channel_id: Optional[int] = None,
                      filter: EventFilter = EventFilter(),
                      float_props: Sequence[str] = ("rating",),
                      ordered: bool = True, with_props: bool = True,
                      shard=None):
        """``shard=(i, n)`` is pushed down as an HTTP row-range request
        (``shard_i``/``shard_n``): the server slices its mmap'd
        projection and ships ONLY this shard's bytes, with a PER-SHARD
        ETag — an N-host pod transfers the log once in aggregate, not N
        times (VERDICT r3 missing #1; the ``JDBCPEvents.scala:49-89``
        partitioned-scan role over the wire)."""
        base, q = self._base(app_id, channel_id)
        sep = "&" if q else "?"
        # the wire protocol is comma-separated, so ',' in a name is
        # unrepresentable — reject it rather than silently request
        # different columns; quote() guards '&'/'='/spaces (the sqlite
        # path gates names to alnum/underscore; remote must not be the
        # one backend where a crafted name rewrites the query string)
        for p in float_props:
            if "," in p:
                raise ValueError(
                    f"float prop name may not contain ',': {p!r}")
        key = (app_id, channel_id, with_props, tuple(float_props),
               None if shard is None else tuple(shard))
        with self.c.lock:
            etag, cached = self.c.columnar_cache.get(key, (None, None))
        headers = {"If-None-Match": etag} if etag else {}
        fp_q = ",".join(urllib.parse.quote(p, safe="")
                        for p in float_props)
        path = (f"{base}/columnar{q}{sep}props="
                f"{'1' if with_props else '0'}"
                f"&float_props={fp_q}")
        if shard is not None:
            if not 0 <= int(shard[0]) < int(shard[1]):
                raise ValueError(f"shard {shard[0]} of {shard[1]}")
            path += f"&shard_i={int(shard[0])}&shard_n={int(shard[1])}"
        status, resp_headers, body = self.c.request(
            "GET", path, headers=headers)
        lower = {k.lower(): v for k, v in resp_headers.items()}
        if status == 304 and cached is not None:
            batch = cached
        else:
            batch = batch_from_npz(body)
            if shard is not None:
                if "x-shard-total" not in lower:
                    # a pre-shard server ignores the query params and
                    # returns the FULL log — treating that as a shard
                    # would feed every rating N times across a pod
                    # (silently wrong factors). Fail loudly.
                    raise StorageError(
                        "storage server ignored the shard request "
                        "(no X-Shard-Total header) — server too old "
                        "for shard pushdown; upgrade it or read "
                        "unsharded")
                batch.shard_offset = int(lower["x-shard-offset"])
                batch.shard_total = int(lower["x-shard-total"])
            with self.c.lock:
                self.c.columnar_cache[key] = (lower.get("etag"), batch)
        out = batch.select(filter, ordered=ordered,
                           with_props=with_props)
        if shard is not None and out is not batch:
            # select returns a fresh view; carry the global-row
            # bookkeeping across it
            out.shard_offset = getattr(batch, "shard_offset", 0)
            out.shard_total = getattr(batch, "shard_total", batch.n)
        return out

    def aggregate_properties(self, app_id: int,
                             channel_id: Optional[int] = None, *,
                             entity_type: str, start_time=None,
                             until_time=None, required=None):
        from ..datamap import PropertyMap

        base, q = self._base(app_id, channel_id)
        doc = {
            "entity_type": entity_type,
            "start_time": start_time.isoformat() if start_time else None,
            "until_time": until_time.isoformat() if until_time else None,
            "required": list(required) if required else None,
        }
        from datetime import datetime

        props = self.c.rpc(f"{base}/aggregate{q}", doc)["properties"]
        return {k: PropertyMap(
            v["fields"],
            first_updated=datetime.fromisoformat(v["first_updated"]),
            last_updated=datetime.fromisoformat(v["last_updated"]))
            for k, v in props.items()}


class _RemoteDAO:
    DAO = ""

    def __init__(self, client: RemoteClient):
        self.c = client

    def _rpc(self, method: str, *args, entity=None):
        doc: dict = {"args": list(args)}
        if entity is not None:
            doc["entity"] = entity_to_doc(entity)
        # metadata inserts auto-assign ids server-side → a lost-response
        # replay would duplicate them; everything else is idempotent
        return self.c.rpc(f"/v1/meta/{self.DAO}/{method}", doc,
                          idempotent=(method != "insert"))

    def _one(self, method: str, *args, entity=None):
        out = self._rpc(method, *args, entity=entity)
        if "entity" in out:
            return entity_from_doc(self.DAO, out["entity"])
        return out.get("result")

    def _many(self, method: str, *args):
        return [entity_from_doc(self.DAO, d)
                for d in self._rpc(method, *args).get("entities", [])]


class RemoteApps(_RemoteDAO, AppsDAO):
    DAO = "apps"

    def insert(self, app):
        return self._one("insert", entity=app)

    def get(self, app_id):
        return self._one("get", app_id)

    def get_by_name(self, name):
        return self._one("get_by_name", name)

    def get_all(self):
        return self._many("get_all")

    def update(self, app):
        self._one("update", entity=app)

    def delete(self, app_id):
        self._one("delete", app_id)


class RemoteAccessKeys(_RemoteDAO, AccessKeysDAO):
    DAO = "access_keys"

    def insert(self, access_key):
        return self._one("insert", entity=access_key)

    def get(self, key):
        return self._one("get", key)

    def get_all(self):
        return self._many("get_all")

    def get_by_app_id(self, app_id):
        return self._many("get_by_app_id", app_id)

    def update(self, access_key):
        self._one("update", entity=access_key)

    def delete(self, key):
        self._one("delete", key)


class RemoteChannels(_RemoteDAO, ChannelsDAO):
    DAO = "channels"

    def insert(self, channel):
        return self._one("insert", entity=channel)

    def get(self, channel_id):
        return self._one("get", channel_id)

    def get_by_app_id(self, app_id):
        return self._many("get_by_app_id", app_id)

    def delete(self, channel_id):
        self._one("delete", channel_id)


class RemoteEngineInstances(_RemoteDAO, EngineInstancesDAO):
    DAO = "engine_instances"

    def insert(self, instance):
        return self._one("insert", entity=instance)

    def get(self, instance_id):
        return self._one("get", instance_id)

    def get_all(self):
        return self._many("get_all")

    def update(self, instance):
        self._one("update", entity=instance)

    def delete(self, instance_id):
        self._one("delete", instance_id)

    def get_completed(self, engine_id, engine_version, engine_variant):
        return self._many("get_completed", engine_id, engine_version,
                          engine_variant)


class RemoteEvaluationInstances(_RemoteDAO, EvaluationInstancesDAO):
    DAO = "evaluation_instances"

    def insert(self, instance):
        return self._one("insert", entity=instance)

    def get(self, instance_id):
        return self._one("get", instance_id)

    def get_all(self):
        return self._many("get_all")

    def get_completed(self):
        return self._many("get_completed")

    def update(self, instance):
        self._one("update", entity=instance)

    def delete(self, instance_id):
        self._one("delete", instance_id)


class RemoteModels(_RemoteDAO, ModelsDAO):
    DAO = "models"

    def insert(self, model: Model) -> None:
        self.c.rpc("/v1/meta/models/insert", {"model": {
            "id": model.id,
            "models": base64.b64encode(model.models).decode()}})

    def get(self, model_id: str) -> Optional[Model]:
        out = self.c.rpc("/v1/meta/models/get", {"args": [model_id]})
        m = out.get("model")
        return None if m is None else Model(
            id=m["id"], models=base64.b64decode(m["models"]))

    def delete(self, model_id: str) -> None:
        self.c.rpc("/v1/meta/models/delete", {"args": [model_id]})
