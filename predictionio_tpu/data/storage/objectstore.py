"""S3 object-store storage backend — the durable shared-artifact tier.

Role of the reference's S3/HDFS backends (``storage/s3/.../S3Models.
scala``, ``storage/hdfs/.../HDFSModels.scala`` — model blobs on storage
that survives any single host) extended to a FULL backend the way this
framework extended localfs: a TPU pod's hosts need model blobs, event
logs and metadata on a bucket, not on one host's disk.

Contract spoken: the S3 REST subset every real object store exposes —
``PUT/GET/DELETE /bucket/key`` plus ``GET /bucket?prefix&marker``
(ListObjects V1 XML, lexicographic keys, marker pagination, ETags).
Point ``PIO_STORAGE_SOURCES_<N>_ENDPOINT`` at any S3-compatible
endpoint (MinIO, a GCS XML-API bucket, an auth-injecting proxy for
real AWS — request signing is the proxy's job, not the data plane's);
tests run against :class:`FakeObjectStoreServer`, an in-process
implementation of the same subset backed by a local directory.

Layout in the bucket:

- ``events/{app}[_{channel}]/{seq}-{uuid}`` — IMMUTABLE JSONL objects,
  one per ``insert_batch`` (the localfs record schema: put/putb/del).
  One batch = one PUT = the all-or-nothing crash contract the kill
  fuzzer checks: an object store commits an object atomically or not
  at all. Replay = LIST the prefix (lexicographic seq order) + fetch;
  immutable objects cache forever by key.
- ``meta/{table}.json`` — one JSON document per metadata table,
  atomically replaced on write (apps, access_keys, channels,
  engine_instances, evaluation_instances, sequences).
- ``models/{id}`` — model blobs, byte-for-byte (the S3Models role).

Concurrency: single-writer per metadata table (last PUT wins — the
reference's S3 backend had no metadata story at all); event appends
from many writers interleave safely because every batch is its own
immutable object with a unique key.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Dict, Iterator, List, Optional, Sequence
from urllib.parse import quote, unquote
from xml.etree import ElementTree

from ..event import Event
from .base import (
    AccessKey,
    AccessKeysDAO,
    App,
    AppsDAO,
    Channel,
    ChannelsDAO,
    EngineInstance,
    EngineInstancesDAO,
    EvaluationInstance,
    EvaluationInstancesDAO,
    EventFilter,
    EventStore,
    Model,
    ModelsDAO,
)


# ---------------------------------------------------------------------------
# client


class ObjectStoreClient:
    """Minimal S3-subset client over HTTP(S): put/get/delete/list.

    ``endpoint`` includes the bucket: ``http://host:port/bucket``.
    Extra headers (e.g. a proxy auth token) come from
    ``PIO_STORAGE_SOURCES_<N>_HEADERS`` as a JSON object.
    """

    def __init__(self, endpoint: str, headers: Optional[dict] = None,
                 timeout: float = 30.0):
        from urllib.parse import urlsplit

        self.endpoint = endpoint.rstrip("/")
        parts = urlsplit(self.endpoint)
        self.host = parts.hostname or "127.0.0.1"
        self.port = parts.port or (443 if parts.scheme == "https" else 80)
        self.tls = parts.scheme == "https"
        self.bucket_path = parts.path.rstrip("/")
        if not self.bucket_path:
            raise ValueError(
                f"object-store endpoint {endpoint!r} must include the "
                f"bucket: http://host:port/bucket")
        self.headers = dict(headers or {})
        self.timeout = timeout
        self._local = threading.local()
        self.lock = threading.RLock()

    @staticmethod
    def from_config(cfg: dict) -> "ObjectStoreClient":
        endpoint = cfg.get("ENDPOINT") or cfg.get("URL") or cfg.get("PATH")
        if not endpoint:
            raise ValueError("object-store backend needs "
                             "PIO_STORAGE_SOURCES_<N>_ENDPOINT "
                             "(http://host:port/bucket)")
        headers = {}
        raw = cfg.get("HEADERS")
        if raw:
            headers = json.loads(raw)
        return ObjectStoreClient(endpoint, headers=headers)

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None

    # -- raw REST ----------------------------------------------------------
    def _conn(self):
        import http.client

        conn = getattr(self._local, "conn", None)
        if conn is None:
            cls = (http.client.HTTPSConnection if self.tls
                   else http.client.HTTPConnection)
            conn = cls(self.host, self.port, timeout=self.timeout)
            self._local.conn = conn
        return conn

    def _request(self, method: str, path: str, body: bytes = b"",
                 retry: bool = True):
        conn = self._conn()
        try:
            conn.request(method, path, body=body or None,
                         headers=self.headers)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, data, dict(resp.getheaders())
        except Exception:
            self.close()
            if retry:  # one reconnect: keep-alive sockets go stale
                return self._request(method, path, body, retry=False)
            raise

    def _key_path(self, key: str) -> str:
        return f"{self.bucket_path}/{quote(key, safe='/')}"

    def put(self, key: str, data: bytes) -> str:
        status, body, headers = self._request("PUT", self._key_path(key),
                                              data)
        if status not in (200, 201):
            raise IOError(f"PUT {key}: HTTP {status} "
                          f"{body[:200].decode('utf-8', 'replace')}")
        return headers.get("ETag", "")

    def get(self, key: str) -> Optional[bytes]:
        status, body, _ = self._request("GET", self._key_path(key))
        if status == 404:
            return None
        if status != 200:
            raise IOError(f"GET {key}: HTTP {status}")
        return body

    def delete(self, key: str) -> None:
        status, _, _ = self._request("DELETE", self._key_path(key))
        if status not in (200, 204, 404):
            raise IOError(f"DELETE {key}: HTTP {status}")

    def list(self, prefix: str = "") -> Iterator[str]:
        """All keys under ``prefix`` in lexicographic order (ListObjects
        V1 marker pagination)."""
        marker = ""
        while True:
            q = f"?prefix={quote(prefix, safe='')}"
            if marker:
                q += f"&marker={quote(marker, safe='')}"
            status, body, _ = self._request(
                "GET", f"{self.bucket_path}{q}")
            if status != 200:
                raise IOError(f"LIST {prefix}: HTTP {status}")
            root = ElementTree.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):  # real S3 namespaces the doc
                ns = root.tag[: root.tag.index("}") + 1]
            keys = [el.findtext(f"{ns}Key") or ""
                    for el in root.iter(f"{ns}Contents")]
            yield from keys
            truncated = (root.findtext(f"{ns}IsTruncated") or
                         "false").lower() == "true"
            if not truncated or not keys:
                return
            marker = root.findtext(f"{ns}NextMarker") or keys[-1]

    # -- document helpers (metadata tables) --------------------------------
    def read_doc(self, name: str, default):
        raw = self.get(f"meta/{name}.json")
        if raw is None:
            return default
        return json.loads(raw.decode("utf-8"))

    def write_doc(self, name: str, value) -> None:
        self.put(f"meta/{name}.json",
                 json.dumps(value).encode("utf-8"))

    def next_seq(self, name: str) -> int:
        doc = f"{name}_seq"
        n = int(self.read_doc(doc, 0)) + 1
        self.write_doc(doc, n)
        return n


# ---------------------------------------------------------------------------
# event store


def _events_prefix(app_id: int, channel_id: Optional[int]) -> str:
    suffix = f"_{channel_id}" if channel_id is not None else ""
    return f"events/{app_id}{suffix}/"


class ObjectStoreEventStore(EventStore):
    """Append-only event log as immutable batch objects (see module
    docstring). Live state is replayed from the listing; objects cache
    by key (immutable), so an incremental read fetches only new keys."""

    def __init__(self, client: ObjectStoreClient):
        self.c = client
        #: prefix → (sorted applied keys tuple, live {id: Event})
        self._state_cache: Dict[str, tuple] = {}

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        prefix = _events_prefix(app_id, channel_id)
        with self.c.lock:
            self._state_cache.pop(prefix, None)
            found = False
            for key in list(self.c.list(prefix)):
                self.c.delete(key)
                found = True
        return found

    def close(self) -> None:
        self.c.close()

    def _seg_key(self, prefix: str) -> str:
        # time-ordered unique keys: lexicographic listing == append
        # order for a single writer; concurrent writers interleave by
        # wall clock (documented out-of-order window, like any log on
        # an object store)
        return f"{prefix}{time.time_ns():020d}-{uuid.uuid4().hex[:8]}"

    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(self, events: Sequence[Event], app_id: int,
                     channel_id: Optional[int] = None) -> List[str]:
        if not events:
            return []
        from ..event import new_event_id

        prefix = _events_prefix(app_id, channel_id)
        stored = [e.copy(event_id=e.event_id or new_event_id())
                  for e in events]
        if len(stored) > 1:
            records = [{"op": "putb",
                        "events": [s.to_json() for s in stored]}]
        else:
            records = [{"op": "put", "event": stored[0].to_json()}]
        payload = "".join(json.dumps(r) + "\n" for r in records) \
            .encode("utf-8")
        with self.c.lock:
            # ONE PUT per batch: the object store's per-object atomicity
            # IS the all-or-nothing insert_batch crash contract
            key = self._seg_key(prefix)
            self.c.put(key, payload)
            # extend the cached state in place (our time-ordered key
            # sorts after everything we had applied) instead of
            # popping it — a pop made every read after a write replay
            # the WHOLE log (O(N²) for interleaved write/read). If a
            # concurrent writer interleaved a key we haven't seen,
            # _replay's listing-prefix check catches it and does the
            # full replay anyway.
            cached = self._state_cache.get(prefix)
            if cached is not None:
                live = cached[1]
                for s in stored:
                    live[s.event_id] = s
                self._state_cache[prefix] = (cached[0] + (key,), live)
        return [s.event_id for s in stored]

    def _replay(self, app_id: int, channel_id: Optional[int],
                deadline: Optional[float] = None) -> Dict[str, Event]:
        prefix = _events_prefix(app_id, channel_id)
        with self.c.lock:
            keys = tuple(self.c.list(prefix))
            cached = self._state_cache.get(prefix)
            if cached is not None and cached[0] == keys:
                return cached[1]
            live: Dict[str, Event] = {}
            if cached is not None and keys[: len(cached[0])] == cached[0]:
                live = dict(cached[1])  # pure append since last replay
                new_keys = keys[len(cached[0]):]
            else:
                new_keys = keys
            for n, key in enumerate(new_keys):
                if deadline is not None and n % 64 == 0 \
                        and time.monotonic() > deadline:
                    raise TimeoutError(
                        "event replay exceeded its deadline")
                # no raw-blob cache: each object is fetched once,
                # folded into the live dict, and dropped — the full
                # log must not live in RAM twice (a re-replay after a
                # non-append change refetches, which is rare)
                blob = self.c.get(key)
                if blob is None:  # deleted under us (remove race)
                    continue
                for line in blob.splitlines():
                    if not line.strip():
                        continue
                    rec = json.loads(line)
                    if rec["op"] == "put":
                        e = Event.from_json(rec["event"])
                        live[e.event_id] = e
                    elif rec["op"] == "putb":
                        for doc in rec["events"]:
                            e = Event.from_json(doc)
                            live[e.event_id] = e
                    elif rec["op"] == "del":
                        live.pop(rec["eventId"], None)
            self._state_cache[prefix] = (keys, live)
            return live

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        return self._replay(app_id, channel_id).get(event_id)

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        prefix = _events_prefix(app_id, channel_id)
        with self.c.lock:
            if event_id not in self._replay(app_id, channel_id):
                return False
            payload = (json.dumps({"op": "del", "eventId": event_id})
                       + "\n").encode("utf-8")
            key = self._seg_key(prefix)
            self.c.put(key, payload)
            cached = self._state_cache.get(prefix)
            if cached is not None:  # in-place, like insert_batch
                cached[1].pop(event_id, None)
                self._state_cache[prefix] = (cached[0] + (key,),
                                             cached[1])
            return True

    def find(self, app_id: int, channel_id: Optional[int] = None,
             filter: EventFilter = EventFilter()) -> Iterator[Event]:
        with self.c.lock:  # snapshot: inserts mutate the live dict
            events = list(self._replay(app_id, channel_id,
                                       filter.deadline).values())
        events = list(filter.apply(events))
        events.sort(key=lambda e: e.event_time_millis,
                    reverse=filter.reversed)
        if filter.limit is not None and filter.limit >= 0:
            events = events[: filter.limit]
        return iter(events)


# ---------------------------------------------------------------------------
# metadata DAOs (single-document tables, like localfs but on the bucket)


class ObjectStoreApps(AppsDAO):
    DOC = "apps"

    def __init__(self, client: ObjectStoreClient):
        self.c = client

    def _load(self) -> List[App]:
        return [App(**a) for a in self.c.read_doc(self.DOC, [])]

    def _store(self, apps: List[App]) -> None:
        self.c.write_doc(self.DOC, [
            {"id": a.id, "name": a.name, "description": a.description}
            for a in apps])

    def insert(self, app: App) -> Optional[int]:
        with self.c.lock:
            apps = self._load()
            if any(a.name == app.name for a in apps):
                return None
            app_id = app.id if app.id > 0 else self.c.next_seq("app")
            if any(a.id == app_id for a in apps):
                return None
            apps.append(App(id=app_id, name=app.name,
                            description=app.description))
            self._store(apps)
            return app_id

    def get(self, app_id: int) -> Optional[App]:
        return next((a for a in self._load() if a.id == app_id), None)

    def get_by_name(self, name: str) -> Optional[App]:
        return next((a for a in self._load() if a.name == name), None)

    def get_all(self) -> List[App]:
        return self._load()

    def update(self, app: App) -> None:
        with self.c.lock:
            apps = [app if a.id == app.id else a for a in self._load()]
            self._store(apps)

    def delete(self, app_id: int) -> None:
        with self.c.lock:
            self._store([a for a in self._load() if a.id != app_id])


class ObjectStoreAccessKeys(AccessKeysDAO):
    DOC = "access_keys"

    def __init__(self, client: ObjectStoreClient):
        self.c = client

    def _load(self) -> List[AccessKey]:
        return [AccessKey(**a) for a in self.c.read_doc(self.DOC, [])]

    def _store(self, keys: List[AccessKey]) -> None:
        self.c.write_doc(self.DOC, [
            {"key": k.key, "app_id": k.app_id, "events": list(k.events)}
            for k in keys])

    def insert(self, access_key: AccessKey) -> Optional[str]:
        with self.c.lock:
            keys = self._load()
            key = access_key.key or self.generate_key()
            if any(k.key == key for k in keys):
                return None
            keys.append(AccessKey(key=key, app_id=access_key.app_id,
                                  events=access_key.events))
            self._store(keys)
            return key

    def get(self, key: str) -> Optional[AccessKey]:
        return next((k for k in self._load() if k.key == key), None)

    def get_all(self) -> List[AccessKey]:
        return self._load()

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [k for k in self._load() if k.app_id == app_id]

    def update(self, access_key: AccessKey) -> None:
        with self.c.lock:
            self._store([access_key if k.key == access_key.key else k
                         for k in self._load()])

    def delete(self, key: str) -> None:
        with self.c.lock:
            self._store([k for k in self._load() if k.key != key])


class ObjectStoreChannels(ChannelsDAO):
    DOC = "channels"

    def __init__(self, client: ObjectStoreClient):
        self.c = client

    def _load(self) -> List[Channel]:
        return [Channel(**a) for a in self.c.read_doc(self.DOC, [])]

    def _store(self, chans: List[Channel]) -> None:
        self.c.write_doc(self.DOC, [
            {"id": c.id, "name": c.name, "app_id": c.app_id}
            for c in chans])

    def insert(self, channel: Channel) -> Optional[int]:
        if not Channel.is_valid_name(channel.name):
            return None
        with self.c.lock:
            chans = self._load()
            cid = channel.id if channel.id > 0 \
                else self.c.next_seq("channel")
            if any(c.id == cid for c in chans):
                return None
            chans.append(Channel(id=cid, name=channel.name,
                                 app_id=channel.app_id))
            self._store(chans)
            return cid

    def get(self, channel_id: int) -> Optional[Channel]:
        return next((c for c in self._load() if c.id == channel_id),
                    None)

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        return [c for c in self._load() if c.app_id == app_id]

    def delete(self, channel_id: int) -> None:
        with self.c.lock:
            self._store([c for c in self._load() if c.id != channel_id])


class ObjectStoreEngineInstances(EngineInstancesDAO):
    DOC = "engine_instances"

    def __init__(self, client: ObjectStoreClient):
        self.c = client

    def _load(self) -> List[EngineInstance]:
        from .wire import entity_from_doc

        return [entity_from_doc(self.DOC, d)
                for d in self.c.read_doc(self.DOC, [])]

    def _store(self, rows) -> None:
        from .wire import entity_to_doc

        self.c.write_doc(self.DOC, [entity_to_doc(r) for r in rows])

    def insert(self, instance) -> str:
        with self.c.lock:
            rows = self._load()
            iid = instance.id or uuid.uuid4().hex
            rows.append(instance.copy(id=iid))
            self._store(rows)
            return iid

    def get(self, instance_id: str):
        return next((r for r in self._load() if r.id == instance_id),
                    None)

    def get_all(self):
        return self._load()

    def update(self, instance) -> None:
        with self.c.lock:
            self._store([instance if r.id == instance.id else r
                         for r in self._load()])

    def delete(self, instance_id: str) -> None:
        with self.c.lock:
            self._store([r for r in self._load() if r.id != instance_id])

    def get_completed(self, engine_id: str, engine_version: str,
                      engine_variant: str):
        from .base import STATUS_COMPLETED

        rows = [r for r in self._load()
                if r.status == STATUS_COMPLETED
                and r.engine_id == engine_id
                and r.engine_version == engine_version
                and r.engine_variant == engine_variant]
        rows.sort(key=lambda r: r.start_time, reverse=True)
        return rows

    def get_latest_completed(self, engine_id: str, engine_version: str,
                             engine_variant: str):
        rows = self.get_completed(engine_id, engine_version,
                                  engine_variant)
        return rows[0] if rows else None


class ObjectStoreEvaluationInstances(EvaluationInstancesDAO):
    DOC = "evaluation_instances"

    def __init__(self, client: ObjectStoreClient):
        self.c = client

    def _load(self) -> List[EvaluationInstance]:
        from .wire import entity_from_doc

        return [entity_from_doc(self.DOC, d)
                for d in self.c.read_doc(self.DOC, [])]

    def _store(self, rows) -> None:
        from .wire import entity_to_doc

        self.c.write_doc(self.DOC, [entity_to_doc(r) for r in rows])

    def insert(self, instance) -> str:
        with self.c.lock:
            rows = self._load()
            iid = instance.id or uuid.uuid4().hex
            rows.append(instance.copy(id=iid))
            self._store(rows)
            return iid

    def get(self, instance_id: str):
        return next((r for r in self._load() if r.id == instance_id),
                    None)

    def get_all(self):
        return self._load()

    def get_completed(self):
        from .base import STATUS_EVALCOMPLETED

        rows = [r for r in self._load()
                if r.status == STATUS_EVALCOMPLETED]
        rows.sort(key=lambda r: r.start_time, reverse=True)
        return rows

    def update(self, instance) -> None:
        with self.c.lock:
            self._store([instance if r.id == instance.id else r
                         for r in self._load()])

    def delete(self, instance_id: str) -> None:
        with self.c.lock:
            self._store([r for r in self._load() if r.id != instance_id])


class ObjectStoreModels(ModelsDAO):
    """Model blobs at ``models/{id}`` — byte-for-byte the reference's
    ``S3Models.scala`` role (get/put/delete of a keyed blob)."""

    def __init__(self, client: ObjectStoreClient):
        self.c = client

    def insert(self, model: Model) -> None:
        self.c.put(f"models/{quote(model.id, safe='')}", model.models)

    def get(self, model_id: str) -> Optional[Model]:
        blob = self.c.get(f"models/{quote(model_id, safe='')}")
        if blob is None:
            return None
        return Model(id=model_id, models=blob)

    def delete(self, model_id: str) -> None:
        self.c.delete(f"models/{quote(model_id, safe='')}")


# ---------------------------------------------------------------------------
# in-process fake server (tests; same REST subset real stores speak)


def build_fake_server_app(root: str):
    """S3-subset REST app over a local directory: PUT/GET/DELETE object
    + ListObjects V1 with prefix/marker/max-keys. Object keys map to
    url-quoted filenames (flat namespace — no traversal surface); PUT
    is atomic (temp + rename), which is the property the crash
    contract leans on."""
    from ...server.http import HTTPApp, Request, Response

    os.makedirs(root, exist_ok=True)
    app = HTTPApp("fake-object-store")

    def _fname(key: str) -> str:
        return os.path.join(root, quote(key, safe=""))

    @app.route("PUT", r"/(?P<bucket>[^/?]+)/(?P<key>.+)")
    def put_object(req: Request) -> Response:
        import hashlib

        path = _fname(unquote(req.path_params["key"]))
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(req.body)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        etag = hashlib.md5(req.body).hexdigest()
        return Response(status=200, body=b"",
                        headers={"ETag": f'"{etag}"'})

    @app.route("GET", r"/(?P<bucket>[^/?]+)/(?P<key>.+)")
    def get_object(req: Request) -> Response:
        path = _fname(unquote(req.path_params["key"]))
        if not os.path.exists(path):
            return Response(status=404, body=b"NoSuchKey",
                            content_type="application/xml")
        with open(path, "rb") as f:
            return Response(status=200, body=f.read(),
                            content_type="application/octet-stream")

    @app.route("DELETE", r"/(?P<bucket>[^/?]+)/(?P<key>.+)")
    def delete_object(req: Request) -> Response:
        path = _fname(unquote(req.path_params["key"]))
        try:
            os.remove(path)
        except FileNotFoundError:
            return Response(status=404, body=b"")
        return Response(status=204, body=b"")

    @app.route("GET", r"/(?P<bucket>[^/?]+)/?")
    def list_objects(req: Request) -> Response:
        prefix = req.query.get("prefix", "")
        marker = req.query.get("marker", "")
        max_keys = int(req.query.get("max-keys", "1000"))
        keys = sorted(unquote(f) for f in os.listdir(root)
                      if ".tmp." not in f)
        keys = [k for k in keys if k.startswith(prefix) and k > marker]
        page, truncated = keys[:max_keys], len(keys) > max_keys
        items = "".join(
            f"<Contents><Key>{_xml(k)}</Key>"
            f"<Size>{os.path.getsize(_fname(k))}</Size></Contents>"
            for k in page)
        nxt = (f"<NextMarker>{_xml(page[-1])}</NextMarker>"
               if truncated and page else "")
        body = (f'<?xml version="1.0" encoding="UTF-8"?>'
                f"<ListBucketResult>"
                f"<IsTruncated>{str(truncated).lower()}</IsTruncated>"
                f"{nxt}{items}</ListBucketResult>")
        return Response(status=200, body=body.encode("utf-8"),
                        content_type="application/xml")

    return app


def _xml(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))


class FakeObjectStoreServer:
    """Directory-backed S3-subset server for tests and local dev
    (``ptpu storageserver --object-store`` exposes the same thing)."""

    def __init__(self, root: str, host: str = "127.0.0.1",
                 port: int = 0):
        from ...server.http import AppServer

        self.app = build_fake_server_app(root)
        self.server = AppServer(self.app, host, port)

    @property
    def port(self) -> int:
        return self.server.port

    def start_background(self):
        self.server.start_background()
        return self

    def shutdown(self) -> None:
        self.server.shutdown()
