"""In-memory storage backend.

The test/dev backend, playing the role the reference's H2-in-MySQL-mode
fixture played for its unit tests (``StorageMockContext.scala:22-64``).
Implements the full event-log and metadata DAO contracts; thread-safe so the
REST servers can call it from executor threads.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Tuple

from ...faults import declare, fire
from ..event import Event, new_event_id
from .base import (
    AccessKey,
    AccessKeysDAO,
    App,
    AppsDAO,
    Channel,
    ChannelsDAO,
    EngineInstance,
    EngineInstancesDAO,
    EvaluationInstance,
    EvaluationInstancesDAO,
    EventFilter,
    EventStore,
    Model,
    ModelsDAO,
    STATUS_COMPLETED,
    STATUS_EVALCOMPLETED,
)

_Key = Tuple[int, Optional[int]]

#: the storage-I/O injection point (docs/reliability.md): drills make
#: the backing store raise/stall without touching the store itself —
#: fired by the in-process backends on the event-log ops the servers
#: and the stream trainer depend on (op=insert|find)
F_STORAGE_IO = declare("storage.io",
                       "event-store read/write on an in-process "
                       "backend (op= labels the operation)")


class MemoryEventStore(EventStore):
    def __init__(self, config: Optional[dict] = None):
        self._log: Dict[_Key, Dict[str, Event]] = {}
        self._lock = threading.RLock()

    def _bucket(self, app_id: int, channel_id: Optional[int]) -> Dict[str, Event]:
        return self._log.setdefault((app_id, channel_id), {})

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self._lock:
            self._bucket(app_id, channel_id)
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self._lock:
            return self._log.pop((app_id, channel_id), None) is not None

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        fire(F_STORAGE_IO, op="insert", backend="memory")
        with self._lock:
            eid = event.event_id or new_event_id()
            self._bucket(app_id, channel_id)[eid] = event.copy(event_id=eid)
            return eid

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        with self._lock:
            return self._bucket(app_id, channel_id).get(event_id)

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        with self._lock:
            return self._bucket(app_id, channel_id).pop(event_id, None) is not None

    def find(self, app_id: int, channel_id: Optional[int] = None,
             filter: EventFilter = EventFilter()) -> Iterator[Event]:
        fire(F_STORAGE_IO, op="find", backend="memory")
        with self._lock:
            events = list(self._bucket(app_id, channel_id).values())
        events = list(filter.apply(events))
        events.sort(key=lambda e: e.event_time_millis, reverse=filter.reversed)
        if filter.limit is not None and filter.limit >= 0:
            events = events[: filter.limit]
        return iter(events)


class MemoryApps(AppsDAO):
    def __init__(self, config: Optional[dict] = None):
        self._apps: Dict[int, App] = {}
        self._next_id = 1
        self._lock = threading.RLock()

    def insert(self, app: App) -> Optional[int]:
        with self._lock:
            app_id = app.id if app.id > 0 else self._next_id
            if app_id in self._apps or self.get_by_name(app.name):
                return None
            self._next_id = max(self._next_id, app_id) + 1
            self._apps[app_id] = App(app_id, app.name, app.description)
            return app_id

    def get(self, app_id: int) -> Optional[App]:
        return self._apps.get(app_id)

    def get_by_name(self, name: str) -> Optional[App]:
        return next((a for a in self._apps.values() if a.name == name), None)

    def get_all(self) -> List[App]:
        return sorted(self._apps.values(), key=lambda a: a.id)

    def update(self, app: App) -> None:
        with self._lock:
            self._apps[app.id] = app

    def delete(self, app_id: int) -> None:
        with self._lock:
            self._apps.pop(app_id, None)


class MemoryAccessKeys(AccessKeysDAO):
    def __init__(self, config: Optional[dict] = None):
        self._keys: Dict[str, AccessKey] = {}
        self._lock = threading.RLock()

    def insert(self, access_key: AccessKey) -> Optional[str]:
        with self._lock:
            key = access_key.key or self.generate_key()
            if key in self._keys:
                return None
            self._keys[key] = AccessKey(key, access_key.app_id,
                                        tuple(access_key.events))
            return key

    def get(self, key: str) -> Optional[AccessKey]:
        return self._keys.get(key)

    def get_all(self) -> List[AccessKey]:
        return list(self._keys.values())

    def get_by_app_id(self, app_id: int) -> List[AccessKey]:
        return [k for k in self._keys.values() if k.app_id == app_id]

    def update(self, access_key: AccessKey) -> None:
        with self._lock:
            self._keys[access_key.key] = access_key

    def delete(self, key: str) -> None:
        with self._lock:
            self._keys.pop(key, None)


class MemoryChannels(ChannelsDAO):
    def __init__(self, config: Optional[dict] = None):
        self._channels: Dict[int, Channel] = {}
        self._next_id = 1
        self._lock = threading.RLock()

    def insert(self, channel: Channel) -> Optional[int]:
        if not Channel.is_valid_name(channel.name):
            return None
        with self._lock:
            chan_id = channel.id if channel.id > 0 else self._next_id
            if chan_id in self._channels:
                return None
            self._next_id = max(self._next_id, chan_id) + 1
            self._channels[chan_id] = Channel(chan_id, channel.name,
                                              channel.app_id)
            return chan_id

    def get(self, channel_id: int) -> Optional[Channel]:
        return self._channels.get(channel_id)

    def get_by_app_id(self, app_id: int) -> List[Channel]:
        return [c for c in self._channels.values() if c.app_id == app_id]

    def delete(self, channel_id: int) -> None:
        with self._lock:
            self._channels.pop(channel_id, None)


class MemoryEngineInstances(EngineInstancesDAO):
    def __init__(self, config: Optional[dict] = None):
        self._instances: Dict[str, EngineInstance] = {}
        self._next = 1
        self._lock = threading.RLock()

    def insert(self, instance: EngineInstance) -> str:
        with self._lock:
            iid = instance.id or str(self._next)
            self._next += 1
            self._instances[iid] = instance.copy(id=iid)
            return iid

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        return self._instances.get(instance_id)

    def get_all(self) -> List[EngineInstance]:
        return list(self._instances.values())

    def get_completed(self, engine_id: str, engine_version: str,
                      engine_variant: str) -> List[EngineInstance]:
        out = [i for i in self._instances.values()
               if i.status == STATUS_COMPLETED
               and i.engine_id == engine_id
               and i.engine_version == engine_version
               and i.engine_variant == engine_variant]
        return sorted(out, key=lambda i: i.start_time, reverse=True)

    def update(self, instance: EngineInstance) -> None:
        with self._lock:
            self._instances[instance.id] = instance

    def delete(self, instance_id: str) -> None:
        with self._lock:
            self._instances.pop(instance_id, None)


class MemoryEvaluationInstances(EvaluationInstancesDAO):
    def __init__(self, config: Optional[dict] = None):
        self._instances: Dict[str, EvaluationInstance] = {}
        self._next = 1
        self._lock = threading.RLock()

    def insert(self, instance: EvaluationInstance) -> str:
        with self._lock:
            iid = instance.id or str(self._next)
            self._next += 1
            self._instances[iid] = instance.copy(id=iid)
            return iid

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        return self._instances.get(instance_id)

    def get_all(self) -> List[EvaluationInstance]:
        return list(self._instances.values())

    def get_completed(self) -> List[EvaluationInstance]:
        out = [i for i in self._instances.values()
               if i.status == STATUS_EVALCOMPLETED]
        return sorted(out, key=lambda i: i.start_time, reverse=True)

    def update(self, instance: EvaluationInstance) -> None:
        with self._lock:
            self._instances[instance.id] = instance

    def delete(self, instance_id: str) -> None:
        with self._lock:
            self._instances.pop(instance_id, None)


class MemoryModels(ModelsDAO):
    def __init__(self, config: Optional[dict] = None):
        self._models: Dict[str, Model] = {}
        self._lock = threading.RLock()

    def insert(self, model: Model) -> None:
        with self._lock:
            self._models[model.id] = model

    def get(self, model_id: str) -> Optional[Model]:
        return self._models.get(model_id)

    def delete(self, model_id: str) -> None:
        with self._lock:
            self._models.pop(model_id, None)
