"""SEGMENTFS storage backend: content-addressed immutable segments +
a manifest, laid out for SHARED filesystems (NFS, GCS/S3 fuse mounts,
Lustre) where N pod hosts read the same event log concurrently.

The role of the reference's network-capable backends (``storage/hbase``,
``storage/jdbc``, ``storage/s3`` — every Spark executor could reach the
store; ``JDBCPEvents.scala:49-89`` partitioned scans across them),
re-designed for the object-store model instead of a database protocol:

- **Segments are immutable and content-addressed** (name carries a
  sha256 of the bytes). Once published they never change, so any number
  of hosts read them lock-free and a per-process parse cache needs no
  invalidation. This is the write-once layout object stores want.
- **The manifest is the only mutable object**: an ordered list of
  segment names, replaced atomically (write-temp + rename) under an
  OS-level ``flock``. Readers never lock — they read whichever manifest
  version is current and only ever see fully-published segments.
- Deletes append tombstone segments; when tombstones outnumber live
  events, writers compact (one merged segment, new manifest). Replaced
  segments are garbage-collected only after a grace period so an
  in-flight reader holding the previous manifest still finds its files.

Metadata DAOs reuse the LOCALFS document implementations wrapped in the
same cross-process lock, and model blobs are plain files — both are
low-rate paths where a lock per mutation is fine.

Caveat: ``flock`` coherence across hosts requires the shared filesystem
to support POSIX locks (NFSv4 does; object-store fuse mounts usually do
not). On lock-free mounts, run a single writer per (app, channel) —
readers are always safe.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
import uuid
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from ..event import Event
from . import localfs
from .base import EventFilter, EventStore
from .localfs import _flock, atomic_write

#: compact when tombstoned/overwritten records outnumber live events
log_ = logging.getLogger("predictionio_tpu.storage.segmentfs")

_COMPACT_RATIO = 1.0
#: watermark sentinel committed by intermediate rebuild chunks — can
#: never equal a jsonl segment name, so a crash mid-rebuild reads as
#: "history changed → invalidate + re-encode", never as complete
_REBUILD_WM = "__rebuild-incomplete__"
#: seconds an unreferenced segment survives before gc (reader grace)
_GC_GRACE_S = 300.0


class SegmentFSClient(localfs.LocalFSClient):
    """Root-directory handle + cross-process document locking.

    Extends the LOCALFS client with (a) a per-process cache of PARSED
    immutable segments and (b) a sequence allocator that holds the OS
    lock across its read-modify-write (LOCALFS only held the in-process
    lock — fine for one process, lost updates across many).
    """

    def __init__(self, path: str):
        super().__init__(path)
        os.makedirs(os.path.join(path, "events"), exist_ok=True)
        #: abs segment path → parsed records; immutable ⇒ never invalidated
        self.segment_cache: Dict[str, List[dict]] = {}
        #: log dir → (manifest segment tuple, live events, dead count) —
        #: the manifest version fully determines the replay result, so a
        #: serving-path get() must not rebuild 1M Event objects per call
        self.replay_cache: Dict[str, tuple] = {}
        self._seg_lock = threading.Lock()

    @staticmethod
    def from_config(cfg: dict) -> "SegmentFSClient":
        path = cfg.get("PATH") or cfg.get("path")
        if not path:
            raise ValueError("SEGMENTFS source needs a PATH property "
                             "(PIO_STORAGE_SOURCES_<NAME>_PATH)")
        return SegmentFSClient(path)

    def next_seq(self, name: str) -> int:
        with self.lock, _flock(self.doc_path(f"{name}_seq")):
            n = int(self.read_doc(f"{name}_seq", 0)) + 1
            self.write_doc(f"{name}_seq", n)
            return n

    def parsed_segment(self, path: str,
                       deadline: Optional[float] = None) -> List[dict]:
        with self._seg_lock:
            recs = self.segment_cache.get(path)
        if recs is not None:
            return recs
        recs = []
        with open(path, "r", encoding="utf-8") as f:
            for ln, line in enumerate(f):
                # a compacted log is ONE big segment: the serving-path
                # deadline must bound the parse itself, not just the
                # replay loop over already-parsed records
                if deadline is not None and ln % 4096 == 0 \
                        and time.monotonic() > deadline:
                    raise TimeoutError(
                        "segment parse exceeded its deadline")
                if line.strip():
                    recs.append(json.loads(line))
        with self._seg_lock:
            self.segment_cache[path] = recs
        return recs


def _log_dir(app_id: int, channel_id: Optional[int]) -> str:
    return f"app_{app_id}" if channel_id is None \
        else f"app_{app_id}_c{channel_id}"


class SegmentFSEventStore(EventStore):
    def __init__(self, client: SegmentFSClient):
        self.c = client

    # -- layout ------------------------------------------------------------
    def _dir(self, app_id: int, channel_id: Optional[int]) -> str:
        return os.path.join(self.c.root, "events",
                            _log_dir(app_id, channel_id))

    def _manifest_path(self, d: str) -> str:
        return os.path.join(d, "manifest.json")

    def _read_manifest(self, d: str) -> List[str]:
        try:
            with open(self._manifest_path(d), "r", encoding="utf-8") as f:
                return json.load(f)["segments"]
        except FileNotFoundError:
            return []

    def _write_manifest(self, d: str, segments: List[str]) -> None:
        atomic_write(self._manifest_path(d),
                     json.dumps({"segments": segments,
                                 "updated": time.time()}))

    def _write_segment(self, d: str, records: List[dict]) -> str:
        payload = "".join(json.dumps(r) + "\n" for r in records)
        return self._write_segment_bytes(d, payload.encode("utf-8"),
                                         len(records))

    def _write_segment_bytes(self, d: str, data: bytes, n: int) -> str:
        digest = hashlib.sha256(data).hexdigest()[:20]
        name = f"seg-{n}-{digest}.jsonl"
        path = os.path.join(d, name)
        if not os.path.exists(path):  # content-addressed: idempotent
            atomic_write(path, data)
        return name

    def _publish(self, d: str, records: List[dict]) -> None:
        payload = "".join(json.dumps(r) + "\n" for r in records)
        self._publish_payload(d, payload.encode("utf-8"), len(records))

    def _publish_payload(self, d: str, payload: bytes, n: int) -> None:
        """Write one immutable segment and link it into the manifest, both
        under the cross-process lock — writing inside the critical section
        closes the window where :meth:`gc` (which takes the same lock)
        could collect a written-but-not-yet-linked segment. A crash before
        the manifest write leaves an unreferenced file for gc, never a
        torn log."""
        with _flock(self._manifest_path(d)):
            name = self._write_segment_bytes(d, payload, n)
            segments = self._read_manifest(d)
            if name not in segments:
                self._write_manifest(d, segments + [name])

    # -- EventStore contract ----------------------------------------------
    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        d = self._dir(app_id, channel_id)
        os.makedirs(d, exist_ok=True)
        if not os.path.exists(self._manifest_path(d)):
            with _flock(self._manifest_path(d)):
                if not os.path.exists(self._manifest_path(d)):
                    self._write_manifest(d, [])
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        d = self._dir(app_id, channel_id)
        if not os.path.isdir(d):
            return False
        # the lock sidecar (and the directory) must survive: unlinking a
        # lockfile lets a process blocked on the old inode and one that
        # re-creates it each hold an "exclusive" flock simultaneously
        # (same invariant as localfs.remove)
        with _flock(self._manifest_path(d)):
            for name in os.listdir(d):
                if name.startswith("seg-") or name == "manifest.json":
                    p = os.path.join(d, name)
                    with self.c._seg_lock:
                        self.c.segment_cache.pop(p, None)
                    if os.path.isfile(p):
                        os.unlink(p)
            cdir = self._columnar_dir(d)
            if os.path.isdir(cdir):
                from ..columnar import SegmentLog
                log = SegmentLog(cdir)
                with log.lock():
                    # same reader grace as rebuilds: another pod host may
                    # still mmap these segments (NFS gives no
                    # unlink-keeps-inode guarantee)
                    log.invalidate(grace_s=_GC_GRACE_S)
                    log.sweep(_GC_GRACE_S)
        with self.c._seg_lock:
            self.c.replay_cache.pop(d, None)
            for wp in (False, True):
                self.c.replay_cache.pop(("columnar", d, wp), None)
        return True

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        return self.insert_batch([event], app_id, channel_id)[0]

    def insert_batch(self, events: Sequence[Event], app_id: int,
                     channel_id: Optional[int] = None) -> List[str]:
        if not events:
            return []
        d = self._dir(app_id, channel_id)
        os.makedirs(d, exist_ok=True)
        records, ids = [], []
        for e in events:
            eid = e.event_id or uuid.uuid4().hex
            records.append({"op": "put", "event": e.copy(event_id=eid).to_json()})
            ids.append(eid)
        self._publish(d, records)
        return ids

    def import_jsonl(self, source, app_id: int,
                     channel_id: Optional[int] = None,
                     chunk: int = 100_000) -> int:
        """Bulk import through the native codec's one-pass
        JSONL→segment lane (parse + validate + normalize + encode in
        C++, ~20× the Python pipeline). Commit unit is a ~32 MB block
        of whole lines → one published segment. Any block the strict
        lane declines (exotic ISO forms, non-string optional fields,
        validation failures that must raise the canonical message)
        re-runs through the Python path, preserving event order and
        error behavior exactly — with ONE documented divergence: the
        native lane stamps a single ``utcnow()`` per block as the
        default eventTime/creationTime for events missing them (the
        Python lanes stamp per event), so default timestamps are
        block-identical here and a block that falls back mid-import
        gets per-event times instead."""
        from ...native import codec as _native_codec

        mod = _native_codec()
        if mod is None or not hasattr(mod, "import_jsonl"):
            return super().import_jsonl(source, app_id, channel_id,
                                        chunk)
        from ..event import isoformat_millis, utcnow
        from .base import JsonlImportError, _open_jsonl, \
            iter_jsonl_blocks

        d = self._dir(app_id, channel_id)
        os.makedirs(d, exist_ok=True)
        block_size = int(os.environ.get("PIO_IMPORT_BLOCK",
                                        str(32 << 20)))
        total = 0
        lineno = 0  # lines fully consumed (== committed: block commits)
        f = _open_jsonl(source)  # missing file: clean OSError
        try:
            with f:
                for buf, nlines in iter_jsonl_blocks(f, block_size):
                    payload, n, _bad = mod.import_jsonl(
                        buf, os.urandom(16 * nlines),
                        isoformat_millis(utcnow()))
                    if payload is None:
                        n = self._import_block_py(buf, lineno, total,
                                                  app_id, channel_id,
                                                  chunk)
                    elif n:
                        self._publish_payload(d, payload, n)
                    total += n
                    lineno += nlines
        except JsonlImportError:
            raise
        except Exception as e:  # noqa: BLE001 — e.g. ENOSPC mid-import:
            # the durable prefix (every fully-consumed block) must be
            # reported, or a re-run after freeing space duplicates it
            raise JsonlImportError(lineno, lineno, total, e) from e
        return total

    def _import_block_py(self, buf: bytes, lines_before: int,
                         events_before: int, app_id: int,
                         channel_id: Optional[int],
                         chunk: int) -> int:
        """Python lane for one block the native converter declined.
        Unlike the fast lane (whose commit unit is the whole block —
        it holds only bytes, never Event objects), this one honors the
        ``chunk`` knob (``PIO_IMPORT_BATCH``): at most ``chunk`` Event
        objects live at once, each batch committed all-or-nothing,
        and a failure reports exactly the committed prefix."""
        from .base import JsonlImportError

        events: List[Event] = []
        rel = 0            # lines consumed within this block
        committed_rel = 0  # lines fully committed within this block
        total_rel = 0
        # split on \n ONLY (remote.py's rule): splitlines() also cuts
        # on lone \r / \x0b / \x1c..., which would import one physical
        # line as two events and shift resume linenos vs the \n-only
        # accounting of iter_jsonl_blocks (ADVICE r4)
        pieces = buf.split(b"\n")
        if pieces and pieces[-1] == b"":
            pieces.pop()  # trailing newline, not a blank line
        try:
            for raw in pieces:
                rel += 1
                s = raw.decode("utf-8").strip()
                if s:
                    events.append(Event.from_json(json.loads(s)))
                if len(events) >= chunk:
                    self.insert_batch(events, app_id, channel_id)
                    total_rel += len(events)
                    committed_rel = rel
                    events = []
            if events:
                self.insert_batch(events, app_id, channel_id)
                total_rel += len(events)
        except Exception as e:  # noqa: BLE001 — durable-progress report
            raise JsonlImportError(lines_before + rel,
                                   lines_before + committed_rel,
                                   events_before + total_rel, e) from e
        return total_rel


    def _replay(self, app_id: int, channel_id: Optional[int],
                deadline: Optional[float] = None,
                segments: Optional[Sequence[str]] = None
                ) -> Tuple[Dict[str, Event], int]:
        """live events (insertion-ordered) + dead-record count, from the
        current manifest's immutable segments — or from an explicitly
        pinned ``segments`` list (the columnar rebuild must replay
        exactly the manifest version its watermark records, not a fresh
        read that may have advanced). Cached per segment tuple (which
        fully determines the result); ``deadline`` bounds a cold replay
        on the serving path (``EventFilter.deadline`` contract,
        ``base.py``)."""
        d = self._dir(app_id, channel_id)
        segments = tuple(self._read_manifest(d)) if segments is None \
            else tuple(segments)
        with self.c._seg_lock:
            cached = self.c.replay_cache.get(d)
        if cached is not None and cached[0] == segments:
            return cached[1], cached[2]
        live: Dict[str, Event] = {}
        dead = 0
        n = 0
        for name in segments:
            for r in self.c.parsed_segment(os.path.join(d, name),
                                           deadline=deadline):
                n += 1
                if deadline is not None and n % 4096 == 0 \
                        and time.monotonic() > deadline:
                    raise TimeoutError(
                        "segment replay exceeded its deadline")
                if r["op"] == "put":
                    e = Event.from_json(r["event"])
                    if e.event_id in live:
                        dead += 1
                    live[e.event_id] = e
                elif r["op"] == "del":
                    if live.pop(r["id"], None) is not None:
                        dead += 1
                    dead += 1
        with self.c._seg_lock:
            self.c.replay_cache[d] = (segments, live, dead)
        return live, dead

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        live, _ = self._replay(app_id, channel_id)
        return live.get(event_id)

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        live, dead = self._replay(app_id, channel_id)
        if event_id not in live:
            return False
        d = self._dir(app_id, channel_id)
        self._publish(d, [{"op": "del", "id": event_id}])
        if dead + 2 > _COMPACT_RATIO * len(live):
            self._compact(app_id, channel_id)
        return True

    def _compact(self, app_id: int, channel_id: Optional[int]) -> None:
        """Merge the log into one segment. Old segments stay on disk for
        a grace period (readers holding the previous manifest), then
        :meth:`gc` removes them."""
        d = self._dir(app_id, channel_id)
        with _flock(self._manifest_path(d)):
            old = self._read_manifest(d)
            live, dead = self._replay(app_id, channel_id)
            if dead == 0:
                return
            records = [{"op": "put", "event": e.to_json()}
                       for e in live.values()]
            name = self._write_segment(d, records) if records else None
            self._write_manifest(d, [name] if name else [])
            # restart the gc grace clock from the moment a segment became
            # UNREFERENCED (not from its creation): a reader holding the
            # pre-compaction manifest must keep finding these files
            now = time.time()
            for n in old:
                if n != name:
                    try:
                        os.utime(os.path.join(d, n), (now, now))
                    except OSError:
                        pass

    def gc(self, app_id: int, channel_id: Optional[int] = None,
           grace_s: float = _GC_GRACE_S) -> int:
        """Delete unreferenced segment files older than ``grace_s``.

        Holds the manifest lock: publishing writes the segment and links
        it under the same lock, so gc can never collect a file between
        its write and its manifest entry (and the referenced-set it reads
        is the current one)."""
        d = self._dir(app_id, channel_id)
        if not os.path.isdir(d):
            return 0
        n = 0
        now = time.time()
        with _flock(self._manifest_path(d)):
            referenced = set(self._read_manifest(d))
            for name in os.listdir(d):
                # unreferenced segments AND crashed-writer temp files
                sweepable = (name.startswith("seg-")
                             and name not in referenced) \
                    or ".tmp." in name
                if not sweepable:
                    continue
                p = os.path.join(d, name)
                try:
                    if now - os.path.getmtime(p) >= grace_s:
                        os.unlink(p)
                        with self.c._seg_lock:
                            self.c.segment_cache.pop(p, None)
                        n += 1
                except OSError:
                    pass
        return n

    # -- columnar bulk reads (PEvents role, pod edition) -------------------
    #
    # The jsonl log is the authoritative store; a shared-filesystem
    # ``SegmentLog`` sidecar (``<log>/columnar/``) holds the same
    # dictionary-encoded numpy segments the SQLite backend builds — but
    # here the sidecar itself lives on the SHARED mount, so ONE pod host
    # pays the encode and every other host mmaps the published segments
    # (no per-host JSONL re-parse; VERDICT r2 weak #4). The sidecar's
    # watermark is the list of jsonl segments consumed; appends encode
    # only the delta, while deletes/replacements/compaction force a
    # rebuild (detected via a per-segment 64-bit id-hash column).

    def _columnar_dir(self, d: str) -> str:
        return os.path.join(d, "columnar")

    def warm_columnar(self, app_id: int,
                      channel_id: Optional[int] = None) -> bool:
        # encode persists ALL columns; want_props=False just skips
        # loading the property bytes into this process
        self._sync_columnar(app_id, channel_id, ("rating",),
                            want_props=False)
        return True

    def find_columnar(self, app_id: int, channel_id: Optional[int] = None,
                      filter: EventFilter = EventFilter(),
                      float_props: Sequence[str] = ("rating",),
                      ordered: bool = True, with_props: bool = True,
                      shard=None):
        batch = self._sync_columnar(app_id, channel_id,
                                    tuple(float_props),
                                    want_props=with_props)
        if shard is not None:
            # zero-copy row range over the shared-mount mmap: each pod
            # host's shard touches only its own segment pages
            return self._shard_and_select(batch, shard, filter,
                                          ordered=ordered,
                                          with_props=with_props)
        return batch.select(filter, ordered=ordered,
                            with_props=with_props)

    def aggregate_properties(self, app_id: int,
                             channel_id: Optional[int] = None, *,
                             entity_type: str, start_time=None,
                             until_time=None, required=None):
        from ..aggregation import AGGREGATION_EVENTS, aggregate_from_columnar
        batch = self._sync_columnar(app_id, channel_id, ("rating",),
                                    want_props=True)
        sub = batch.select(EventFilter(
            entity_type=entity_type, start_time=start_time,
            until_time=until_time,
            event_names=list(AGGREGATION_EVENTS)), ordered=False)
        result = aggregate_from_columnar(sub)
        if required:
            req = set(required)
            result = {k: v for k, v in result.items()
                      if req <= set(v.keys())}
        return result

    def _sync_columnar(self, app_id: int, channel_id: Optional[int],
                       float_props: tuple, want_props: bool = True):
        """``want_props=False`` (the training read) skips loading the
        property-byte columns entirely — on an IO-bound shared mount
        they are a large fraction of a cold read no trainer touches."""
        from ..columnar import ColumnarBatch, SegmentLog

        d = self._dir(app_id, channel_id)
        src = tuple(self._read_manifest(d))
        ck = ("columnar", d, bool(want_props))
        with self.c._seg_lock:
            cached = self.c.replay_cache.get(ck)
        if cached is not None and cached[0] == src:
            return cached[1]
        if not src:
            return ColumnarBatch.empty(float_props=float_props)
        log = SegmentLog(self._columnar_dir(d))
        with log.lock():
            # re-read the jsonl manifest INSIDE the sidecar lock: another
            # host may have appended (and synced the sidecar) since the
            # lock-free read above — a stale view must not be mistaken
            # for changed history
            src = tuple(self._read_manifest(d))
            man = log.read_manifest()
            if log.format_stale(man):
                # older encoded format (e.g. the v1 epoch-seconds
                # event_time bug): rebuild from the source log
                log.invalidate(grace_s=_GC_GRACE_S)
                man = None
            from ..columnar import hash_impl
            if man is not None and man.get("hash_impl") != hash_impl():
                # the writer's bulk_hash64 differs from ours (pandas
                # siphash vs blake2b): stored id_hash columns can never
                # match, so the crash-replay dup check would fail open
                # and append duplicate rows — rebuild instead. Loud:
                # MIXED-stack pods ping-pong full re-encodes forever;
                # the fix is homogeneous stacks, not silent rebuilds.
                log_.warning(
                    "segmentfs sidecar %s was hashed with %r but this "
                    "host uses %r — rebuilding; mixed pandas/non-pandas "
                    "hosts on one mount will thrash rebuilds",
                    self._columnar_dir(d),
                    (man or {}).get("hash_impl"), hash_impl())
                log.invalidate(grace_s=_GC_GRACE_S)
                man = None
            done: tuple = tuple((man or {}).get("watermark") or ())
            if man is not None and done != src[:len(done)]:
                if done[:len(src)] == src:
                    # the sidecar is AHEAD of this host's (attribute-
                    # cache-lagged) manifest view: it reflects a newer
                    # log version, which an append-only reader may use —
                    # never destroy the shared encode for being fresh
                    src = done
                else:
                    # compaction / manifest rewrite: history changed
                    log.invalidate(grace_s=_GC_GRACE_S)
                    man, done = None, ()
            delta = src[len(done):]
            if delta:
                self._encode_columnar_delta(log, d, src, done, delta,
                                            float_props, app_id,
                                            channel_id)
            batch, _ = log.load(with_props=want_props)
            if batch is None:
                batch = ColumnarBatch.empty(float_props=float_props)
            log.sweep(_GC_GRACE_S)
        with self.c._seg_lock:
            self.c.replay_cache[ck] = (src, batch)
        return batch

    def _stored_id_hashes(self, log) -> "np.ndarray":
        """Concatenated per-segment id-hash columns (uint64), or None if
        any segment is missing its hash file (crash window → rebuild)."""
        import numpy as np

        man = log.read_manifest()
        if man is None:
            return np.empty(0, np.uint64)
        parts = []
        for seg in man["segments"]:
            p = os.path.join(log.path, seg["name"], "id_hash.npy")
            if not os.path.exists(p):
                return None
            parts.append(np.load(p, mmap_mode="r", allow_pickle=False))
        return np.concatenate(parts) if parts else np.empty(0, np.uint64)

    #: delta records per sidecar segment append (bounds host memory —
    #: a compacted jsonl log can be ONE multi-million-line segment)
    COLUMNAR_CHUNK = 500_000
    #: bytes per native-codec parse call (plus the current line's tail)
    CODEC_BLOCK = 64 << 20

    @staticmethod
    def _iter_records(path: str) -> Iterator[dict]:
        """Stream-parse a jsonl segment WITHOUT the replay cache: the
        encode touches each segment once, and caching would pin the
        whole parsed log as Python dicts for the process lifetime."""
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                if line.strip():
                    yield json.loads(line)

    #: encode-chunk column names (parallel lists)
    _CCOLS = ("event", "entity_type", "entity_id", "target_type",
              "target_id", "time_iso", "event_id", "props_raw")

    def _iter_segment_columns(self, path: str, float_props: tuple):
        """Yield column-dict blocks for one jsonl segment — the NATIVE
        codec (C++ tokenizer, predictionio_tpu/native) when available,
        else a pure-Python fallback with identical semantics. Yields
        ``None`` (then stops) on the first non-"put" record: the caller
        rebuilds (deletes falsify incremental encode)."""
        from ...native import codec

        m = codec()
        if m is not None:
            yielded = False
            try:
                with open(path, "rb") as f:
                    while True:
                        data = f.read(self.CODEC_BLOCK)
                        if not data:
                            return
                        tail = f.readline()  # finish the cut line
                        if tail:
                            data += tail
                        out = m.parse_segment(data, tuple(float_props))
                        if out is None:
                            yield None
                            return
                        ev, et, ei, tt, ti, times, ids, praw, fps = out
                        if not ev:
                            continue  # blank-only block
                        yielded = True
                        yield {"event": ev, "entity_type": et,
                               "entity_id": ei, "target_type": tt,
                               "target_id": ti, "time_iso": times,
                               "event_id": ids, "props_raw": praw,
                               "fprops": fps}
                return
            except (ValueError, UnicodeDecodeError):
                # content the strict C++ tokenizer refuses (e.g. LONE
                # surrogate escapes, which Python's json round-trips).
                # Only a CLEAN restart may redo the segment on the
                # Python path — if blocks already went downstream, a
                # re-read would duplicate them (dup-check → pointless
                # full rebuild); signal rebuild directly instead.
                if yielded:
                    yield None
                    return
        from ..columnar import bulk_to_float64

        def fresh():
            c = {k: [] for k in self._CCOLS}
            c["fprops"] = [[] for _ in float_props]
            return c

        def finish(c):
            # ONE numbers-only gate for both producers (bulk_to_float64;
            # the codec applies the same gate in C++)
            c["fprops"] = [bulk_to_float64(raw).tolist()
                           for raw in c["fprops"]]
            return c

        cols = fresh()
        n = 0
        for r in self._iter_records(path):
            if r["op"] != "put":
                yield None
                return
            e = r["event"]
            props = e.get("properties")
            cols["event"].append(e["event"])
            cols["entity_type"].append(e["entityType"])
            cols["entity_id"].append(e["entityId"])
            cols["target_type"].append(e.get("targetEntityType"))
            cols["target_id"].append(e.get("targetEntityId"))
            cols["time_iso"].append(e["eventTime"])
            cols["event_id"].append(e.get("eventId") or "")
            cols["props_raw"].append(
                json.dumps(props).encode("utf-8") if props else None)
            for w, nm in enumerate(float_props):
                cols["fprops"][w].append((props or {}).get(nm))
            n += 1
            if n >= self.COLUMNAR_CHUNK:
                yield finish(cols)
                cols = fresh()
                n = 0
        if n:
            yield finish(cols)

    def _encode_columnar_delta(self, log, d: str, src: tuple, done: tuple,
                               delta: tuple, float_props: tuple,
                               app_id: int,
                               channel_id: Optional[int]) -> None:
        import numpy as np

        from ..columnar import bulk_hash64, hash_impl

        def rebuild() -> None:
            # deletes/replacements: rebuild the projection of LIVE
            # events, replaying EXACTLY the src manifest version the
            # watermark will record (a fresh manifest read could have
            # advanced past it). Retired segments keep the reader grace.
            live, _ = self._replay(app_id, channel_id, segments=src)
            log.invalidate(grace_s=_GC_GRACE_S)
            if not live:
                from ..columnar import ColumnarBatch
                log.append(ColumnarBatch.empty(float_props=float_props),
                           watermark=list(src), prev_dict_counts={},
                           hash_impl=hash_impl())
                self._write_id_hashes(log, np.empty(0, np.uint64))
                return
            events = list(live.values())
            ids = np.asarray(list(live.keys()), dtype=object)
            prev_counts: dict = {}
            for s in range(0, len(events), self.COLUMNAR_CHUNK):
                from ..columnar import columnar_from_events
                dicts, prev_counts = log.dicts_and_counts()
                batch = columnar_from_events(
                    events[s:s + self.COLUMNAR_CHUNK], dicts=dicts,
                    float_props=float_props)
                # only the FINAL chunk's manifest commit may claim the
                # src watermark: a crash between chunk appends must
                # leave a sidecar the next reader detects as stale
                # (sentinel ⇒ invalidate+rebuild), not serve a
                # truncated batch as the complete training read
                final = s + self.COLUMNAR_CHUNK >= len(events)
                log.append(batch,
                           watermark=list(src) if final
                           else [_REBUILD_WM],
                           prev_dict_counts=prev_counts,
                           hash_impl=hash_impl())
                self._write_id_hashes(
                    log, bulk_hash64(ids[s:s + self.COLUMNAR_CHUNK]))

        stored = self._stored_id_hashes(log)
        if stored is None:
            rebuild()  # hash-file crash window: can't dup-check
            return
        stored = np.asarray(stored)
        consumed = list(done)
        chunk: Optional[dict] = None

        def extend(acc, cols):
            if acc is None:
                return cols
            for k in self._CCOLS:
                acc[k].extend(cols[k])
            for w in range(len(acc["fprops"])):
                acc["fprops"][w].extend(cols["fprops"][w])
            return acc

        def flush(chunk, consumed_after) -> bool:
            """Encode one chunk; False → dup detected, caller rebuilds."""
            nonlocal stored
            new_h = bulk_hash64(
                np.asarray(chunk["event_id"], dtype=object))
            if len(np.unique(new_h)) != len(new_h) \
                    or (len(stored) and np.isin(new_h, stored).any()):
                return False
            self._append_put_chunk(log, chunk, consumed_after,
                                   float_props, new_h)
            stored = np.concatenate([stored, new_h])
            return True

        def split(c, n):
            """First n rows of a column chunk, and the remainder."""
            head = {k: c[k][:n] for k in self._CCOLS}
            head["fprops"] = [f[:n] for f in c["fprops"]]
            rest = {k: c[k][n:] for k in self._CCOLS}
            rest["fprops"] = [f[n:] for f in c["fprops"]]
            return head, (rest if rest["event"] else None)

        for name in delta:
            for cols in self._iter_segment_columns(
                    os.path.join(d, name), float_props):
                if cols is None:
                    rebuild()
                    return
                chunk = extend(chunk, cols)
                while chunk is not None \
                        and len(chunk["event"]) >= self.COLUMNAR_CHUNK:
                    # mid-segment flush in CHUNK-row slices (a codec
                    # block can carry several chunks' worth): watermark
                    # only advances at segment boundaries (crash ⇒
                    # re-encode of this segment is caught by the dup
                    # check → rebuild)
                    head, chunk = split(chunk, self.COLUMNAR_CHUNK)
                    if not flush(head, consumed):
                        rebuild()
                        return
            consumed.append(name)
            if chunk is not None \
                    and len(chunk["event"]) >= self.COLUMNAR_CHUNK // 2:
                if not flush(chunk, consumed):
                    rebuild()
                    return
                chunk = None
        if chunk is not None and chunk["event"]:
            if not flush(chunk, consumed):
                rebuild()
                return
        elif consumed != list(done):
            man = log.read_manifest()
            if man is not None:
                man["watermark"] = consumed
                log._write_manifest(man)

    def _append_put_chunk(self, log, cols: dict, consumed: list,
                          float_props: tuple, new_h) -> None:
        """Commit one column-chunk (see ``_CCOLS`` + per-prop float
        lists, NaN for missing — both producers pre-apply the
        numbers-only gate) as a sidecar segment."""
        import numpy as np

        from ..columnar import (
            bulk_iso_to_millis,
            columnar_from_columns,
            hash_impl,
        )

        dicts, prev_counts = log.dicts_and_counts()
        times = bulk_iso_to_millis(cols["time_iso"])
        fpv = {nm: np.asarray(cols["fprops"][w], dtype=np.float64)
               for w, nm in enumerate(float_props)}
        batch = columnar_from_columns(
            dicts, cols["event"], cols["entity_type"],
            cols["entity_id"], cols["target_type"], cols["target_id"],
            np.asarray(times, dtype=np.int64), cols["props_raw"],
            float_props=float_props, float_prop_values=fpv)
        log.append(batch, watermark=list(consumed),
                   prev_dict_counts=prev_counts,
                   hash_impl=hash_impl())
        self._write_id_hashes(log, new_h)

    def _write_id_hashes(self, log, hashes) -> None:
        """Persist the id-hash column beside the newest segment (written
        after the manifest commit; a crash in between leaves a missing
        hash file, which the dup check treats as 'rebuild')."""
        import numpy as np

        man = log.read_manifest()
        seg = man["segments"][-1]["name"]
        np.save(os.path.join(log.path, seg, "id_hash.npy"),
                np.asarray(hashes, dtype=np.uint64),
                allow_pickle=False)

    def find(self, app_id: int, channel_id: Optional[int] = None,
             filter: EventFilter = EventFilter()) -> Iterator[Event]:
        live, _ = self._replay(app_id, channel_id,
                               deadline=filter.deadline)
        # sort by epoch millis, not raw datetimes: naive and tz-aware
        # event times must not TypeError against each other
        events = sorted(live.values(), key=lambda e: e.event_time_millis,
                        reverse=filter.reversed)
        it = filter.apply(events)
        if filter.limit is not None and filter.limit >= 0:
            import itertools
            it = itertools.islice(it, filter.limit)
        return it


def _locked(method_names):
    """Class decorator: wrap mutating DAO methods in the cross-process
    document lock (the LOCALFS implementations they inherit only hold
    the in-process lock — lost updates across pod hosts otherwise)."""
    def deco(cls):
        for mname in method_names:
            base = getattr(cls.__mro__[1], mname)

            def wrapper(self, *a, __base=base, **kw):
                with _flock(self.c.doc_path(self.DOC)):
                    return __base(self, *a, **kw)
            wrapper.__name__ = mname
            setattr(cls, mname, wrapper)
        return cls
    return deco


@_locked(["insert", "update", "delete"])
class SegmentFSApps(localfs.LocalFSApps):
    DOC = "apps"


@_locked(["insert", "update", "delete"])
class SegmentFSAccessKeys(localfs.LocalFSAccessKeys):
    DOC = "access_keys"


@_locked(["insert", "delete"])
class SegmentFSChannels(localfs.LocalFSChannels):
    DOC = "channels"


@_locked(["insert", "update", "delete"])
class SegmentFSEngineInstances(localfs.LocalFSEngineInstances):
    DOC = "engine_instances"


@_locked(["insert", "update", "delete"])
class SegmentFSEvaluationInstances(localfs.LocalFSEvaluationInstances):
    DOC = "evaluation_instances"


class SegmentFSModels(localfs.LocalFSModels):
    pass  # inherits the temp+rename atomic blob writes
