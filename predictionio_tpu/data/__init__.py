"""Data layer: event model, property maps, aggregation, storage, stores."""

from .datamap import DataMap, DataMapError, PropertyMap
from .event import Event, EventValidationError, SPECIAL_EVENTS
from .bimap import BiMap
from .entitymap import EntityIdIxMap, EntityMap, extract_entity_map
from .aggregation import (
    EventOp,
    aggregate_properties,
    aggregate_properties_ordered,
    aggregate_properties_single,
)

__all__ = [
    "DataMap",
    "DataMapError",
    "PropertyMap",
    "Event",
    "EventValidationError",
    "SPECIAL_EVENTS",
    "BiMap",
    "EntityIdIxMap",
    "EntityMap",
    "extract_entity_map",
    "EventOp",
    "aggregate_properties",
    "aggregate_properties_ordered",
    "aggregate_properties_single",
]
