"""Data layer: event model, property maps, aggregation, storage, stores."""

from .datamap import DataMap, DataMapError, PropertyMap
from .event import Event, EventValidationError, SPECIAL_EVENTS
from .bimap import BiMap
from .aggregation import (
    EventOp,
    aggregate_properties,
    aggregate_properties_ordered,
    aggregate_properties_single,
)

__all__ = [
    "DataMap",
    "DataMapError",
    "PropertyMap",
    "Event",
    "EventValidationError",
    "SPECIAL_EVENTS",
    "BiMap",
    "EventOp",
    "aggregate_properties",
    "aggregate_properties_ordered",
    "aggregate_properties_single",
]
