"""Schemaless property maps.

Capability parity with the reference's ``DataMap`` (json4s-backed;
``data/src/main/scala/org/apache/predictionio/data/storage/DataMap.scala:56-122``)
and ``PropertyMap`` (``data/.../storage/PropertyMap.scala``), re-designed on
plain Python JSON values: a ``DataMap`` wraps a dict of JSON-compatible values
with typed accessors; a ``PropertyMap`` additionally carries the first/last
updated times produced by property aggregation.
"""

from __future__ import annotations

import json
from datetime import datetime
from typing import Any, Iterator, Mapping, Optional, Type, TypeVar

T = TypeVar("T")

_JSON_TYPES = (type(None), bool, int, float, str, list, dict)


class DataMapError(KeyError):
    """Raised when a required field is missing or has the wrong type."""


class DataMap(Mapping[str, Any]):
    """An immutable, schemaless map of JSON values with typed ``get``.

    Unlike the reference's json4s AST, values are plain Python JSON values
    (None/bool/int/float/str/list/dict); ``get(name, type)`` performs the
    typed extraction the reference does with manifests.
    """

    __slots__ = ("_fields",)

    def __init__(self, fields: Optional[Mapping[str, Any] | str] = None):
        if fields is None:
            fields = {}
        elif isinstance(fields, str):
            fields = json.loads(fields)
        elif isinstance(fields, DataMap):
            fields = fields._fields
        if not isinstance(fields, Mapping):
            raise DataMapError(f"DataMap requires a JSON object, got {type(fields)}")
        self._fields = dict(fields)

    # -- Mapping protocol --------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        try:
            return self._fields[key]
        except KeyError:
            raise DataMapError(f"The field {key} is required.")

    def __iter__(self) -> Iterator[str]:
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def __contains__(self, key: object) -> bool:
        return key in self._fields

    def __eq__(self, other: object) -> bool:
        if isinstance(other, DataMap):
            return self._fields == other._fields
        if isinstance(other, Mapping):
            return self._fields == dict(other)
        return NotImplemented

    def __hash__(self) -> int:
        return hash(json.dumps(self._fields, sort_keys=True, default=str))

    def __repr__(self) -> str:
        return f"DataMap({self._fields!r})"

    # -- typed access ------------------------------------------------------
    def get(self, name: str, cls: Optional[Type[T]] = None, default: Any = ...) -> Any:
        """Typed field access: ``get("a", int)``; raises :class:`DataMapError`
        when the field is absent (unless ``default`` is given) or not
        convertible to ``cls``."""
        if name not in self._fields:
            if default is not ...:
                return default
            raise DataMapError(f"The field {name} is required.")
        v = self._fields[name]
        if cls is None:
            return v
        return _coerce(name, v, cls)

    def get_opt(self, name: str, cls: Optional[Type[T]] = None) -> Optional[T]:
        """Optional typed access; returns None when absent or null."""
        v = self._fields.get(name)
        if v is None:
            return None
        return _coerce(name, v, cls) if cls is not None else v

    def get_list(self, name: str, cls: Optional[Type[T]] = None) -> list:
        v = self.get(name)
        if not isinstance(v, list):
            raise DataMapError(f"The field {name} is not a list.")
        if cls is None:
            return list(v)
        return [_coerce(name, x, cls) for x in v]

    # -- algebra (used by aggregation) -------------------------------------
    def union(self, other: "DataMap | Mapping[str, Any]") -> "DataMap":
        """Right-biased merge (reference ``DataMap.++``)."""
        merged = dict(self._fields)
        merged.update(dict(other))
        return DataMap(merged)

    def without(self, keys) -> "DataMap":
        """Remove keys (reference ``DataMap.--``)."""
        drop = set(keys)
        return DataMap({k: v for k, v in self._fields.items() if k not in drop})

    def keys(self):
        return self._fields.keys()

    def to_dict(self) -> dict:
        return dict(self._fields)

    def to_json(self) -> str:
        return json.dumps(self._fields, sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "DataMap":
        return DataMap(json.loads(s))


def _coerce(name: str, v: Any, cls: Type[T]) -> T:
    if cls is float and isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)  # type: ignore[return-value]
    if cls is int and isinstance(v, bool):
        raise DataMapError(f"The field {name} is not an int.")
    if cls is int and isinstance(v, float) and v.is_integer():
        return int(v)  # type: ignore[return-value]
    if cls is bool and not isinstance(v, bool):
        raise DataMapError(f"The field {name} is not a bool.")
    if not isinstance(v, cls):
        raise DataMapError(f"The field {name} has type {type(v).__name__}, "
                           f"expected {cls.__name__}.")
    return v


class PropertyMap(DataMap):
    """A :class:`DataMap` with aggregation bookkeeping: when the entity's
    properties were first and last updated (reference
    ``data/.../storage/PropertyMap.scala``)."""

    __slots__ = ("first_updated", "last_updated")

    def __init__(self, fields: Optional[Mapping[str, Any] | str],
                 first_updated: datetime, last_updated: datetime):
        super().__init__(fields)
        self.first_updated = first_updated
        self.last_updated = last_updated

    def __eq__(self, other: object) -> bool:
        if isinstance(other, PropertyMap):
            return (self._fields == other._fields
                    and self.first_updated == other.first_updated
                    and self.last_updated == other.last_updated)
        return super().__eq__(other)

    def __hash__(self) -> int:
        return hash((super().__hash__(), self.first_updated, self.last_updated))

    def __repr__(self) -> str:
        return (f"PropertyMap({self._fields!r}, first_updated="
                f"{self.first_updated!r}, last_updated={self.last_updated!r})")

    def to_datamap(self) -> DataMap:
        return DataMap(self._fields)
