"""EntityMap: id-indexed entity data.

Capability parity with ``data/.../storage/EntityMap.scala``
(``EntityIdIxMap`` :28-66, ``EntityMap`` :69-…) and
``PEvents.extractEntityMap`` (``storage/PEvents.scala:136-…``): a
string-id ↔ dense-int indexation plus per-entity payloads extracted from
aggregated properties — the host-side precursor to device-resident
embedding/feature tables keyed by the same dense ids.
"""

from __future__ import annotations

from typing import Callable, Dict, Generic, Iterable, Optional, TypeVar

from .bimap import BiMap
from .datamap import PropertyMap

A = TypeVar("A")


class EntityIdIxMap:
    """String id ↔ dense index (``EntityIdIxMap``)."""

    def __init__(self, id_to_ix: BiMap):
        self.id_to_ix = id_to_ix
        self.ix_to_id = id_to_ix.inverse

    @staticmethod
    def from_keys(keys: Iterable[str]) -> "EntityIdIxMap":
        return EntityIdIxMap(BiMap.string_int(keys))

    def __getitem__(self, key):
        if isinstance(key, str):
            return self.id_to_ix[key]
        return self.ix_to_id[key]

    def __contains__(self, key) -> bool:
        return (key in self.id_to_ix if isinstance(key, str)
                else key in self.ix_to_id)

    def get(self, key, default=None):
        return (self.id_to_ix.get(key, default) if isinstance(key, str)
                else self.ix_to_id.get(key, default))

    def to_map(self) -> Dict[str, int]:
        return self.id_to_ix.to_dict()

    def __len__(self) -> int:
        return len(self.id_to_ix)

    def _first_keys(self, n: int) -> list:
        import itertools

        return list(itertools.islice(self.id_to_ix.keys(), n))

    def take(self, n: int) -> "EntityIdIxMap":
        return EntityIdIxMap(self.id_to_ix.take(self._first_keys(n)))


class EntityMap(EntityIdIxMap, Generic[A]):
    """EntityIdIxMap + a payload per entity (``EntityMap[A]``)."""

    def __init__(self, id_to_data: Dict[str, A],
                 id_to_ix: Optional[BiMap] = None):
        super().__init__(id_to_ix if id_to_ix is not None
                         else BiMap.string_int(id_to_data.keys()))
        self.id_to_data = dict(id_to_data)

    def data(self, key) -> A:
        if isinstance(key, str):
            return self.id_to_data[key]
        return self.id_to_data[self.ix_to_id[key]]

    def take(self, n: int) -> "EntityMap[A]":
        """First-n entities WITH their payloads (the reference's
        ``EntityMap.take`` override)."""
        keys = self._first_keys(n)
        return EntityMap({k: self.id_to_data[k] for k in keys},
                         self.id_to_ix.take(keys))


def extract_entity_map(store, app_name: str, entity_type: str,
                       extract: Callable[[PropertyMap], A],
                       channel_name: Optional[str] = None,
                       start_time=None, until_time=None,
                       required=None) -> EntityMap[A]:
    """``PEvents.extractEntityMap`` over the facade: aggregate an entity
    type's properties and map each through ``extract``."""
    props = store.aggregate_properties(
        app_name, entity_type, channel_name=channel_name,
        start_time=start_time, until_time=until_time, required=required)
    return EntityMap({eid: extract(pm) for eid, pm in props.items()})
