"""Webhook connectors: third-party payloads → framework events.

Capability parity with the reference's webhook layer
(``data/webhooks/{JsonConnector,FormConnector,ConnectorUtil}.scala`` and
the registry ``data/api/WebhooksConnectors.scala:30-34``): a connector
translates one provider's payload into the event-JSON wire format, and the
Event Server routes ``/webhooks/<name>.json`` / ``.form`` through this
registry.
"""

from __future__ import annotations

import abc
from typing import Dict, Mapping

from ..event import Event

__all__ = ["ConnectorException", "JsonConnector", "FormConnector",
           "json_connectors", "form_connectors", "to_event"]


class ConnectorException(Exception):
    """Payload could not be converted (``ConnectorException.scala``)."""


class JsonConnector(abc.ABC):
    """JSON-body webhook converter (``JsonConnector.scala``)."""

    @abc.abstractmethod
    def to_event_json(self, data: Mapping) -> dict:
        """Return the event-JSON dict for one provider payload."""


class FormConnector(abc.ABC):
    """Form-encoded webhook converter (``FormConnector.scala``)."""

    @abc.abstractmethod
    def to_event_json(self, data: Mapping[str, str]) -> dict:
        ...


def to_event(connector, data: Mapping) -> Event:
    """Convert and parse in one step (``ConnectorUtil.toEvent``)."""
    return Event.from_json(connector.to_event_json(data))


def _builtin_json() -> Dict[str, JsonConnector]:
    from .segmentio import SegmentIOConnector
    return {"segmentio": SegmentIOConnector()}


def _builtin_form() -> Dict[str, FormConnector]:
    from .mailchimp import MailChimpConnector
    return {"mailchimp": MailChimpConnector()}


#: name → connector registries (``WebhooksConnectors.scala:30-34``).
json_connectors: Dict[str, JsonConnector] = _builtin_json()
form_connectors: Dict[str, FormConnector] = _builtin_form()
