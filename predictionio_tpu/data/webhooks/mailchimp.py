"""MailChimp form-webhook connector.

Capability parity with the reference connector
(``data/webhooks/mailchimp/MailChimpConnector.scala``): converts
MailChimp's form-encoded webhook payloads (``type`` ∈ subscribe,
unsubscribe, profile, upemail, cleaned, campaign; bracketed ``data[...]``
keys; ``fired_at`` as ``YYYY-MM-DD HH:MM:SS`` UTC) into event JSON with
the same entity/target mappings (user→list for member events, list for
cleaned, campaign→list for campaign sends).
"""

from __future__ import annotations

from datetime import datetime, timezone
from typing import Mapping, Optional

from . import ConnectorException, FormConnector
from ..event import isoformat_millis


def _parse_fired_at(s: str) -> str:
    try:
        t = datetime.strptime(s, "%Y-%m-%d %H:%M:%S")
    except ValueError:
        raise ConnectorException(f"invalid fired_at time: {s!r}")
    return isoformat_millis(t.replace(tzinfo=timezone.utc))


def _merges(data: Mapping[str, str]) -> dict:
    out = {}
    for k in ("EMAIL", "FNAME", "LNAME"):
        key = f"data[merges][{k}]"
        if key in data:
            out[k] = data[key]
    if "data[merges][INTERESTS]" in data:
        out["INTERESTS"] = data["data[merges][INTERESTS]"]
    return out


def _get(data: Mapping[str, str], key: str) -> str:
    if key not in data:
        raise ConnectorException(f"missing MailChimp field {key!r}")
    return data[key]


class MailChimpConnector(FormConnector):
    def to_event_json(self, data: Mapping[str, str]) -> dict:
        msg_type: Optional[str] = data.get("type")
        handlers = {
            "subscribe": self._subscribe,
            "unsubscribe": self._unsubscribe,
            "profile": self._profile,
            "upemail": self._upemail,
            "cleaned": self._cleaned,
            "campaign": self._campaign,
        }
        if msg_type not in handlers:
            raise ConnectorException(
                f"Cannot convert unknown MailChimp type {msg_type} to event JSON.")
        return handlers[msg_type](data)

    def _subscribe(self, d: Mapping[str, str]) -> dict:
        return {
            "event": "subscribe",
            "entityType": "user", "entityId": _get(d, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": _get(d, "data[list_id]"),
            "eventTime": _parse_fired_at(_get(d, "fired_at")),
            "properties": {
                "email": _get(d, "data[email]"),
                "email_type": _get(d, "data[email_type]"),
                "merges": _merges(d),
                "ip_opt": _get(d, "data[ip_opt]"),
                "ip_signup": _get(d, "data[ip_signup]"),
            },
        }

    def _unsubscribe(self, d: Mapping[str, str]) -> dict:
        return {
            "event": "unsubscribe",
            "entityType": "user", "entityId": _get(d, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": _get(d, "data[list_id]"),
            "eventTime": _parse_fired_at(_get(d, "fired_at")),
            "properties": {
                "action": _get(d, "data[action]"),
                "reason": _get(d, "data[reason]"),
                "email": _get(d, "data[email]"),
                "email_type": _get(d, "data[email_type]"),
                "merges": _merges(d),
                "ip_opt": _get(d, "data[ip_opt]"),
                "campaign_id": _get(d, "data[campaign_id]"),
            },
        }

    def _profile(self, d: Mapping[str, str]) -> dict:
        return {
            "event": "profile",
            "entityType": "user", "entityId": _get(d, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": _get(d, "data[list_id]"),
            "eventTime": _parse_fired_at(_get(d, "fired_at")),
            "properties": {
                "email": _get(d, "data[email]"),
                "email_type": _get(d, "data[email_type]"),
                "merges": _merges(d),
                "ip_opt": _get(d, "data[ip_opt]"),
            },
        }

    def _upemail(self, d: Mapping[str, str]) -> dict:
        return {
            "event": "upemail",
            "entityType": "user", "entityId": _get(d, "data[new_id]"),
            "targetEntityType": "list",
            "targetEntityId": _get(d, "data[list_id]"),
            "eventTime": _parse_fired_at(_get(d, "fired_at")),
            "properties": {
                "new_email": _get(d, "data[new_email]"),
                "old_email": _get(d, "data[old_email]"),
            },
        }

    def _cleaned(self, d: Mapping[str, str]) -> dict:
        return {
            "event": "cleaned",
            "entityType": "list", "entityId": _get(d, "data[list_id]"),
            "eventTime": _parse_fired_at(_get(d, "fired_at")),
            "properties": {
                "campaignId": _get(d, "data[campaign_id]"),
                "reason": _get(d, "data[reason]"),
                "email": _get(d, "data[email]"),
            },
        }

    def _campaign(self, d: Mapping[str, str]) -> dict:
        return {
            "event": "campaign",
            "entityType": "campaign", "entityId": _get(d, "data[id]"),
            "targetEntityType": "list",
            "targetEntityId": _get(d, "data[list_id]"),
            "eventTime": _parse_fired_at(_get(d, "fired_at")),
            "properties": {
                "subject": _get(d, "data[subject]"),
                "status": _get(d, "data[status]"),
                "reason": _get(d, "data[reason]"),
            },
        }
