"""Segment.io webhook connector.

Capability parity with the reference connector
(``data/webhooks/segmentio/SegmentIOConnector.scala``): accepts Segment
v2-style payloads (snake_case keys: ``type``, ``user_id``/``anonymous_id``,
``timestamp``, ``version``) for the six message types ``identify``,
``track``, ``alias``, ``page``, ``screen``, ``group``, and emits event
JSON with the message type as the event name, ``entityType="user"``, the
user (or anonymous) id as the entity id, and per-type payload fields —
plus the ``context`` object, when present — folded into ``properties``.
"""

from __future__ import annotations

from typing import Mapping

from . import ConnectorException, JsonConnector


def _require(data: Mapping, key: str) -> object:
    if key not in data:
        raise ConnectorException(
            f"Cannot extract {key!r} from segment.io payload.")
    return data[key]


#: type → payload fields folded into event properties.
_TYPE_FIELDS = {
    "identify": ("traits",),
    "track": ("properties", "event"),
    "alias": ("previous_id",),
    "screen": ("name", "properties"),
    "page": ("name", "properties"),
    "group": ("group_id", "traits"),
}


class SegmentIOConnector(JsonConnector):
    def to_event_json(self, data: Mapping) -> dict:
        if "version" not in data:
            raise ConnectorException("Failed to get segment.io API version.")
        msg_type = str(_require(data, "type"))
        if msg_type not in _TYPE_FIELDS:
            raise ConnectorException(
                f"Cannot convert unknown type {msg_type} to event JSON.")
        user_id = data.get("user_id") or data.get("anonymous_id")
        if not user_id:
            raise ConnectorException(
                "there was no `userId` or `anonymousId` in the common fields.")

        properties = {}
        for field in _TYPE_FIELDS[msg_type]:
            if data.get(field) is not None:
                properties[field] = data[field]
        if data.get("context") is not None:
            properties["context"] = data["context"]

        out = {
            "event": msg_type,
            "entityType": "user",
            "entityId": str(user_id),
            "properties": properties,
        }
        if data.get("timestamp"):
            out["eventTime"] = data["timestamp"]
        return out
