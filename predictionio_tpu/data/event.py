"""Event model for the TPU-native framework.

Capability parity with the reference event model
(``data/src/main/scala/org/apache/predictionio/data/storage/Event.scala:42-53``,
validation rules at ``Event.scala:112-160``, special events at ``Event.scala:83``),
re-designed for a Python host layer: events are immutable dataclasses whose
properties are schemaless :class:`~predictionio_tpu.data.datamap.DataMap` values,
with millisecond-precision UTC timestamps.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field, replace
from datetime import datetime, timezone
from typing import Any, Mapping, Optional, Sequence

from .datamap import DataMap

#: Reserved events that mutate entity properties.
SPECIAL_EVENTS = frozenset({"$set", "$unset", "$delete"})


def utcnow() -> datetime:
    """Current time, timezone-aware UTC, truncated to millisecond precision."""
    now = datetime.now(timezone.utc)
    return now.replace(microsecond=(now.microsecond // 1000) * 1000)


def to_millis(t: datetime) -> int:
    """Epoch milliseconds of a (timezone-aware) datetime."""
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    return int(t.timestamp() * 1000)


def from_millis(ms: int) -> datetime:
    return datetime.fromtimestamp(ms / 1000.0, tz=timezone.utc)


class EventValidationError(ValueError):
    """Raised when an event fails the framework's validation rules."""


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise EventValidationError(msg)


@dataclass(frozen=True)
class Event:
    """A single immutable record in the append-only event log.

    Field set matches the reference's ``Event`` case class
    (``data/.../storage/Event.scala:42-53``): name, entity, optional target
    entity, schemaless properties, event time, tags, optional prediction id
    (for the serving feedback loop) and creation time.
    """

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: Optional[str] = None
    target_entity_id: Optional[str] = None
    properties: DataMap = field(default_factory=DataMap)
    event_time: datetime = field(default_factory=utcnow)
    tags: Sequence[str] = ()
    pr_id: Optional[str] = None
    creation_time: datetime = field(default_factory=utcnow)
    event_id: Optional[str] = None

    def __post_init__(self) -> None:
        if not isinstance(self.properties, DataMap):
            object.__setattr__(self, "properties", DataMap(self.properties))
        if isinstance(self.tags, list):
            object.__setattr__(self, "tags", tuple(self.tags))
        validate_event(self)

    # -- convenience -------------------------------------------------------
    @property
    def event_time_millis(self) -> int:
        return to_millis(self.event_time)

    def copy(self, **changes: Any) -> "Event":
        return replace(self, **changes)

    def is_special(self) -> bool:
        return self.event in SPECIAL_EVENTS

    # -- JSON wire format (API-compatible with the reference event server) --
    def to_json(self) -> dict:
        """Render in the REST API's JSON schema (camelCase keys, ISO times),
        mirroring the reference's ``EventJson4sSupport.APISerializer``."""
        out: dict[str, Any] = {
            "event": self.event,
            "entityType": self.entity_type,
            "entityId": self.entity_id,
        }
        if self.event_id is not None:
            out["eventId"] = self.event_id
        if self.target_entity_type is not None:
            out["targetEntityType"] = self.target_entity_type
        if self.target_entity_id is not None:
            out["targetEntityId"] = self.target_entity_id
        if len(self.properties) > 0:
            out["properties"] = self.properties.to_dict()
        out["eventTime"] = isoformat_millis(self.event_time)
        if self.tags:
            out["tags"] = list(self.tags)
        if self.pr_id is not None:
            out["prId"] = self.pr_id
        out["creationTime"] = isoformat_millis(self.creation_time)
        return out

    @staticmethod
    def from_json(obj: Mapping[str, Any]) -> "Event":
        """Parse the REST API's JSON schema into an :class:`Event`."""
        try:
            event = obj["event"]
            entity_type = obj["entityType"]
            entity_id = obj["entityId"]
        except KeyError as e:
            raise EventValidationError(f"missing required field {e.args[0]!r}")
        for k, v in (("event", event), ("entityType", entity_type),
                     ("entityId", entity_id)):
            if not isinstance(v, str):
                raise EventValidationError(f"field {k!r} must be a string")
        event_time = obj.get("eventTime")
        creation_time = obj.get("creationTime")
        return Event(
            event=event,
            entity_type=entity_type,
            entity_id=entity_id,
            target_entity_type=obj.get("targetEntityType"),
            target_entity_id=obj.get("targetEntityId"),
            properties=DataMap(obj.get("properties") or {}),
            event_time=parse_iso(event_time) if event_time else utcnow(),
            tags=tuple(obj.get("tags") or ()),
            pr_id=obj.get("prId"),
            creation_time=parse_iso(creation_time) if creation_time else utcnow(),
            event_id=obj.get("eventId"),
        )


def isoformat_millis(t: datetime) -> str:
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    t = t.astimezone(timezone.utc)
    return t.strftime("%Y-%m-%dT%H:%M:%S.") + f"{t.microsecond // 1000:03d}Z"


def parse_iso(s: str) -> datetime:
    """Parse ISO-8601; accepts 'Z' suffix and fractional seconds."""
    if not isinstance(s, str):
        raise EventValidationError(f"invalid time value: {s!r}")
    raw = s.strip()
    if raw.endswith(("Z", "z")):
        raw = raw[:-1] + "+00:00"
    try:
        t = datetime.fromisoformat(raw)
    except ValueError:
        raise EventValidationError(f"invalid ISO-8601 time: {s!r}")
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    return t


#: Entity types the framework itself writes: prediction feedback
#: entities (``pio_pr``, the serving feedback loop) and the streaming
#: trainer's durable consumer cursors (``pio_stream``, ISSUE 10 —
#: persisted through EVENTDATA so they survive restarts with the log
#: they index).
BUILTIN_ENTITY_TYPES = frozenset({"pio_pr", "pio_stream"})

#: Reserved-prefix property names the framework itself stamps onto
#: events. ``pio_traceparent`` carries the W3C trace context of the
#: ingest request (ISSUE 12, docs/tracing.md) so a streaming fold-in
#: can link the event's trace to the hot-swap that made it servable.
BUILTIN_PROPERTY_NAMES = frozenset({"pio_traceparent"})

#: Reserved name prefix for entity types and property names.
RESERVED_PREFIX = "pio_"


def _is_reserved(name: str) -> bool:
    return name.startswith("$") or name.startswith(RESERVED_PREFIX)


def validate_event(e: Event) -> None:
    """Enforce the reference's event validation rules
    (``data/.../storage/Event.scala:112-160``): non-empty names/ids; target
    entity type/id specified together; reserved ``$``-prefix only for special
    events; ``$unset`` requires non-empty properties; special events take no
    target entity; ``pio_`` prefix reserved for built-in entity types and
    property names.
    """
    _require(bool(e.event), "event must not be empty")
    _require(bool(e.entity_type), "entityType must not be empty")
    _require(bool(e.entity_id), "entityId must not be empty")
    _require(e.target_entity_type is None or bool(e.target_entity_type),
             "targetEntityType must not be empty string")
    _require(e.target_entity_id is None or bool(e.target_entity_id),
             "targetEntityId must not be empty string")
    _require((e.target_entity_type is None) == (e.target_entity_id is None),
             "targetEntityType and targetEntityId must be specified together")
    _require(not _is_reserved(e.event) or e.event in SPECIAL_EVENTS,
             f"{e.event!r} is not a supported reserved event name")
    if e.event == "$unset":
        _require(len(e.properties) > 0, "$unset event requires properties")
    if e.event in SPECIAL_EVENTS:
        _require(e.target_entity_type is None and e.target_entity_id is None,
                 f"reserved event {e.event} cannot have targetEntity")
    _require(not _is_reserved(e.entity_type)
             or e.entity_type in BUILTIN_ENTITY_TYPES,
             f"entityType {e.entity_type!r} is not allowed; "
             f"{RESERVED_PREFIX!r} is a reserved prefix")
    if e.target_entity_type is not None:
        _require(not _is_reserved(e.target_entity_type)
                 or e.target_entity_type in BUILTIN_ENTITY_TYPES,
                 f"targetEntityType {e.target_entity_type!r} is not allowed; "
                 f"{RESERVED_PREFIX!r} is a reserved prefix")
    for k in e.properties.keys():
        _require(not _is_reserved(k) or k in BUILTIN_PROPERTY_NAMES,
                 f"property {k!r} is not allowed; "
                 f"{RESERVED_PREFIX!r} is a reserved prefix")


def new_event_id() -> str:
    """Generate a unique event id (hex UUID4, like the reference's backends)."""
    return uuid.uuid4().hex
