"""Template-facing event store facades.

Capability parity with the reference's ``PEventStore``/``LEventStore``
(``data/.../store/PEventStore.scala:35-121``,
``data/.../store/LEventStore.scala:48-265``): templates address data by
**app name** (+ optional channel name), and the facade resolves names to
ids through the metadata DAOs (``store/Common.scala``).

The L/P split collapses here: one facade serves both the bulk training
reads (events stream into columnar host shards → sharded ``jax.Array``s)
and the serving-time point lookups (``find_by_entity`` with a deadline).
"""

from __future__ import annotations

import time
from datetime import datetime
from typing import Dict, Iterator, List, Optional, Sequence

from .datamap import PropertyMap
from .event import Event
from .storage.base import ANY, EventFilter, StorageError
from .storage.registry import Storage, get_storage


class EventStoreFacade:
    def __init__(self, storage: Optional[Storage] = None):
        self._storage = storage

    @property
    def storage(self) -> Storage:
        return self._storage if self._storage is not None else get_storage()

    # -- name resolution (store/Common.scala appNameToId) ------------------
    def resolve(self, app_name: str,
                channel_name: Optional[str] = None) -> tuple:
        app = self.storage.apps().get_by_name(app_name)
        if app is None:
            raise StorageError(f"App {app_name!r} does not exist; create it "
                               f"first (pio app new {app_name})")
        channel_id = None
        if channel_name is not None:
            chans = self.storage.channels().get_by_app_id(app.id)
            match = next((c for c in chans if c.name == channel_name), None)
            if match is None:
                raise StorageError(f"Channel {channel_name!r} does not exist "
                                   f"in app {app_name!r}")
            channel_id = match.id
        return app.id, channel_id

    # -- bulk reads (PEventStore.find, :59) --------------------------------
    def find(self, app_name: str, channel_name: Optional[str] = None,
             start_time: Optional[datetime] = None,
             until_time: Optional[datetime] = None,
             entity_type: Optional[str] = None,
             entity_id: Optional[str] = None,
             event_names: Optional[Sequence[str]] = None,
             target_entity_type=ANY, target_entity_id=ANY,
             limit: Optional[int] = None,
             reversed: bool = False) -> Iterator[Event]:
        app_id, channel_id = self.resolve(app_name, channel_name)
        return self.storage.events().find(app_id, channel_id, EventFilter(
            start_time=start_time, until_time=until_time,
            entity_type=entity_type, entity_id=entity_id,
            event_names=event_names, target_entity_type=target_entity_type,
            target_entity_id=target_entity_id, limit=limit,
            reversed=reversed))

    # -- columnar bulk reads (PEventStore.find as RDD, PEvents.scala:38) ---
    def find_columnar(self, app_name: str,
                      channel_name: Optional[str] = None,
                      start_time: Optional[datetime] = None,
                      until_time: Optional[datetime] = None,
                      entity_type: Optional[str] = None,
                      entity_id: Optional[str] = None,
                      event_names: Optional[Sequence[str]] = None,
                      target_entity_type=ANY, target_entity_id=ANY,
                      float_props: Sequence[str] = ("rating",),
                      ordered: bool = True, with_props: bool = True,
                      host_sharded: bool = False):
        """The training-read path: the matching events as a
        :class:`~predictionio_tpu.data.columnar.ColumnarBatch` (dict-encoded
        numpy columns, vectorized filter pushdown) instead of an ``Event``
        stream — what ``PEventStore.find``'s RDD was to the reference.

        ``host_sharded=True`` returns only THIS process's contiguous
        slice under a multi-controller runtime (the RDD-partition-per-
        executor role; single-process it is the identity). The shard is
        PUSHED DOWN to the storage layer (``shard=(i, n)``): a remote
        backend transfers only this host's row range, a shared-mount
        sidecar touches only this host's mmap pages — the shard slices
        the unfiltered storage-order projection, with the filter
        applied within it (union over hosts == the unsharded read)."""
        app_id, channel_id = self.resolve(app_name, channel_name)
        filt = EventFilter(
            start_time=start_time, until_time=until_time,
            entity_type=entity_type, entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id)
        shard = None
        if host_sharded:
            import jax

            if jax.process_count() > 1:  # single-process: identity, free
                shard = (jax.process_index(), jax.process_count())
        return self.storage.events().find_columnar(
            app_id, channel_id, filt,
            float_props=float_props, ordered=ordered,
            with_props=with_props, shard=shard)

    # -- property aggregation (PEventStore.aggregateProperties, :99) -------
    def aggregate_properties(
            self, app_name: str, entity_type: str,
            channel_name: Optional[str] = None,
            start_time: Optional[datetime] = None,
            until_time: Optional[datetime] = None,
            required: Optional[Sequence[str]] = None) -> Dict[str, PropertyMap]:
        app_id, channel_id = self.resolve(app_name, channel_name)
        return self.storage.events().aggregate_properties(
            app_id, channel_id, entity_type=entity_type,
            start_time=start_time, until_time=until_time, required=required)

    # -- serving-time point lookups (LEventStore.findByEntity, :76) --------
    def find_by_entity(self, app_name: str, entity_type: str, entity_id: str,
                       channel_name: Optional[str] = None,
                       event_names: Optional[Sequence[str]] = None,
                       target_entity_type=ANY, target_entity_id=ANY,
                       start_time: Optional[datetime] = None,
                       until_time: Optional[datetime] = None,
                       limit: Optional[int] = None,
                       latest: bool = True,
                       timeout_ms: Optional[int] = None) -> List[Event]:
        """Blocking point read used by serving-time filters (e.g. the
        e-commerce template's seen/unavailable lookups). ``timeout_ms``
        bounds wall-clock like the reference's Duration argument
        (``LEventStore.scala:76-120``): the deadline is pushed into the
        backend scan (checked inside iteration) and also enforced while
        draining the iterator, so a heavy entity raises ``TimeoutError``
        at ~the deadline instead of after materializing everything."""
        deadline = (time.monotonic() + timeout_ms / 1000.0
                    if timeout_ms is not None else None)
        app_id, channel_id = self.resolve(app_name, channel_name)
        it = self.storage.events().find(app_id, channel_id, EventFilter(
            start_time=start_time, until_time=until_time,
            entity_type=entity_type, entity_id=entity_id,
            event_names=event_names, target_entity_type=target_entity_type,
            target_entity_id=target_entity_id, limit=limit,
            reversed=latest, deadline=deadline))
        drain = EventFilter(deadline=deadline)  # matches all; bounds drain
        return list(drain.apply(it))


#: Default facade bound to the process-wide storage — what templates import,
#: in the position of the reference's `PEventStore`/`LEventStore` objects.
event_store = EventStoreFacade()
