"""Core workflow: train and evaluation drivers with metadata bookkeeping.

Capability parity with ``workflow/CoreWorkflow.scala``: ``run_train``
mirrors ``runTrain`` (:45-102 — EngineInstance INIT→COMPLETED, model blob
insert at :76-81) and ``run_evaluation`` mirrors ``runEvaluation``
(:104-164 — EvaluationInstance INIT→EVALCOMPLETED with one-liner/HTML/JSON
results). The spark-submit process boundary (``tools/Runner.scala:185``)
does not exist here: training runs in-process against the mesh.
"""

from __future__ import annotations

import logging
from datetime import datetime, timezone
from typing import Any, List, Optional, Sequence

from ..controller.base import PersistentModelManifest
from ..controller.context import Context
from ..controller.engine import Engine
from ..controller.evaluation import (
    Evaluation,
    MetricEvaluator,
    MetricEvaluatorResult,
)
from ..controller.params import EngineParams, params_to_json
from ..data.storage.base import (
    EngineInstance,
    EvaluationInstance,
    Model,
    STATUS_COMPLETED,
    STATUS_EVALCOMPLETED,
    STATUS_INIT,
)
from . import persistence

log = logging.getLogger(__name__)


def _now() -> datetime:
    return datetime.now(timezone.utc)


def run_train(ctx: Context, engine: Engine, engine_params: EngineParams,
              engine_id: str = "default", engine_version: str = "1",
              engine_variant: str = "engine.json",
              engine_factory: str = "") -> str:
    """Train and persist: returns the COMPLETED engine-instance id.

    Multihost (``jax.process_count() > 1``): run_train is SPMD — every
    process executes the collective parts (training, the replicating
    ``to_host`` inside ``make_persistent_model``) — but process 0 is
    the SINGLE WRITER of engine-instance metadata and the model blob
    (the driver-program role of ``CoreWorkflow.scala:45-102``): the
    instance transitions INIT→COMPLETED exactly once however many
    hosts train."""
    import json as _json

    import jax

    from ..parallel.multihost import broadcast_str

    is_writer = jax.process_count() == 1 or jax.process_index() == 0
    storage = ctx.storage
    instances = storage.engine_instances()
    ep = engine_params
    instance_id = ""
    if is_writer:
        instance = EngineInstance(
            id="", status=STATUS_INIT, start_time=_now(),
            end_time=_now(),
            engine_id=engine_id, engine_version=engine_version,
            engine_variant=engine_variant, engine_factory=engine_factory,
            batch=ctx.batch,
            data_source_params=_json.dumps(
                {ep.datasource[0]: params_to_json(ep.datasource[1])}),
            preparator_params=_json.dumps(
                {ep.preparator[0]: params_to_json(ep.preparator[1])}),
            algorithms_params=_json.dumps(
                [{name: params_to_json(p)} for name, p in ep.algorithms]),
            serving_params=_json.dumps(
                {ep.serving[0]: params_to_json(ep.serving[1])}))
        instance_id = instances.insert(instance)
    instance_id = broadcast_str(instance_id)
    log.info("engine instance %s: training started", instance_id)

    # warm the device runtime (backend init + one tiny D2H) in the
    # background while the datasource reads from storage: the FIRST
    # device→host fetch of a process pays a ~10-15s tunnel/runtime
    # warmup (measured at ML-20M: the model fetch took 15.7s cold,
    # 1.4s after any prior fetch), and overlapping it with the
    # storage read makes it free
    import threading as _threading
    import time as _time

    def _warm_device():
        try:
            import numpy as _np

            import jax.numpy as _jnp

            _np.asarray(_jnp.ones((8, 128), _jnp.float32) * 2)
        except Exception:  # noqa: BLE001 — warmup must never kill a train
            pass

    warm = _threading.Thread(target=_warm_device, daemon=True,
                             name="device-warmup")
    warm.start()

    result = engine.train(ctx, engine_params)
    if ctx.stop_after_read or ctx.stop_after_prepare:
        log.info("workflow stopped early (stop-after flag); instance %s "
                 "left in INIT", instance_id)
        return instance_id

    t0 = _time.monotonic()
    algos = engine.make_algorithms(engine_params)
    stored: List[Any] = []
    for i, (algo, model) in enumerate(zip(algos, result.models)):
        # collective on every process (replicates sharded leaves)
        stored.append(algo.make_persistent_model(model, instance_id, i))
    if is_writer:
        storage.models().insert(
            Model(id=instance_id, models=persistence.dumps_models(stored)))
        done = instances.get(instance_id)
        assert done is not None
        instances.update(done.copy(status=STATUS_COMPLETED,
                                   end_time=_now()))
    ctx.stage_timings["persist_s"] = round(_time.monotonic() - t0, 2)
    # one parseable line: the northstar harness lifts this into its
    # artifact (VERDICT r4 next-round item 1's stage breakdown)
    log.info("engine instance %s: training completed; stages=%s",
             instance_id, _json.dumps(ctx.stage_timings))
    return instance_id


def load_models_for_deploy(ctx: Context, engine: Engine,
                           instance: EngineInstance,
                           engine_params: EngineParams) -> List[Any]:
    """Invert persisted blobs into live models (``CreateServer.scala:202-206``
    + ``Engine.prepareDeploy`` :198-267)."""
    blob = ctx.storage.models().get(instance.id)
    if blob is None:
        raise RuntimeError(f"no persisted models for instance {instance.id}")
    stored = persistence.loads_models(blob.models)
    return engine.prepare_deploy(ctx, engine_params, stored, instance.id)


def run_evaluation(ctx: Context, evaluation: Evaluation,
                   params_list: Sequence[EngineParams],
                   evaluation_class: str = "",
                   params_generator_class: str = "",
                   parallelism: int = 1) -> MetricEvaluatorResult:
    """Evaluate the search grid and record the winner.

    ``parallelism>1`` walks the grid with a thread pool (the reference's
    ``.par`` grid walk, ``MetricEvaluator.scala:224-231``); packing and
    fold prefixes are compute-once, so threads overlap host work with
    device dispatches."""
    storage = ctx.storage
    instances = storage.evaluation_instances()
    instance_id = instances.insert(EvaluationInstance(
        id="", status=STATUS_INIT, start_time=_now(), end_time=_now(),
        evaluation_class=evaluation_class,
        engine_params_generator_class=params_generator_class,
        batch=ctx.batch))
    log.info("evaluation instance %s: started (%d params sets)",
             instance_id, len(params_list))

    evaluator = MetricEvaluator(evaluation, parallelism=parallelism)
    result = evaluator.evaluate(ctx, params_list)

    done = instances.get(instance_id)
    assert done is not None
    instances.update(done.copy(
        status=STATUS_EVALCOMPLETED, end_time=_now(),
        evaluator_results=result.to_one_liner(),
        evaluator_results_html=result.to_html(),
        evaluator_results_json=result.to_json()))
    log.info("evaluation instance %s: %s", instance_id, result.to_one_liner())
    return result


def get_latest_completed(ctx: Context, engine_id: str = "default",
                         engine_version: str = "1",
                         engine_variant: str = "engine.json"
                         ) -> Optional[EngineInstance]:
    return ctx.storage.engine_instances().get_latest_completed(
        engine_id, engine_version, engine_variant)
