"""Batch prediction: JSON-lines queries in → JSON-lines results out.

Capability parity with the reference ``BatchPredict``
(``workflow/BatchPredict.scala:145-235``): each input line is a query;
output lines are self-descriptive ``{"query": …, "prediction": …}``
objects (:218-227). Where the reference map-partitions an RDD, here the
queries are batched through ``Algorithm.batch_predict`` so a vectorized
(vmapped/jitted) implementation sees device-sized batches instead of one
query per dispatch.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Iterable, Iterator, List, Optional

from ..controller.context import Context
from ..controller.engine import Engine
from ..controller.params import EngineParams
from ..data.storage.base import EngineInstance
from ..utils.jsonutil import from_jsonable, to_jsonable

_dispatch_pool = None


def _algo_pool():
    """Shared executor for concurrent per-algorithm dispatches (the
    reference's ``CreateServer.scala:507-510`` "TODO: Parallelize" —
    per-algorithm predictions are independent by the DASE contract).
    Module-level so multi-algorithm engines don't pay pool setup per
    coalesced batch."""
    global _dispatch_pool
    if _dispatch_pool is None:
        from concurrent.futures import ThreadPoolExecutor

        _dispatch_pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="algo-batch-dispatch")
    return _dispatch_pool


def supplement_batch(serving: Any, queries: List[Any], out: List[Any],
                     timings: Optional[Dict[str, float]] = None
                     ) -> tuple:
    """Supplement each query (the assemble-stage host work). Returns
    ``(supplemented, live)``; per-query supplement failures land as the
    raised exception in that query's ``out`` slot. With more than one
    query the supplements run CONCURRENTLY on the shared dispatch pool:
    for templates whose supplement reads the event store (seen/
    constraint lookups), a serial loop made a 128-query batch pay 128
    sequential storage round trips before the device saw anything.
    Futures are drained in query order, so result order and per-query
    error slots are exactly the serial loop's."""
    supplemented: List[Any] = []
    live: List[int] = []
    t0 = time.monotonic()
    if len(queries) > 1:
        pool = _algo_pool()
        futures = [pool.submit(serving.supplement, q) for q in queries]
        for i, f in enumerate(futures):
            try:
                supplemented.append(f.result())
                live.append(i)
            except Exception as e:  # noqa: BLE001 — isolate per query
                out[i] = e
    else:
        for i, q in enumerate(queries):
            try:
                supplemented.append(serving.supplement(q))
                live.append(i)
            except Exception as e:  # noqa: BLE001 — isolate per query
                out[i] = e
    if timings is not None:
        timings["supplement"] = (timings.get("supplement", 0.0)
                                 + (time.monotonic() - t0))
    return supplemented, live


def dispatch_batch(algorithms: List[Any], models: List[Any],
                   supplemented: List[Any],
                   timings: Optional[Dict[str, float]] = None
                   ) -> List[Any]:
    """Per-algorithm device DISPATCH without readback (ISSUE 9):
    returns one no-arg resolver per algorithm; calling it blocks until
    that algorithm's predictions are host-real. Algorithms exposing
    ``batch_predict_async`` (the dispatch/readback split — e.g. ALS)
    enqueue here and block only in their resolver, which is what lets
    the serving pipeline launch batch k+1 before batch k's results
    exist. Algorithms without the hook run their full (blocking)
    ``batch_predict`` on the shared pool — the resolver blocks on the
    future — preserving the concurrent multi-algorithm dispatch and
    still overlapping host stages of OTHER batches.

    A dispatch-time failure raises out of this call (the caller fills
    every live slot — one dispatch, whole batch); resolver-time
    failures raise out of the resolver the same way."""
    t0 = time.monotonic()
    try:
        resolvers: List[Any] = []
        for a, m in zip(algorithms, models):
            async_fn = getattr(a, "batch_predict_async", None)
            if async_fn is not None:
                resolvers.append(async_fn(m, supplemented))
            else:
                resolvers.append(_algo_pool().submit(
                    a.batch_predict, m, supplemented).result)
        return resolvers
    finally:
        if timings is not None:
            timings["dispatch"] = (timings.get("dispatch", 0.0)
                                   + (time.monotonic() - t0))


class PendingBatch:
    """An in-flight coalesced batch: device dispatches enqueued, host
    results not yet read back. Built by :func:`dispatch_serve_batch`
    (or assembled from parts by the engine server's staged pipeline);
    :meth:`resolve` blocks on the device arrays and finishes the
    per-query serving — the readback stage's work."""

    __slots__ = ("queries", "serving", "out", "live", "resolvers")

    def __init__(self, queries: List[Any], serving: Any, out: List[Any],
                 live: List[int], resolvers: List[Any]):
        self.queries = queries
        self.serving = serving
        self.out = out
        self.live = live
        self.resolvers = resolvers

    def resolve(self, timings: Optional[Dict[str, float]] = None
                ) -> List[Any]:
        """Block on the device results (``device_wait``), then serve
        per query (``serve``). Same error contract as the serial path:
        a per-algorithm readback failure fills every live slot; a
        per-query serve failure fills only its own."""
        out, live = self.out, self.live
        if not live:
            return out
        t1 = time.monotonic()
        try:
            per_algo = [r() for r in self.resolvers]
        except Exception as e:  # noqa: BLE001 — one dispatch, whole batch
            for i in live:
                out[i] = e
            return out
        finally:
            t2 = time.monotonic()
            if timings is not None:
                timings["device_wait"] = (timings.get("device_wait", 0.0)
                                          + (t2 - t1))
        for row, i in enumerate(live):
            try:
                # serve sees the original query (CreateServer.scala:511)
                out[i] = self.serving.serve(
                    self.queries[i], [preds[row] for preds in per_algo])
            except Exception as e:  # noqa: BLE001
                out[i] = e
        if timings is not None:
            timings["serve"] = (timings.get("serve", 0.0)
                                + (time.monotonic() - t2))
        return out


def dispatch_serve_batch(algorithms: List[Any], models: List[Any],
                         serving: Any, queries: List[Any],
                         timings: Optional[Dict[str, float]] = None
                         ) -> PendingBatch:
    """Supplement + per-algorithm device dispatch, WITHOUT blocking on
    results: returns a :class:`PendingBatch` whose ``resolve()`` does
    the readback and per-query serving. The serving pipeline's dispatch
    stage uses this to keep the device enqueued batch after batch while
    earlier batches' results are still in flight (ISSUE 9)."""
    out: List[Any] = [None] * len(queries)
    supplemented, live = supplement_batch(serving, queries, out,
                                          timings=timings)
    resolvers: List[Any] = []
    if live:
        try:
            resolvers = dispatch_batch(algorithms, models, supplemented,
                                       timings=timings)
        except Exception as e:  # noqa: BLE001 — one dispatch, whole batch
            for i in live:
                out[i] = e
            live = []
    return PendingBatch(queries, serving, out, live, resolvers)


def predict_serve_batch(algorithms: List[Any], models: List[Any],
                        serving: Any, queries: List[Any],
                        timings: Optional[Dict[str, float]] = None
                        ) -> List[Any]:
    """The batched serving pipeline shared by the engine server's
    micro-batcher and the batch-predict job: supplement each query, ONE
    ``batch_predict`` device dispatch per algorithm, then serve per
    query. Per-query failures (supplement/serve) come back as the raised
    exception in that query's slot; a ``batch_predict`` failure fills
    every live slot (it is one dispatch). When ``timings`` is given, the
    wall time of each internal phase is accumulated into it under
    ``supplement``/``dispatch``/``device_wait``/``serve`` (the engine
    server's per-phase telemetry reads these; ``dispatch`` is the pure
    device ENQUEUE since ISSUE 9, ``device_wait`` the block on its
    results). Realized as dispatch + immediate resolve so the serial
    and staged paths can never diverge."""
    return dispatch_serve_batch(algorithms, models, serving, queries,
                                timings=timings).resolve(timings=timings)


def batch_predict_lines(engine: Engine,
                        engine_params: EngineParams, models: List[Any],
                        query_lines: Iterable[str],
                        batch_size: int = 1024,
                        ctx: Optional[Context] = None) -> Iterator[str]:
    """Yield one JSON result line per non-empty input query line."""
    algorithms = engine.make_algorithms(engine_params)
    if ctx is not None:
        for algo in algorithms:
            algo.bind_serving(ctx)
    # same placement fix as the engine server's bind: device-resident
    # factors once, not a host re-transfer per flushed batch
    models = [a.prepare_serving_model(m, batch_size)
              for a, m in zip(algorithms, models)]
    serving = engine.make_serving(engine_params)
    query_cls = algorithms[0].query_class

    def flush(raw_batch: List[Any]) -> Iterator[str]:
        queries = [from_jsonable(query_cls, q) for q in raw_batch]
        results = predict_serve_batch(algorithms, models, serving, queries)
        for i, prediction in enumerate(results):
            if isinstance(prediction, Exception):
                raise prediction  # a batch job fails loudly
            yield json.dumps({"query": to_jsonable(raw_batch[i]),
                              "prediction": to_jsonable(prediction)})

    raw_batch: List[Any] = []
    for line in query_lines:
        line = line.strip()
        if not line:
            continue
        raw_batch.append(json.loads(line))
        if len(raw_batch) >= batch_size:
            yield from flush(raw_batch)
            raw_batch = []
    if raw_batch:
        yield from flush(raw_batch)


def run_batch_predict(ctx: Context, engine: Engine,
                      engine_params: EngineParams,
                      input_path: str, output_path: str,
                      engine_id: str = "default", engine_version: str = "1",
                      engine_variant: str = "engine.json",
                      instance: Optional[EngineInstance] = None,
                      batch_size: int = 1024) -> int:
    """The ``pio batchpredict`` flow: load the latest COMPLETED instance's
    models, stream the input file, write the output file. Returns the
    number of predictions written."""
    from . import core as wf

    if instance is None:
        instance = ctx.storage.engine_instances().get_latest_completed(
            engine_id, engine_version, engine_variant)
        if instance is None:
            raise RuntimeError("No COMPLETED engine instance; train first.")
    models = wf.load_models_for_deploy(ctx, engine, instance, engine_params)
    n = 0
    with open(input_path, "r", encoding="utf-8") as fin, \
            open(output_path, "w", encoding="utf-8") as fout:
        for line in batch_predict_lines(engine, engine_params, models,
                                        fin, batch_size=batch_size,
                                        ctx=ctx):
            fout.write(line + "\n")
            n += 1
    return n
